"""crashsan matrix — every injectable crash point, every mode, recovered.

The runtime half of the r21 durability work: graftlint v7 proves every
durable write ROUTES through ``common/durable.py``; this driver proves the
routed writes RECOVER.  Three scenarios — the master journal, the pod
reattach registry, the checkpoint manifest — each run once under
``crashsan.record()`` to enumerate their durable-op crossings, then re-run
in a fresh directory for every (op, crash mode) pair with
``crashsan.crash_at`` armed.  The crossing produces ON DISK the exact
state a real process death at that point leaves (torn final append, temp
complete but rename never landed, rename-before-fsync tear) and the
scenario's REAL recovery reader (``journal.read_journal``,
``PodManager.scan_registry``, ``checkpoint.read_manifest``) then runs
against it.  Each outcome must land in the scenario's documented contract
class (docs/robustness.md "Durability contracts"):

- ``exact-prefix``       append crashes: replay returns exactly the
                         records of every COMPLETED op; the torn tail
                         (never acknowledged to anyone) is dropped.
- ``previous-version``   publish crashes before the rename landed: the
                         reader sees the previous complete version.
- ``watermark-fallback``  the journal is absent or has no usable base:
                         ``JournalError`` — the master falls back to the
                         coarse watermark loudly (at-least-once).
- ``fallback-empty``     registry/manifest absent or torn by a simulated
                         NON-compliant writer (``published_torn``): the
                         tolerant reader reports "nothing published".

Anything else — records that are not a prefix, an unexpected exception,
silent acceptance of mid-file garbage — is an UNRECOVERED crash point and
fails the row.  ``tools/bench_regress.py`` gates the summary's
``unrecovered`` count at zero via the LINT artifact merge
(tools/graftlint.py --artifact picks up artifacts/crashsan_matrix.json).

Usage:
    python tools/crashsan_matrix.py            # print summary, exit 1 on
                                               # any unrecovered point
    python tools/crashsan_matrix.py --artifact # also stamp the artifact

tests/test_crashsan.py drives the same scenario functions in-process, so
the committed artifact and the tier-1 gate exercise one definition.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Callable, Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# The sanitizer must be armed before any scenario runs (crash_at refuses
# to arm otherwise — a sweep that never crashes proves nothing).
os.environ.setdefault("GRAFT_CRASHSAN", "1")

ARTIFACT_NAME = "crashsan_matrix.json"

#: pids beyond any live process (default pid_max) — the registry scan's
#: liveness probe must classify them dead deterministically.
_DEAD_PID_BASE = 4_194_304 + 7


# -- journal scenario ------------------------------------------------------

def _journal_events() -> List[Tuple[str, dict]]:
    """The workload script: (durable-op kind, logical record).  Rotation
    publishes a fresh base; appends extend the WAL.  Op 3 is the r18
    regression's membership record — the crash-at-rotation rows prove it
    can no longer land in NEITHER file."""
    base1 = {"kind": "base", "dispatcher": {"doing": 0, "done": []}}
    base2 = {"kind": "base", "dispatcher": {"doing": 0, "done": [1, 2]}}
    return [
        ("publish", base1),
        ("append", {"kind": "handout", "worker": "w0", "tasks": [{"id": 1}]}),
        ("append", {"kind": "report", "task_id": 1, "success": True,
                    "worker": "w0", "requeue": False}),
        ("append", {"kind": "membership", "version": 7}),
        ("publish", base2),
        ("append", {"kind": "handout", "worker": "w1", "tasks": [{"id": 3}]}),
        ("append", {"kind": "stop"}),
    ]


def journal_expected(completed: int) -> List[dict]:
    """The record list read_journal must see after ``completed`` ops
    landed fully: the latest completed rotation's base plus every append
    after it."""
    events = _journal_events()[:completed]
    out: List[dict] = []
    for kind, rec in events:
        if kind == "publish":
            out = [dict(rec, kind="base")]
        else:
            out.append(rec)
    return out


def run_journal(directory: str, crash: Optional[Tuple[int, str]] = None):
    """Run the journal workload, optionally crashing at op ``crash[0]``
    with mode ``crash[1]``; returns the recovery view ``(records, torn)``
    or the string ``"watermark-fallback"`` when the journal is unusable
    (absent / no base) — the master's documented fallback."""
    from elasticdl_tpu.common import crashsan
    from elasticdl_tpu.master import journal as journal_mod

    path = os.path.join(directory, journal_mod.JOURNAL_FILENAME)
    j = journal_mod.MasterJournal(path)
    try:
        if crash is not None:
            crashsan.arm(crash[0], crash[1])
        try:
            for kind, rec in _journal_events():
                if kind == "publish":
                    j.rotate(rec)
                else:
                    j.record(rec)
        except crashsan.CrashPoint:
            pass  # the simulated death; recovery runs below
        else:
            if crash is not None:
                raise AssertionError(
                    f"armed crash {crash} never fired in the journal "
                    "workload"
                )
    finally:
        if crash is not None:
            crashsan.disarm()
        j.close()
    if not os.path.exists(path):
        return "watermark-fallback"
    try:
        base, events, torn = journal_mod.read_journal(path)
    except journal_mod.JournalError:
        return "watermark-fallback"
    return [base] + events, torn


# -- registry scenario -----------------------------------------------------

def _registry_versions() -> List[dict]:
    """Three successive registry publishes, i+1 slots each — distinct
    sizes so which VERSION a recovery scan sees is unambiguous."""
    out = []
    for v in range(1, 4):
        out.append({
            "slots": {
                str(s): {
                    "name": f"w{s}", "pid": _DEAD_PID_BASE + s,
                    "relaunches": 0, "gen": v, "cmdline": None,
                }
                for s in range(v)
            }
        })
    return out


def run_registry(directory: str, crash: Optional[Tuple[int, str]] = None):
    """Publish three registry generations through the durable shape the
    pod manager uses, optionally crashing; recovery is the REAL
    ``PodManager.scan_registry``.  Returns its dict."""
    from elasticdl_tpu.common import crashsan, durable
    from elasticdl_tpu.master.pod_manager import PodManager

    path = os.path.join(directory, PodManager.REGISTRY_FILENAME)
    if crash is not None:
        crashsan.arm(crash[0], crash[1])
    try:
        for payload in _registry_versions():
            durable.atomic_publish_json(path, payload, sort_keys=True)
    except crashsan.CrashPoint:
        pass
    else:
        if crash is not None:
            raise AssertionError(
                f"armed crash {crash} never fired in the registry workload"
            )
    finally:
        if crash is not None:
            crashsan.disarm()
    return PodManager.scan_registry(path)


# -- manifest scenario -----------------------------------------------------

def run_manifest(directory: str, crash: Optional[Tuple[int, str]] = None):
    """Publish checkpoint manifests for steps 100 then 200, optionally
    crashing; recovery is the REAL ``checkpoint.read_manifest``.  Returns
    its dict (or None)."""
    from elasticdl_tpu.common import checkpoint, crashsan

    if crash is not None:
        crashsan.arm(crash[0], crash[1])
    try:
        for step in (100, 200):
            checkpoint.publish_manifest(directory, step, code_rev="matrix")
    except crashsan.CrashPoint:
        pass
    else:
        if crash is not None:
            raise AssertionError(
                f"armed crash {crash} never fired in the manifest workload"
            )
    finally:
        if crash is not None:
            crashsan.disarm()
    return checkpoint.read_manifest(directory)


# -- sweep + contract classification ---------------------------------------

def _enumerate_ops(scenario: Callable) -> List[dict]:
    from elasticdl_tpu.common import crashsan

    with tempfile.TemporaryDirectory() as d:
        with crashsan.record() as ops:
            scenario(d)
    return list(ops)


def _judge_journal(op_index: int, kind: str, mode: str, result) -> Tuple[bool, str]:
    if result == "watermark-fallback":
        # Legal only when no completed rotation's base can be on disk:
        # crashes at/around the FIRST publish, or a published_torn tear of
        # a later rotation (the non-compliant-writer mode tears the base).
        legal = op_index == 0 or (kind == "publish" and mode == "published_torn")
        return legal, "watermark-fallback"
    records, torn = result
    if records == journal_expected(op_index):
        if kind == "publish" and op_index > 0:
            return True, "previous-version"
        return True, "exact-prefix"
    return False, f"unexpected records: {json.dumps(records)[:200]}"


def _judge_registry(op_index: int, kind: str, mode: str, scan) -> Tuple[bool, str]:
    recorded = scan.get("recorded")
    if scan.get("alive"):
        return False, f"dead pids scanned alive: {scan}"
    if recorded == op_index and op_index > 0:
        return True, "previous-version"
    if recorded == 0:
        legal = op_index == 0 or mode == "published_torn"
        return legal, "fallback-empty"
    return False, f"unexpected scan: {scan}"


def _judge_manifest(op_index: int, kind: str, mode: str, m) -> Tuple[bool, str]:
    steps = (100, 200)
    if m is None:
        legal = op_index == 0 or mode == "published_torn"
        return legal, "fallback-empty"
    if isinstance(m, dict) and m.get("step") == steps[op_index - 1]:
        return True, "previous-version"
    return False, f"unexpected manifest: {m}"


SCENARIOS = (
    ("journal", run_journal, _judge_journal),
    ("registry", run_registry, _judge_registry),
    ("manifest", run_manifest, _judge_manifest),
)


def run_matrix() -> dict:
    """The full sweep: every scenario x every durable op x every crash
    mode its kind admits.  Returns ``{"rows": [...], "summary": {...}}``."""
    from elasticdl_tpu.common import crashsan

    rows: List[dict] = []
    crash_points = 0
    for name, scenario, judge in SCENARIOS:
        ops = _enumerate_ops(scenario)
        crash_points += len(ops)
        for op in ops:
            modes = (
                crashsan.APPEND_MODES if op["kind"] == "append"
                else crashsan.PUBLISH_MODES
            )
            for mode in modes:
                with tempfile.TemporaryDirectory() as d:
                    result = scenario(d, crash=(op["index"], mode))
                ok, contract = judge(op["index"], op["kind"], mode, result)
                rows.append({
                    "scenario": name,
                    "op": op["index"],
                    "kind": op["kind"],
                    "file": op["file"],
                    "mode": mode,
                    "recovered": bool(ok),
                    "contract": contract,
                })
    by_contract: Dict[str, int] = {}
    for r in rows:
        if r["recovered"]:
            by_contract[r["contract"]] = by_contract.get(r["contract"], 0) + 1
    summary = {
        "crash_points": crash_points,
        "injected": len(rows),
        "recovered": sum(1 for r in rows if r["recovered"]),
        "unrecovered": sum(1 for r in rows if not r["recovered"]),
        "by_contract": dict(sorted(by_contract.items())),
        "by_scenario": {
            name: sum(1 for r in rows if r["scenario"] == name)
            for name, _s, _j in SCENARIOS
        },
    }
    return {"rows": rows, "summary": summary}


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    out = run_matrix()
    s = out["summary"]
    for r in out["rows"]:
        if not r["recovered"]:
            print(
                f"UNRECOVERED {r['scenario']} op={r['op']} "
                f"({r['kind']} {r['file']}) mode={r['mode']}: "
                f"{r['contract']}",
                file=sys.stderr,
            )
    print(json.dumps(s, indent=1, sort_keys=True))
    if "--artifact" in argv:
        from tools.artifact import code_rev, write_artifact

        write_artifact(
            {
                "metric": "crashsan_matrix",
                "summary": s,
                "rows": out["rows"],
                "code_rev": code_rev(),
            },
            ARTIFACT_NAME,
            env_var="CRASHSAN_MATRIX_OUT",
        )
    return 1 if s["unrecovered"] else 0


if __name__ == "__main__":
    sys.exit(main())
