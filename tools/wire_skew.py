"""wire_skew — the version-skew roundtrip proof (graftlint v8 / wiresan).

Runs a REAL gRPC job with a v1-masked worker against a current master:
the client arms wiresan's version mask (``GRAFT_WIRESAN_MASK`` semantics
via :func:`wiresan.set_mask`), so every outgoing request and incoming
response is stripped to exactly the fields a peer built at wire revision
1 would speak — no ``lease`` batching, no ``seq`` dedup ledger, no
``trace``/``gauge`` envelopes, no ``server_ts_us`` clock stamp.  The
additive-compat stance ("optional field, no PROTOCOL_VERSION bump",
r9/r12/r14/r18) is only real if that worker still completes the job with
ZERO wire violations and ZERO double-trains; this tool proves it and
stamps the verdict into ``artifacts/wire_skew.json``, which
``tools/graftlint.py --artifact`` merges into the LINT artifact (env
``WIRE_SKEW`` overrides the read path there, ``WIRE_SKEW_OUT`` the write
path here) — the same static-tool/runtime-dump split as the jitsan stats
and the crashsan matrix.

Usage:
    python tools/wire_skew.py [--shards N]

Exit 0 = the masked fleet completed clean; 1 = any wire violation,
undone task, double-train, or stale report.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# The mask refuses to arm unless the sanitizer is on (fail-loud stance);
# set BEFORE any rpc import so every hook in this process is live.
os.environ.setdefault("GRAFT_WIRESAN", "1")

#: The emulated peer's wire revision: the pre-r9 baseline — every field
#: added since (lease, requeue, seq, trace, gauge, phase_counts, ...) is
#: stripped both directions.
MASK_REV = 1


def run_skew(num_shards: int, log=print) -> dict:
    from elasticdl_tpu.common import wiresan
    from elasticdl_tpu.common.rpc import JsonRpcClient
    from elasticdl_tpu.data.reader import Shard
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    wiresan.reset()
    shards = [
        Shard(name=f"shard-{i}", start=i * 10, end=(i + 1) * 10)
        for i in range(num_shards)
    ]
    dispatcher = TaskDispatcher(shards)
    servicer = MasterServicer(dispatcher, rendezvous=RendezvousServer())
    server = MasterServer(servicer, port=0).start()
    verdict = {
        "mask_rev": MASK_REV,
        "shards": num_shards,
        "tasks_done": 0,
        "heartbeats": 0,
        "wire_violations": 0,
        "errors": [],
    }
    try:
        worker = JsonRpcClient(server.address)
        worker.wait_ready(10.0)
        # The v1 peer: the worker-side loop SENDS modern payloads (seq,
        # requeue, lease) and the mask strips them on the way out — the
        # proof must cover the stripping itself, not a hand-tailored old
        # payload.  Responses are masked too: a v1 worker never sees
        # tasks/entries batches or the server_ts_us stamp.
        wiresan.set_mask(MASK_REV)
        try:
            worker.call("RegisterWorker", {
                "worker_id": "w0", "proto": 2, "incarnation": "inc-1",
                "held_tasks": [],
            }, timeout_s=10.0)
            beat = worker.call(
                "Heartbeat", {"worker_id": "w0"}, timeout_s=10.0
            )
            verdict["heartbeats"] += 1
            if "server_ts_us" in beat:
                verdict["errors"].append(
                    "response mask leaked server_ts_us (since r12) to the "
                    "v1 peer"
                )
            seq = 0
            while True:
                resp = worker.call(
                    "GetTask", {"worker_id": "w0", "lease": 4},
                    timeout_s=10.0,
                )
                if "tasks" in resp:
                    verdict["errors"].append(
                        "response mask leaked the r9 'tasks' lease batch "
                        "to the v1 peer"
                    )
                task = resp.get("task")
                if task is None:
                    if resp["finished"]:
                        break
                    verdict["errors"].append(
                        "no task and not finished — the masked loop "
                        "would spin"
                    )
                    break
                seq += 1
                ack = worker.call("ReportTaskResult", {
                    "worker_id": "w0",
                    "task_id": int(task["task_id"]),
                    "success": True,
                    "task_type": str(task.get("type", "training")),
                    "seq": seq,
                    "requeue": False,
                }, timeout_s=10.0)
                if not ack.get("accepted"):
                    verdict["errors"].append(
                        f"report for task {task['task_id']} not accepted"
                    )
                verdict["tasks_done"] += 1
        finally:
            wiresan.set_mask(None)
        # The unmasked admin view settles the double-train question: the
        # masked worker sent NO seq ledger (stripped), so every report
        # had to be applied exactly once on its own merits.
        admin = JsonRpcClient(server.address)
        admin.wait_ready(10.0)
        status = admin.call("JobStatus", {}, timeout_s=10.0)
        verdict["job_status"] = {
            k: status[k]
            for k in ("todo", "doing", "done", "abandoned",
                      "duplicate_done", "stale_reports", "finished")
        }
        if status["done"] != num_shards:
            verdict["errors"].append(
                f"done={status['done']} != shards={num_shards}"
            )
        if status["duplicate_done"]:
            verdict["errors"].append(
                f"double-train: duplicate_done={status['duplicate_done']}"
            )
        if status["stale_reports"]:
            verdict["errors"].append(
                f"stale_reports={status['stale_reports']}"
            )
        if not status["finished"]:
            verdict["errors"].append("job not finished")
    except wiresan.WireSanViolation as e:
        verdict["errors"].append(f"wire violation: {e}")
    finally:
        server.stop(grace=0)
    stats = wiresan.stats()
    verdict["wiresan"] = stats
    verdict["wire_violations"] = stats["violations"]
    verdict["ok"] = not verdict["errors"] and not stats["violations"]
    log(
        f"wire_skew: mask_rev={MASK_REV} tasks_done={verdict['tasks_done']}"
        f"/{num_shards} violations={stats['violations']} "
        f"ok={verdict['ok']}"
    )
    for err in verdict["errors"]:
        log(f"wire_skew: FAIL {err}")
    return verdict


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="wire_skew", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--shards", type=int, default=8,
        help="training shards the masked worker must complete (default 8)",
    )
    parser.add_argument(
        "--out", default=None, metavar="PATH",
        help="artifact path (default artifacts/wire_skew.json; "
        "env WIRE_SKEW_OUT overrides)",
    )
    args = parser.parse_args(argv)

    from tools.artifact import ArtifactRun

    run = ArtifactRun()  # capture code_rev before the run dirties anything
    verdict = run_skew(args.shards)
    run.write(verdict, "wire_skew.json", env_var="WIRE_SKEW_OUT",
              path=args.out)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
