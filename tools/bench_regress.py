"""bench_regress — the cross-rev perf-trajectory gate.

Every bench in this repo stamps a committed JSON under ``artifacts/``
(plus the occasional top-level ``BENCH_*.json`` from chip sessions), and
the filenames carry the revision that produced them (``gang_ingest_r06``
vs ``gang_ingest_r09``).  Until r14 nothing ever READ that trajectory:
the cross-rev story lived in docs/perf.md prose, and a rev that silently
regressed a previously-recorded number shipped clean.  This tool closes
the loop:

1. **Index**: scan ``artifacts/*.json`` + ``BENCH_*.json``, parse each
   file's family (name with the ``_rNN`` revision stripped) and revision,
   and extract the comparable metrics via the per-family extractor table
   below (direction-annotated: examples/sec is higher-better, p99 and
   recovery time are lower-better).
2. **Trajectory**: group extracted points into per-(family, metric,
   pipeline-config) series ordered by revision.  Two points compare only
   when their declared pipeline configs agree on every key BOTH declare
   (the bench.py record-guard stance: a sharded-optimizer run and a
   replicated one never compete; an artifact that predates a config key
   is unconstrained on it).  Same-rev duplicates (``bench_r05`` +
   ``bench_r05_latest``) keep the direction-best value — record
   semantics.
3. **Gate**: the newest point of each series is compared against the
   previous revision's; a direction-adjusted drop past ``--threshold``
   (default 10%, generous because the CPU-box benches carry co-tenant
   weather — see TRACE_r12's ab_note) is a REGRESSION: listed, stamped,
   and exit 1.

The whole trajectory is stamped into ``artifacts/TRAJECTORY.json`` (via
``ArtifactRun`` — code_rev captured at tool entry, since this tool's own
output dirties the tree it measures).  ``bench_all`` runs the gate after
the full battery and on ``--gauge-smoke``.

jax-free, stdlib + the artifact writer: runs in CI next to graftlint.

Usage:
  python tools/bench_regress.py [--threshold 10] [--repo PATH]
      [--no-artifact] [--json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HIGHER = "higher"  # bigger is better (throughput)
LOWER = "lower"    # smaller is better (latency, recovery time)

#: Filename -> (family, rev).  ``gang_ingest_r09.json`` -> ("gang_ingest",
#: 9); a ``_latest``/``_partial`` suffix folds into its base family;
#: rev-less names get rev 0 (a family with one rev simply never compares).
_REV_RE = re.compile(r"^(?P<family>.*?)_?r(?P<rev>\d+)(?P<suffix>_[a-z]+)?$",
                     re.IGNORECASE)


def parse_name(filename: str):
    stem = os.path.splitext(os.path.basename(filename))[0]
    m = _REV_RE.match(stem)
    if not m:
        return stem, 0
    return m.group("family"), int(m.group("rev"))


def _per_fleet(d: dict, field: str, direction: str) -> Dict[str, tuple]:
    """One metric per fleet entry, keyed by worker count + group mode so a
    1-worker number never compares against a 2-worker gang's."""
    out: Dict[str, tuple] = {}
    fleets = d.get("fleets")
    items = (
        fleets.items() if isinstance(fleets, dict)
        else enumerate(fleets or [])
    )
    for _, f in items:
        if not isinstance(f, dict) or field not in f:
            continue
        label = f.get("label") or (
            f"{f.get('workers', '?')}w" + ("_gang" if f.get("group_mode") else "")
        )
        out[f"{field}[{label}]"] = (f.get(field), direction)
    return out


def _per_point(d: dict, field: str, direction: str) -> Dict[str, tuple]:
    """serving_bench-style QPS points: one metric per offered-QPS row."""
    out: Dict[str, tuple] = {}
    for p in d.get("points") or []:
        if isinstance(p, dict) and field in p:
            out[f"{field}[qps{p.get('offered_qps')}]"] = (p[field], direction)
    return out


#: artifact "metric" field -> extractor(d) -> {name: (value, direction)}.
#: Declarative so a new bench joins the gate by adding one line; families
#: without an entry still index into the trajectory (for the record) but
#: carry no gated metrics.
EXTRACTORS = {
    "deepfm_criteo_e2e_examples_per_sec_per_chip": lambda d: {
        "e2e_examples_per_sec_per_chip": (d.get("value"), HIGHER),
        "device_step_examples_per_sec_per_chip": (
            d.get("device_step_examples_per_sec_per_chip"), HIGHER),
    },
    "gang_ingest_e2e_examples_per_sec": lambda d: _per_fleet(
        d, "examples_per_sec", HIGHER),
    "parallel_ingest_host_examples_per_sec": lambda d: {
        "best_examples_per_sec": (
            max((p.get("examples_per_sec", 0.0) for p in d.get("sweep") or []
                 if isinstance(p, dict)), default=None), HIGHER),
    },
    "serving_latency_vs_qps": lambda d: {
        **_per_point(d, "p50_ms", LOWER),
        **_per_point(d, "p99_ms", LOWER),
    },
    # r19 fleet ramp: the aggregate QPS the fleet held inside the online
    # SLO (up), the single-replica knee on the same substrate (up), the
    # online p99 at that best point (down), and two zero-baseline gates —
    # autoscaler flaps (direction reversals beyond the ramp's own
    # up-then-down shape) and replica relaunches (a crash, or a jitsan
    # over-budget retrace with GRAFT_JITSAN armed in every replica).
    "serving_fleet_ramp": lambda d: {
        "fleet_sla_qps": (
            (d.get("aggregate") or {}).get("best_sla_qps"), HIGHER),
        "online_p99_at_sla_ms": (
            (d.get("aggregate") or {}).get("p99_at_best_sla_ms"), LOWER),
        "single_replica_knee_qps": (
            (d.get("single_replica") or {}).get("knee_qps"), HIGHER),
        "autoscale_flaps": (
            (d.get("convergence") or {}).get("flaps"), LOWER),
        "replica_relaunches": (
            (d.get("convergence") or {}).get("relaunches"), LOWER),
    },
    "chaos_recovery_and_goodput_under_churn": lambda d: {
        **_per_fleet(d, "examples_per_sec", HIGHER),
        "kill_recovery_time_ms": (
            ((d.get("fleets") or {}).get("kill") or {})
            .get("recovery", {}).get("recovery_time_ms"), LOWER),
    },
    "ps_pull_push_latency": lambda d: {},  # indexed, not gated (shape varies)
    # r18 master crash survivability: the kill -> first-post-replay-task
    # recovery (down), its replay stage (down), and goodput under the
    # restart (up) — TRAJECTORY gates master restarts from r18 on.
    "master_kill_survivability": lambda d: {
        "recovery_ms": (
            ((d.get("fleets") or {}).get("masterkill") or {})
            .get("recovery", {}).get("recovery_ms"), LOWER),
        "journal_replay_ms": (
            ((d.get("fleets") or {}).get("masterkill") or {})
            .get("recovery", {}).get("replay_ms"), LOWER),
        "goodput_under_restart": (d.get("goodput_under_restart"), HIGHER),
    },
    # graftreduce (r15): step time per sweep point (down), and the
    # in-collective straggler degradation — the subgroup path's in-step
    # wait on phase clocks (the skip-to-recover twin of r13's
    # recovery_time, down).
    "collective_step_time_and_straggler_degradation": lambda d: {
        **{
            f"step_ms[dp{p.get('dp')}_{p.get('mode')}]": (p.get("step_ms"), LOWER)
            for p in d.get("sweep") or [] if isinstance(p, dict)
        },
        "subgroup_in_step_wait_ms": (
            (d.get("chaos") or {}).get("in_step_wait_ms", {})
            .get("subgroup"), LOWER),
    },
    # 2D hybrid mesh (r20): per-(dp,tp) step time and the analytic
    # inter-host bytes of the dp-only grad reduce (both down — the bytes
    # are the traffic the tp dimension exists to not move), plus two
    # zero-baseline gates: the 1D-vs-2D loss divergence (float32
    # reduction-order noise at a healthy rev; any climb is a sharded-math
    # bug) and the chaos reform's moment-mismatch count (bit-exact
    # re-partitioning or bust).
    "mesh2d_parity_step_and_bytes": lambda d: {
        **{
            f"step_ms[dp{p.get('dp')}xtp{p.get('tp')}]": (
                p.get("step_ms"), LOWER)
            for p in d.get("sweep") or [] if isinstance(p, dict)
        },
        **{
            f"interhost_bytes[dp{p.get('dp')}xtp{p.get('tp')}]": (
                p.get("interhost_bytes_resolved"), LOWER)
            for p in d.get("sweep") or [] if isinstance(p, dict)
        },
        "parity_max_abs_loss_diff": (
            (d.get("parity") or {}).get("max_abs_loss_diff"), LOWER),
        "chaos_moment_mismatches": (
            sum(
                1 for t in (d.get("chaos") or {}).get("transitions") or []
                if isinstance(t, dict) and not t.get("moments_bit_exact")
            ), LOWER),
    },
    "bench_all_configs": lambda d: {
        f"examples_per_sec_per_chip[{c.get('config')}]": (
            c.get("examples_per_sec_per_chip"), HIGHER)
        for c in d.get("configs") or [] if isinstance(c, dict)
    },
    # graftlint (r16): the trajectory gate covers LINT DEBT too — the
    # repo-wide findings count must only ever go down (it is 0 at every
    # shipped rev; any increase is a regression against a zero baseline).
    # v6 adds the jitsan compile contract: per declared jit site, the
    # measured lowerings past the declared budget (compiles minus
    # instances*budget, floored at 0).  The series is 0 at every healthy
    # rev, so any climb off the zero baseline — a production retrace the
    # declared variant budget does not cover — gates outright under the
    # zero-baseline LOWER rule below.
    "lint_findings": lambda d: {
        "findings": (d.get("findings"), LOWER),
        # v7 durability series, both zero at every healthy rev: the two
        # durable-discipline rules' repo-wide finding count, and the
        # crashsan matrix's unrecovered crash points (a crash state some
        # recovery reader mishandled).  Any climb off zero gates outright.
        "durability_findings": (
            (
                float((d.get("by_rule") or {}).get(
                    "durable-write-discipline", 0))
                + float((d.get("by_rule") or {}).get(
                    "recovery-read-discipline", 0))
            ) if isinstance(d.get("by_rule"), dict) else None,
            LOWER,
        ),
        "crashsan_unrecovered": (
            (((d.get("crashsan") or {}).get("summary")) or {}).get(
                "unrecovered"
            ),
            LOWER,
        ),
        # v8 wire series, both zero at every healthy rev: the two
        # wire-schema rules' repo-wide finding count, and the unknown
        # fields the skew run's wiresan counted (a non-zero count in a
        # SAME-VERSION run means a payload carries keys its schema never
        # declared — exactly the silent drop wire-discipline exists to
        # prevent).  Any climb off zero gates outright.
        "wire_findings": (
            (
                float((d.get("by_rule") or {}).get("wire-discipline", 0))
                + float((d.get("by_rule") or {}).get("wire-evolution", 0))
            ) if isinstance(d.get("by_rule"), dict) else None,
            LOWER,
        ),
        "wire_unknown_fields": (
            (d.get("wire") or {}).get("unknown_total"),
            LOWER,
        ),
        **{
            f"jit_over_budget[{fn}]": (
                max(
                    0.0,
                    float(rec.get("compiles", 0))
                    - float(rec.get("instances", 1))
                    * float(rec.get("budget", 1)),
                ),
                LOWER,
            )
            for fn, rec in sorted(
                (((d.get("jitsan") or {}).get("runtime")) or {}).items()
            )
            # underscore keys are dump metadata (_meta), not jit sites
            if isinstance(rec, dict) and not fn.startswith("_")
        },
    },
}

#: Family-name fallback extractors for artifacts that predate their
#: ``metric`` field — the LINT_r07..r15 files carry ``findings`` but no
#: metric key, and the lint-debt series is only a trajectory if the old
#: revs index too.
FAMILY_EXTRACTORS = {
    "LINT": EXTRACTORS["lint_findings"],
}

#: Keys that define "same pipeline config".  Two points compare only when
#: they agree on every key BOTH declare — the record-guard stance: a
#: missing key (an artifact predating it) is unconstrained, a conflicting
#: one splits the series.
CONFIG_KEYS = (
    "jax_platforms", "pipeline", "harness", "config", "model",
    "max_batch", "max_delay_ms", "clients", "workers", "unit",
)


def config_identity(d: dict) -> Dict[str, Any]:
    return {k: d[k] for k in CONFIG_KEYS if k in d}


def configs_comparable(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    return all(a[k] == b[k] for k in a.keys() & b.keys())


def index_artifacts(repo: str = _REPO_ROOT) -> List[dict]:
    """Every readable artifact as {file, family, rev, metric, config,
    metrics:{name: {value, direction}}} — the raw trajectory input."""
    paths = sorted(
        glob.glob(os.path.join(repo, "artifacts", "*.json"))
        + glob.glob(os.path.join(repo, "BENCH_*.json"))
    )
    entries: List[dict] = []
    for path in paths:
        base = os.path.basename(path)
        if base == "TRAJECTORY.json":
            continue  # this tool's own output must not index itself
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue  # unreadable artifacts are not this gate's business
        if not isinstance(d, dict):
            continue
        family, rev = parse_name(path)
        extractor = EXTRACTORS.get(d.get("metric")) or FAMILY_EXTRACTORS.get(
            family
        )
        metrics: Dict[str, dict] = {}
        if extractor is not None:
            for name, (value, direction) in extractor(d).items():
                if isinstance(value, (int, float)) and not isinstance(
                    value, bool
                ):
                    metrics[name] = {
                        "value": float(value), "direction": direction
                    }
        entries.append({
            "file": os.path.relpath(path, repo),
            "family": family,
            "rev": rev,
            "metric": d.get("metric"),
            "config": config_identity(d),
            "metrics": metrics,
        })
    return entries


def build_trajectory(entries: List[dict], threshold_pct: float) -> dict:
    """Series per (family, metric-name), best-per-rev, latest-vs-previous
    gated.  A config break between the two newest revs reports as
    ``config_changed`` (skipped), never a regression."""
    series: Dict[tuple, dict] = {}
    for e in entries:
        for name, m in e["metrics"].items():
            key = (e["family"], name)
            slot = series.setdefault(
                key, {"family": e["family"], "name": name,
                      "direction": m["direction"], "points": {}},
            )
            pts = slot["points"]
            prev = pts.get(e["rev"])
            better = (
                prev is None
                or (m["direction"] == HIGHER and m["value"] > prev["value"])
                or (m["direction"] == LOWER and m["value"] < prev["value"])
            )
            if better:
                pts[e["rev"]] = {
                    "value": m["value"], "file": e["file"],
                    "config": e["config"],
                }
    out_series: List[dict] = []
    regressions: List[dict] = []
    for slot in series.values():
        pts = slot.pop("points")
        revs = sorted(pts)
        slot["points"] = [
            {"rev": r, "value": pts[r]["value"], "file": pts[r]["file"]}
            for r in revs
        ]
        slot["status"] = "single-point"
        if len(revs) >= 2:
            latest, prev = pts[revs[-1]], pts[revs[-2]]
            if not configs_comparable(latest["config"], prev["config"]):
                slot["status"] = "config_changed"
            elif prev["value"] == 0:
                # A zero baseline has no meaningful ratio — EXCEPT for
                # lower-is-better counts (lint findings), where any climb
                # off zero is a regression outright (delta vs a floor of
                # 1 keeps the number finite and honest in scale).
                if slot["direction"] == LOWER and latest["value"] > 0:
                    slot["status"] = "REGRESSED"
                    slot["latest_delta_pct"] = round(
                        -latest["value"] * 100.0, 2
                    )
                    regressions.append({
                        "family": slot["family"], "name": slot["name"],
                        "delta_pct": slot["latest_delta_pct"],
                        "from": {"rev": revs[-2], **{
                            k: prev[k] for k in ("value", "file")}},
                        "to": {"rev": revs[-1], **{
                            k: latest[k] for k in ("value", "file")}},
                    })
                else:
                    slot["status"] = "zero-baseline"
            else:
                delta = (latest["value"] - prev["value"]) / abs(prev["value"])
                if slot["direction"] == LOWER:
                    delta = -delta
                slot["latest_delta_pct"] = round(delta * 100, 2)
                if delta * 100 < -threshold_pct:
                    slot["status"] = "REGRESSED"
                    regressions.append({
                        "family": slot["family"], "name": slot["name"],
                        "delta_pct": slot["latest_delta_pct"],
                        "from": {"rev": revs[-2], **{
                            k: prev[k] for k in ("value", "file")}},
                        "to": {"rev": revs[-1], **{
                            k: latest[k] for k in ("value", "file")}},
                    })
                else:
                    slot["status"] = "ok"
        out_series.append(slot)
    out_series.sort(key=lambda s: (s["family"], s["name"]))
    return {
        "metric": "cross_rev_perf_trajectory",
        "threshold_pct": threshold_pct,
        "artifacts_indexed": len(entries),
        "series": out_series,
        "compared": sum(
            1 for s in out_series if s["status"] in ("ok", "REGRESSED")),
        "regressions": regressions,
    }


def run_gate(repo: str = _REPO_ROOT, threshold_pct: float = 10.0,
             write: bool = True, log=None) -> dict:
    """Index + trajectory + (optionally) stamp; the bench_all entry."""
    from tools.artifact import ArtifactRun

    run = ArtifactRun(repo)  # code_rev BEFORE our own output dirties it
    say = log or (lambda m: print(m, file=sys.stderr, flush=True))
    trajectory = build_trajectory(index_artifacts(repo), threshold_pct)
    if write:
        run.write(
            trajectory, "TRAJECTORY.json", env_var="TRAJECTORY_OUT",
            path=os.path.join(repo, "artifacts", "TRAJECTORY.json"),
            log=say,
        )
    for s in trajectory["series"]:
        if s["status"] in ("ok", "REGRESSED"):
            say(f"  {s['family']}/{s['name']}: "
                f"{s['latest_delta_pct']:+.1f}% ({s['status']})")
    for r in trajectory["regressions"]:
        say(f"REGRESSION {r['family']}/{r['name']}: {r['delta_pct']:+.1f}% "
            f"({r['from']['file']} -> {r['to']['file']})")
    return trajectory


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="regression gate in percent (direction-adjusted)")
    ap.add_argument("--repo", default=_REPO_ROOT)
    ap.add_argument("--no-artifact", action="store_true",
                    help="gate only; do not rewrite TRAJECTORY.json")
    ap.add_argument("--json", action="store_true",
                    help="print the full trajectory JSON to stdout")
    args = ap.parse_args(argv)
    trajectory = run_gate(
        repo=args.repo, threshold_pct=args.threshold,
        write=not args.no_artifact,
    )
    if args.json:
        print(json.dumps(trajectory, indent=1))
    return 1 if trajectory["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
