"""straggler_report — per-rank gang-boundary wait skew and per-phase
p50/p99 from a merged grafttrace, stamped as a perf artifact.

ROADMAP item 3 (tail tolerance) needs stragglers as a RECORDED number
before anything can sacrifice or route around them: OptiReduce-style
timeout-bounded collectives and hot-spare splicing both key off per-rank
timing visibility.  This tool turns the merged cross-process trace
(tools/trace_dump.py) into exactly that:

- **gang-boundary wait skew**: every rank's ``gang_boundary`` spans
  (worker/_next_lease — the lockstep hand-out each rank crosses at the
  same seq) summed per rank; the max-min spread is the skew a straggler
  imposes on its peers.
- **per-phase p50/p99 (+ shared histogram buckets)**: every ``phase``-
  category span's duration distribution per process — prep_wait/dispatch/
  step_wait/... as distributions, not just the cumulative sums PhaseTimers
  already ships.

Modes:
    python tools/straggler_report.py --trace merged.json [--artifact [PATH]]
    python tools/straggler_report.py --raw dump.json     [--artifact [PATH]]
    python tools/straggler_report.py --run-gang 2        [--tasks 8]
        drive a REAL 2-worker lockstep gang (tools/multiworker_bench.py's
        ingest fleet) with --trace on, dump + merge it (the merged file is
        itself committed: artifacts/trace_gang_r12.json), run the ingest
        trace-overhead A/B, and stamp artifacts/TRACE_r12.json with skew +
        per-phase stats + measured overhead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

ARTIFACT_NAME = "TRACE_r12.json"
MERGED_TRACE_NAME = "trace_gang_r12.json"


def analyze(merged: dict) -> dict:
    """Per-process straggler analytics over a merged Chrome trace."""
    from tools.artifact import latency_stats

    events = merged.get("traceEvents") or []
    proc_names: Dict[int, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc_names[e.get("pid")] = e["args"]["name"]

    per_proc: Dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        proc = proc_names.get(e.get("pid"), str(e.get("pid")))
        d = per_proc.setdefault(
            proc,
            {"phases": {}, "gang_wait_ms": 0.0, "gang_crossings": 0,
             "first_us": None, "last_us": None},
        )
        ts = float(e.get("ts", 0.0))
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        d["first_us"] = ts if d["first_us"] is None else min(d["first_us"], ts)
        d["last_us"] = (
            ts + dur_ms * 1e3 if d["last_us"] is None
            else max(d["last_us"], ts + dur_ms * 1e3)
        )
        if e.get("cat") == "phase":
            d["phases"].setdefault(e["name"], []).append(dur_ms)
        elif e.get("cat") == "gang" and e.get("name") == "gang_boundary":
            d["gang_wait_ms"] += dur_ms
            d["gang_crossings"] += 1

    report: dict = {"processes": {}}
    for proc, d in sorted(per_proc.items()):
        phases = {
            name: {
                "count": len(durs),
                "total_ms": round(sum(durs), 2),
                # The shared bucket grid (tools/artifact.py): tail SHAPE
                # per phase, comparable across artifacts and rounds.
                **latency_stats(durs, buckets=True),
            }
            for name, durs in sorted(d["phases"].items())
        }
        entry: dict = {"phases": phases}
        if d["first_us"] is not None:
            entry["span_wall_s"] = round((d["last_us"] - d["first_us"]) / 1e6, 3)
        if d["gang_crossings"]:
            entry["gang_boundary_wait_ms"] = round(d["gang_wait_ms"], 2)
            entry["gang_crossings"] = d["gang_crossings"]
        report["processes"][proc] = entry

    # Per-rank gang wait = lockstep hand-out wait (gang_boundary spans)
    # plus the collective drain (step_wait phase): in this gang a fast
    # rank's surplus shows up BLOCKED IN THE COLLECTIVE on its slow peer,
    # so the drain is where peer-waiting actually lands — the boundary RPC
    # alone would understate it.
    waits = {}
    for p, e in report["processes"].items():
        if "gang_boundary_wait_ms" not in e:
            continue
        drain = e["phases"].get("step_wait", {}).get("total_ms", 0.0)
        waits[p] = {
            "boundary_ms": e["gang_boundary_wait_ms"],
            "collective_drain_ms": drain,
            "total_ms": round(e["gang_boundary_wait_ms"] + drain, 2),
        }
    if waits:
        totals = {p: w["total_ms"] for p, w in waits.items()}
        slowest = min(totals, key=totals.get)
        report["gang_boundary_skew"] = {
            "per_rank": waits,
            # The straggler is the rank that waits LEAST — its wall went
            # into its own work (prep/decode/compute) while every peer's
            # surplus wait absorbed the difference.
            "skew_ms": round(max(totals.values()) - min(totals.values()), 2),
            "straggler": slowest,
            "note": "per-rank gang_boundary span walls + step_wait "
                    "(collective drain) totals; the rank with the SMALLEST "
                    "total wait is the straggler its peers wait for",
        }
    return report


def _merged_from_args(args) -> dict:
    from tools.trace_dump import merge

    if args.trace:
        with open(args.trace) as f:
            return json.load(f)
    with open(args.raw) as f:
        return merge(json.load(f))


def run_gang(n_workers: int, n_tasks: int, log) -> dict:
    """Drive a real lockstep gang with tracing on; return the analysis plus
    bench figures, and leave the merged trace in artifacts/."""
    import tempfile

    # multiworker_bench pins this (jax-free) process and the worker env to
    # cpu at import; the gang runs exactly like the r9 ingest bench.
    from tools.multiworker_bench import _run_ingest_fleet
    from tools.trace_dump import merge

    tmp = tempfile.mkdtemp(prefix="straggler_")
    raw_path = os.path.join(tmp, "dump_raw.json")
    fleet = _run_ingest_fleet(
        n_workers, n_tasks, tmp, log, platform="cpu",
        trace_dump_raw=raw_path,
    )
    if not os.path.exists(raw_path):
        # The bench swallows dump-write failures by design (a failed dump
        # must not fail the BENCH) — but for THIS caller the dump IS the
        # product: fail with the real cause, not a bare FileNotFoundError
        # after a multi-minute run.
        raise RuntimeError(
            f"gang run finished but wrote no trace dump at {raw_path} — "
            "see the bench log above for the swallowed dump error"
        )
    with open(raw_path) as f:
        dump = json.load(f)
    merged = merge(dump)
    merged_path = os.path.join(_REPO_ROOT, "artifacts", MERGED_TRACE_NAME)
    os.makedirs(os.path.dirname(merged_path), exist_ok=True)
    with open(merged_path, "w") as f:
        json.dump(merged, f)
    log(f"merged Perfetto trace -> {merged_path} "
        f"({len(merged['traceEvents'])} events)")
    report = analyze(merged)
    report["gang"] = {
        "workers": fleet["workers"],
        "examples_per_sec": fleet["examples_per_sec"],
        "tasks_total": fleet["tasks_total"],
        "merged_trace": os.path.relpath(merged_path, _REPO_ROOT),
        "merged_events": len(merged["traceEvents"]),
    }
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="straggler_report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--trace", default="", help="merged Chrome-trace JSON")
    ap.add_argument("--raw", default="", help="raw DumpTrace response JSON")
    ap.add_argument(
        "--run-gang", type=int, default=0, metavar="N",
        help="drive an N-worker lockstep gang with tracing on (cpu "
        "harness), merge its trace, and analyze it",
    )
    ap.add_argument("--tasks", type=int, default=8, help="gang tasks")
    ap.add_argument(
        "--artifact", nargs="?", const="", default=None, metavar="PATH",
        help=f"stamp the report (+ the ingest trace-overhead A/B) as "
        f"artifacts/{ARTIFACT_NAME} (env override TRACE_OUT)",
    )
    args = ap.parse_args(argv)
    log = lambda m: print(f"[straggler] {m}", file=sys.stderr, flush=True)
    run = None
    if args.artifact is not None:
        # ArtifactRun captures code_rev at ENTRY, before run_gang rewrites
        # the committed trace artifacts (tools/artifact.py documents why a
        # stamp-time read would mark every --run-gang artifact "-dirty"
        # from its own outputs).
        from tools.artifact import ArtifactRun

        run = ArtifactRun()

    if bool(args.run_gang) + bool(args.trace) + bool(args.raw) != 1:
        print(
            "straggler_report: exactly one of --run-gang/--trace/--raw",
            file=sys.stderr,
        )
        return 2

    if args.run_gang:
        report = run_gang(args.run_gang, args.tasks, log)
    else:
        report = analyze(_merged_from_args(args))

    if args.artifact is not None:
        # The overhead A/B belongs in the SAME artifact as the skew
        # numbers: "stragglers are measurable AND measuring them is ~free"
        # is one claim, checkable from one file.
        from tools.ingest_bench import trace_overhead_ab

        overhead = trace_overhead_ab(log)
        run.write(
            {
                "metric": "gang_trace_straggler_report",
                **report,
                "trace_overhead_ingest_ab": overhead,
            },
            ARTIFACT_NAME,
            env_var="TRACE_OUT",
            path=args.artifact or None,
            log=log,
        )
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
