"""End-to-end DeepFM/Criteo training throughput — the WHOLE worker path.

Unlike bench.py's device-step phase (one pre-sharded synthetic batch reused
every step), this runs the real job stack on real files: recordio on disk ->
master task dispatch -> worker shard read (bulk C++ recordio read) -> criteo
decode (C++ codec) -> prefetch -> shard_batch -> jitted hybrid train step,
for every batch.  The number it reports is what a user's `elasticdl train`
job actually sustains per chip (SURVEY.md §3.1-3.3; the reference's
tf.data-fed worker loop is the parity target — VERDICT r3 Missing #1).

Measurement: per-task completion timestamps via a wrapping master proxy;
the first ``WARM_TASKS`` tasks (XLA compile + cache warmup) are excluded,
throughput = records in the remaining tasks / the time they took.

Standalone: ``python tools/bench_e2e.py`` prints the result dict.
bench.py imports ``run_e2e`` for the committed artifact.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MINIBATCH = 8192
MINIBATCHES_PER_TASK = 8  # the reference's num_minibatches_per_task default
RECORDS_PER_TASK = MINIBATCH * MINIBATCHES_PER_TASK
FILE_TASKS = 2          # tasks per epoch; the file holds this many
WARM_TASKS = 2          # excluded from the measurement (compile + warmup)
MEASURE_TASKS = 46      # ~3M examples measured

_CACHE_VERSION = 1  # bump when the synthetic generator's output changes


def _dataset(tmp_dir: str = "/tmp") -> str:
    """Synthetic criteo recordio, cached across runs (generation is a
    Python-loop one-time cost, ~30 us/record)."""
    from elasticdl_tpu.data.synthetic import synthetic_criteo

    n = RECORDS_PER_TASK * FILE_TASKS
    path = os.path.join(tmp_dir, f"edl_bench_criteo_v{_CACHE_VERSION}_{n}.rio")
    if not os.path.exists(path):
        from elasticdl_tpu.common import durable

        tmp = durable.tmp_path(path)
        synthetic_criteo(tmp, n, seed=11, container="recordio")
        durable.atomic_replace(tmp, path)
    return path


def _link_probe(log=lambda msg: None) -> dict:
    """Measure the host<->device link before the run: dispatch RTT and
    effective H2D bandwidth (put + forced arrival via a device reduce +
    scalar fetch).  On a tunneled/remote chip this link is the e2e bound —
    ~20-40 MB/s measured across sessions, bimodal with multi-second stalls
    — so the committed artifact must carry the link quality its throughput
    number was recorded under."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.common.jax_compat import jit_compiled

    d = jax.devices()[0]
    # graftlint: allow[jit-stability] one-shot link probe: the process runs this exactly once, and the probe's 2 lowerings (8B + MB buffers) are the measurement
    f = jit_compiled(
        lambda a: jnp.sum(a, dtype=jnp.int32),
        name="bench_e2e.link_probe", expected_variants=2,
    )
    tiny = np.zeros(8, np.uint8)
    int(f(jax.device_put(tiny, d)))  # warm the compile
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        int(f(jax.device_put(tiny, d)))
        rtts.append(time.perf_counter() - t0)
    mbs = 5.2
    bws = []
    for i in range(3):
        buf = np.random.default_rng(i).integers(
            0, 255, size=(int(mbs * 1e6),), dtype=np.uint8
        )
        t0 = time.perf_counter()
        int(f(jax.device_put(buf, d)))
        bws.append(mbs / (time.perf_counter() - t0))
    out = {
        "link_rtt_ms": round(sorted(rtts)[len(rtts) // 2] * 1e3, 1),
        "link_h2d_mbps": round(sorted(bws)[len(bws) // 2], 1),
    }
    log(f"link probe: RTT {out['link_rtt_ms']} ms, "
        f"H2D {out['link_h2d_mbps']} MB/s")
    return out


def run_e2e(log=lambda msg: None) -> dict:
    import jax

    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    path = _dataset()
    log(f"dataset ready: {path} ({os.path.getsize(path) >> 20} MiB)")
    link = _link_probe(log)

    total_tasks = WARM_TASKS + MEASURE_TASKS
    epochs = -(-total_tasks // FILE_TASKS)  # ceil; runs epochs*FILE_TASKS tasks
    total_tasks = epochs * FILE_TASKS
    config = JobConfig(
        model_def="deepfm.model_spec",
        model_params="buckets_per_feature=65536;embedding_dim=8;hidden=[400,400]",
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        training_data=path,
        minibatch_size=MINIBATCH,
        num_minibatches_per_task=MINIBATCHES_PER_TASK,
        num_epochs=epochs,
    )
    reader = create_data_reader(path)
    dispatcher = TaskDispatcher(
        reader.create_shards(RECORDS_PER_TASK), num_epochs=epochs
    )
    servicer = MasterServicer(dispatcher)
    spec = load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        buckets_per_feature=65536,
        embedding_dim=8,
        hidden=(400, 400),
    )

    reports = []

    class TimingProxy(DirectMasterProxy):
        def call(self, method, request):
            resp = super().call(method, request)
            if method == "ReportTaskResult":
                reports.append(time.perf_counter())
                if len(reports) % 16 == 0:
                    log(f"{len(reports)} tasks done")
            return resp

    worker = Worker(
        config,
        TimingProxy(servicer),
        reader,
        worker_id="bench-w0",
        spec=spec,
        devices=jax.devices(),
    )
    log(f"running {total_tasks} tasks x {RECORDS_PER_TASK} records "
        f"(epochs={epochs})")
    t_start = time.perf_counter()
    result = worker.run()
    t_total = time.perf_counter() - t_start

    if len(reports) <= WARM_TASKS:
        raise RuntimeError(
            f"only {len(reports)} tasks completed; nothing to measure"
        )
    measured = len(reports) - WARM_TASKS
    elapsed = reports[-1] - reports[WARM_TASKS - 1]
    examples = measured * RECORDS_PER_TASK
    n_chips = len(jax.devices())
    from elasticdl_tpu.data.ingest_pool import resolve_threads

    return {
        "e2e_examples_per_sec_per_chip": examples / elapsed / n_chips,
        "tasks_measured": measured,
        "examples_measured": examples,
        "elapsed_s": elapsed,
        "wall_total_s": t_total,
        "steps": result["step"],
        "warm_tasks_excluded": WARM_TASKS,
        **link,
        # Pipeline config (r9): e2e numbers are only comparable at equal
        # ingest/prep/lease shape, exactly like the link fields above —
        # bench.py's record guard enforces it.
        "ingest_threads": resolve_threads(config.ingest_threads),
        "prep_depth": config.prep_depth,
        "lease_batch": config.lease_batch,
        # Step-shape config (r11): the optimizer layout and donation knob
        # change what the jitted step computes/holds resident, so runs at
        # different settings are different experiments — same guard.
        "optimizer_sharding": config.optimizer_sharding,
        "donate_train_state": config.donate_train_state,
    }


if __name__ == "__main__":
    from elasticdl_tpu.common.platform import (
        apply_platform_env,
        enable_compile_cache,
    )

    apply_platform_env()
    enable_compile_cache()
    out = run_e2e(log=lambda m: print(f"[e2e] {m}", file=sys.stderr, flush=True))
    print(out)
