"""Multi-worker control-plane bound (VERDICT r4 Weak #6 / Next #7).

The reference's sustained-throughput story is many workers sharing one
master; tools/bench_e2e.py measures a single in-process worker.  This tool
bounds what the CONTROL PLANE (task dispatch, result reporting, rendezvous
heartbeats, the RPC server itself) costs per worker as real worker
processes are added — on the CPU harness, so the accelerator never gates.

Method: a deliberately task-bound job — tiny model, one minibatch per task,
hundreds of tasks — so wall-clock is dominated by GetTask/ReportTaskResult
round-trips, not math.  Run the same job at fleet sizes 1/2/4 real worker
subprocesses against one embedded RPC master; report aggregate and
per-worker task rates and the scaling efficiency vs the 1-worker figure.
If the master's hot loop (SURVEY §3.2) serializes, efficiency collapses as
workers are added; numbers near 1.0 bound the per-worker overhead at
(1/rate) per task.

Writes ONE JSON artifact (the number of record — docs/perf.md quotes the
file): ``artifacts/multiworker_r05.json`` by default.

Usage: python tools/multiworker_bench.py [--fleets 1,2,4] [--tasks 96]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# FORCE cpu (not setdefault): the image exports JAX_PLATFORMS=axon, so a
# default would aim this CPU-harness tool at the real (possibly hung) chip.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_fleet(n_workers: int, n_tasks: int, tmp: str, log) -> dict:
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    mb = 16
    path = os.path.join(tmp, "mw.rio")
    if not os.path.exists(path):
        generate("mnist", path, mb * n_tasks)
    shards = create_data_reader(path).create_shards(mb)

    dispatcher = TaskDispatcher(shards, num_epochs=1)
    rendezvous = RendezvousServer(heartbeat_timeout_s=30.0)
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    server = MasterServer(servicer, port=0).start()

    # Per-worker ReportTaskResult timestamps via a servicer wrapper thread?
    # Simpler: poll JobStatus; per-worker split comes from task ownership.
    config = JobConfig(
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=path,
        minibatch_size=mb,
        num_minibatches_per_task=1,
        num_epochs=1,
        master_addr=server.address,
        prefetch_depth=0,       # decode cost ~0; keep the loop RPC-bound
        fused_task_scan=False,  # per-step dispatch = max control-plane load
        checkpoint_steps=0,
    )
    env_base = dict(os.environ)
    env_base.update(config.to_env())
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env_base.pop("PALLAS_AXON_POOL_IPS", None)
    # Shared compile cache: the jitted step compiles once, every process
    # loads it — measurement starts after a warmup barrier anyway.
    env_base["JAX_COMPILATION_CACHE_DIR"] = os.path.join(tmp, "jax_cache")

    procs = []
    logs = []
    t0 = time.perf_counter()
    for i in range(n_workers):
        env = dict(env_base)
        env["ELASTICDL_WORKER_ID"] = f"mw-{n_workers}-{i}"
        lf = open(os.path.join(tmp, f"mw{n_workers}_{i}.log"), "w")
        logs.append(lf)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.worker.main"],
            env=env, stdout=lf, stderr=subprocess.STDOUT, cwd=_REPO_ROOT,
        ))
    # Warmup window: exclude process boot + compile from the rate by
    # timestamping from the FIRST completed task to the LAST.
    first_done = None
    deadline = time.time() + 600
    while time.time() < deadline:
        status = servicer.JobStatus({})
        if first_done is None and status["done"] > 0:
            first_done = (time.perf_counter(), status["done"])
        if status["finished"]:
            break
        time.sleep(0.05)
    t_end = time.perf_counter()
    status = servicer.JobStatus({})
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
    for lf in logs:
        lf.close()
    server.stop()
    if not status["finished"]:
        raise RuntimeError(
            f"fleet {n_workers}: job not finished ({status['done']} tasks)"
        )
    t_first, done_at_first = first_done
    measured_tasks = status["done"] - done_at_first
    elapsed = t_end - t_first
    if measured_tasks <= 0 or elapsed <= 0:
        # Job finished within the first-done poll window (tiny --tasks):
        # fall back to the boot-inclusive rate rather than reporting 0 and
        # poisoning the retention baseline (review r5).
        measured_tasks = status["done"]
        elapsed = t_end - t0
    rate = measured_tasks / elapsed
    out = {
        "workers": n_workers,
        "tasks_total": status["done"],
        "tasks_measured": measured_tasks,
        "elapsed_s": round(elapsed, 3),
        "tasks_per_sec": round(rate, 2),
        "tasks_per_sec_per_worker": round(rate / n_workers, 2),
        "wall_total_s": round(t_end - t0, 2),
    }
    log(f"fleet {n_workers}: {out}")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fleets", default="1,2,4")
    ap.add_argument("--tasks", type=int, default=96)
    ap.add_argument(
        "--out", default=os.path.join(_REPO_ROOT, "artifacts",
                                      "multiworker_r05.json")
    )
    args = ap.parse_args()
    import tempfile

    log = lambda m: print(f"[mw] {m}", file=sys.stderr, flush=True)
    tmp = tempfile.mkdtemp(prefix="mw_bench_")
    fleets = [int(x) for x in args.fleets.split(",")]
    results = [_run_fleet(n, args.tasks, tmp, log) for n in fleets]
    # On this 1-core host every worker shares the CPU, so per-worker rate
    # falls ~1/N by CONTENTION alone; the control-plane bound is how much
    # of the AGGREGATE rate survives as workers multiply — a serializing
    # master would drop it, a clean one holds it flat.
    base = results[0]["tasks_per_sec"]
    for r in results:
        r["aggregate_retention_vs_1w"] = round(r["tasks_per_sec"] / base, 3)
    worst = min(r["aggregate_retention_vs_1w"] for r in results)
    artifact = {
        "metric": "control_plane_task_rate",
        "unit": "tasks/sec",
        "harness": f"cpu ({os.cpu_count()} core host), 1 fake device per "
                   "worker, task-bound job (1 minibatch of 16 per task)",
        "fleets": results,
        "control_plane_overhead_bound_pct": round((1 - worst) * 100, 1),
        "note": "per-step dispatch + prefetch off: every task is pure "
                "GetTask/feed/step/ReportTaskResult; aggregate retention "
                "~1.0 = the master adds no per-worker serialization at "
                "this scale (per-worker division is meaningless under "
                "full CPU sharing)",
    }
    from tools.artifact import write_artifact

    write_artifact(artifact, "multiworker_r05.json", path=args.out, log=log)
    print(json.dumps(artifact["fleets"]), flush=True)


if __name__ == "__main__":
    main()
