"""Multi-worker control-plane bound (VERDICT r4 Weak #6 / Next #7).

The reference's sustained-throughput story is many workers sharing one
master; tools/bench_e2e.py measures a single in-process worker.  This tool
bounds what the CONTROL PLANE (task dispatch, result reporting, rendezvous
heartbeats, the RPC server itself) costs per worker as real worker
processes are added — on the CPU harness, so the accelerator never gates.

Method: a deliberately task-bound job — tiny model, one minibatch per task,
hundreds of tasks — so wall-clock is dominated by GetTask/ReportTaskResult
round-trips, not math.  Run the same job at fleet sizes 1/2/4 real worker
subprocesses against one embedded RPC master; report aggregate and
per-worker task rates and the scaling efficiency vs the 1-worker figure.
If the master's hot loop (SURVEY §3.2) serializes, efficiency collapses as
workers are added; numbers near 1.0 bound the per-worker overhead at
(1/rate) per task.

Two modes:

- ``--mode control`` (default): the r5 task-bound job above — the per-task
  RPC overhead bound.
- ``--mode ingest`` (r6): gang-mode INGEST e2e.  A lockstep gang of real
  worker processes (``multihost=True``, one jax.distributed world) trains
  criteo recordio through the full worker path — bulk C++ read, criteo
  decode, prefetch, fused scan, prep-ahead pipelining (group-eligible
  since r6) — and the number is examples/sec through the gang, with the
  workers' phase decomposition (common/metrics.py PhaseTimers) attached.
  The control mode deliberately starves the data path; this mode is the
  one that can see gang-mode ingest regressions at all.

Writes ONE JSON artifact per mode (the number of record — docs/perf.md
quotes the file): ``artifacts/multiworker_r05.json`` /
``artifacts/gang_ingest_r09.json`` by default.

Usage: python tools/multiworker_bench.py [--mode control|ingest]
           [--fleets 1,2,4] [--tasks 96] [--platform cpu|chip]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# FORCE cpu (not setdefault): the image exports JAX_PLATFORMS=axon, so a
# default would aim this CPU-harness tool at the real (possibly hung) chip.
# The pre-force value is kept so ``--platform chip`` can hand the REAL
# backend to worker subprocesses (the bench process itself never needs it:
# the master is jax-free).
_CHIP_JAX_PLATFORMS = os.environ.get("JAX_PLATFORMS", "")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_fleet(n_workers: int, n_tasks: int, tmp: str, log) -> dict:
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    mb = 16
    path = os.path.join(tmp, "mw.rio")
    if not os.path.exists(path):
        generate("mnist", path, mb * n_tasks)
    shards = create_data_reader(path).create_shards(mb)

    dispatcher = TaskDispatcher(shards, num_epochs=1)
    rendezvous = RendezvousServer(heartbeat_timeout_s=30.0)
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    server = MasterServer(servicer, port=0).start()

    # Per-worker ReportTaskResult timestamps via a servicer wrapper thread?
    # Simpler: poll JobStatus; per-worker split comes from task ownership.
    config = JobConfig(
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=path,
        minibatch_size=mb,
        num_minibatches_per_task=1,
        num_epochs=1,
        master_addr=server.address,
        prefetch_depth=0,       # decode cost ~0; keep the loop RPC-bound
        fused_task_scan=False,  # per-step dispatch = max control-plane load
        checkpoint_steps=0,
    )
    env_base = dict(os.environ)
    env_base.update(config.to_env())
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env_base.pop("PALLAS_AXON_POOL_IPS", None)
    # Shared compile cache: the jitted step compiles once, every process
    # loads it — measurement starts after a warmup barrier anyway.
    env_base["JAX_COMPILATION_CACHE_DIR"] = os.path.join(tmp, "jax_cache")

    procs = []
    logs = []
    t0 = time.perf_counter()
    for i in range(n_workers):
        env = dict(env_base)
        env["ELASTICDL_WORKER_ID"] = f"mw-{n_workers}-{i}"
        lf = open(os.path.join(tmp, f"mw{n_workers}_{i}.log"), "w")
        logs.append(lf)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.worker.main"],
            env=env, stdout=lf, stderr=subprocess.STDOUT, cwd=_REPO_ROOT,
        ))
    # Warmup window: exclude process boot + compile from the rate by
    # timestamping from the FIRST completed task to the LAST.
    first_done = None
    deadline = time.time() + 600
    while time.time() < deadline:
        status = servicer.JobStatus({})
        if first_done is None and status["done"] > 0:
            first_done = (time.perf_counter(), status["done"])
        if status["finished"]:
            break
        time.sleep(0.05)
    t_end = time.perf_counter()
    status = servicer.JobStatus({})
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
    for lf in logs:
        lf.close()
    server.stop()
    if not status["finished"]:
        raise RuntimeError(
            f"fleet {n_workers}: job not finished ({status['done']} tasks)"
        )
    t_first, done_at_first = first_done
    measured_tasks = status["done"] - done_at_first
    elapsed = t_end - t_first
    if measured_tasks <= 0 or elapsed <= 0:
        # Job finished within the first-done poll window (tiny --tasks):
        # fall back to the boot-inclusive rate rather than reporting 0 and
        # poisoning the retention baseline (review r5).
        measured_tasks = status["done"]
        elapsed = t_end - t0
    rate = measured_tasks / elapsed
    out = {
        "workers": n_workers,
        "tasks_total": status["done"],
        "tasks_measured": measured_tasks,
        "elapsed_s": round(elapsed, 3),
        "tasks_per_sec": round(rate, 2),
        "tasks_per_sec_per_worker": round(rate / n_workers, 2),
        "wall_total_s": round(t_end - t0, 2),
    }
    log(f"fleet {n_workers}: {out}")
    return out


# ---------------------------------------------------------------------------
# ingest mode: gang-mode ingest e2e (r6)
# ---------------------------------------------------------------------------

_INGEST_MB = 2048
_INGEST_MB_PER_TASK = 4
_INGEST_RECORDS_PER_TASK = _INGEST_MB * _INGEST_MB_PER_TASK


def _run_ingest_fleet(
    n_workers: int, n_tasks: int, tmp: str, log, platform: str,
    trace_dump_raw: str = "",
) -> dict:
    """One lockstep gang of ``n_workers`` REAL worker processes training
    criteo recordio end to end; returns examples/sec through the gang plus
    the workers' phase decomposition.

    ``trace_dump_raw``: enable grafttrace on every process (workers via the
    config bus, the embedded master in-process) and save the raw DumpTrace
    response there after the job finishes — the supply side of
    tools/straggler_report.py's gang analysis."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import synthetic_criteo
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.common.platform import free_port
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.worker import RESTART_EXIT_CODE

    path = os.path.join(tmp, "gang_criteo.rio")
    file_tasks = 4
    if not os.path.exists(path):
        synthetic_criteo(
            path, _INGEST_RECORDS_PER_TASK * file_tasks, seed=11,
            container="recordio",
        )
    reader = create_data_reader(path)
    shards = reader.create_shards(_INGEST_RECORDS_PER_TASK)
    epochs = -(-n_tasks // file_tasks)  # ceil

    dispatcher = TaskDispatcher(shards, num_epochs=epochs)
    rendezvous = RendezvousServer(heartbeat_timeout_s=60.0)
    # Symmetric gang formation: settle only once every member of the full
    # fleet has registered — an incumbent/joiner split would spend the
    # measurement window on membership restarts instead of ingest.
    rendezvous.set_expected(n_workers)
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    server = MasterServer(servicer, port=0).start()

    # The gang-ingest parity config: the full r6 hot path — fused scan,
    # task pipelining, prep-ahead (all group-eligible now) — on a modest
    # CPU-compilable DeepFM.  AllReduce: dense device tables, no host tier,
    # so prep-ahead stays eligible (host_io pins prep to the main thread).
    config = JobConfig(
        model_def="deepfm.model_spec",
        model_params="buckets_per_feature=4096;embedding_dim=4;"
                     "hidden=[64,64];compute_dtype=float32",
        distribution_strategy="AllReduce",
        training_data=path,
        minibatch_size=_INGEST_MB,
        num_minibatches_per_task=_INGEST_MB_PER_TASK,
        num_epochs=epochs,
        master_addr=server.address,
        multihost=n_workers > 1,
        coordinator_port=free_port(),
        fused_task_scan=True,
        task_pipelining=True,
        checkpoint_steps=0,  # checkpoint wire has its own instrument
        distributed_heartbeat_timeout_s=100.0,
        trace=bool(trace_dump_raw),
    )
    if trace_dump_raw:
        # The embedded master's own spans (rpc.server, lease lifecycle)
        # join the dump; workers enable via the config env bus.
        from elasticdl_tpu.common import trace as _trace

        _trace.configure(enabled=True)
    env_base = dict(os.environ)
    env_base.update(config.to_env())
    if platform == "chip":
        if _CHIP_JAX_PLATFORMS:
            env_base["JAX_PLATFORMS"] = _CHIP_JAX_PLATFORMS
        else:
            env_base.pop("JAX_PLATFORMS", None)
        env_base.pop("XLA_FLAGS", None)
    else:
        env_base["JAX_PLATFORMS"] = "cpu"
        env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env_base.pop("PALLAS_AXON_POOL_IPS", None)
    env_base["JAX_COMPILATION_CACHE_DIR"] = os.path.join(tmp, "jax_cache")

    def _spawn(i: int):
        env = dict(env_base)
        env["ELASTICDL_WORKER_ID"] = f"gi-{n_workers}-{i}"
        lf = open(os.path.join(tmp, f"gi{n_workers}_{i}.log"), "a")
        p = subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.worker.main"],
            env=env, stdout=lf, stderr=subprocess.STDOUT, cwd=_REPO_ROOT,
        )
        lf.close()
        return p

    procs = {i: _spawn(i) for i in range(n_workers)}
    fail_budget = {i: 3 for i in range(n_workers)}
    t0 = time.perf_counter()
    first_done = None
    phase_times: dict = {}
    deadline = time.time() + 1200
    finished = False
    try:
        while time.time() < deadline:
            status = servicer.JobStatus({})
            if first_done is None and status["done"] > 0:
                first_done = (time.perf_counter(), status["done"])
            if status.get("phase_times"):
                phase_times = status["phase_times"]
            if status["finished"]:
                finished = True
                break
            for i, p in list(procs.items()):
                rc = p.poll()
                if rc is None:
                    continue
                if rc == RESTART_EXIT_CODE:
                    # Membership churn (a peer registering mid-boot): the
                    # gang contract IS restart-to-resync; relaunch
                    # budget-free, exactly as the PodManager does.
                    procs[i] = _spawn(i)
                    continue
                # Any other exit mirrors the PodManager's FAILED policy:
                # relaunch while the slot's budget lasts.  The expected
                # shape here is the coordination-runtime SIGABRT a survivor
                # takes when the gang LEADER restarts mid-formation (its
                # PJRT client hard-exits on the closed coordinator socket)
                # — churn the production pod flow absorbs, not a bench
                # failure.
                fail_budget[i] -= 1
                tail = ""
                lp = os.path.join(tmp, f"gi{n_workers}_{i}.log")
                if os.path.exists(lp):
                    tail = open(lp).read()[-2000:]
                if fail_budget[i] < 0:
                    raise RuntimeError(
                        f"gang worker {i} exited rc={rc} with relaunch "
                        f"budget exhausted; log tail:\n{tail}"
                    )
                log(
                    f"gang worker {i} exited rc={rc} "
                    f"(budget {fail_budget[i]} left); relaunching"
                )
                procs[i] = _spawn(i)
            time.sleep(0.1)
        t_end = time.perf_counter()
        status = servicer.JobStatus({})
    finally:
        # Runs on the raise paths too: surviving gang members (wedged on a
        # dead peer) and the master server must not outlive the fleet run.
        for p in procs.values():
            if finished:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
            elif p.poll() is None:
                p.kill()
        if finished and trace_dump_raw:
            # After the workers exited (their job-end trace tails shipped
            # on the final heartbeats) and before the server goes away.
            try:
                with open(trace_dump_raw, "w") as f:
                    json.dump(servicer.DumpTrace({}), f)
                log(f"raw trace dump -> {trace_dump_raw}")
            except Exception as e:  # a failed dump must not fail the bench
                log(f"trace dump failed: {e}")
        server.stop()
    if not finished:
        raise RuntimeError(
            f"gang fleet {n_workers}: job not finished "
            f"({status['done']} tasks done)"
        )
    if first_done is not None:
        t_first, done_at_first = first_done
    else:
        t_first, done_at_first = t0, 0
    measured_tasks = status["done"] - done_at_first
    elapsed = t_end - t_first
    if measured_tasks <= 0 or elapsed <= 0:
        measured_tasks, elapsed = status["done"], t_end - t0
    eps = measured_tasks * _INGEST_RECORDS_PER_TASK / elapsed
    out = {
        "workers": n_workers,
        "group_mode": n_workers > 1,
        "tasks_total": status["done"],
        "tasks_measured": measured_tasks,
        "records_per_task": _INGEST_RECORDS_PER_TASK,
        "elapsed_s": round(elapsed, 3),
        "examples_per_sec": round(eps),
        "wall_total_s": round(t_end - t0, 2),
        # Cumulative per-worker phase split (prep_wait/dispatch/step_wait/
        # metrics/checkpoint/control) — the ingest number's decomposition.
        "phase_times": phase_times,
    }
    log(f"ingest fleet {n_workers}: {out}")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("control", "ingest"), default="control")
    ap.add_argument("--fleets", default="")
    ap.add_argument("--tasks", type=int, default=0)
    ap.add_argument(
        "--platform", choices=("cpu", "chip"), default="cpu",
        help="ingest mode: backend handed to worker subprocesses — cpu "
             "(emulated mesh, the harness default) or chip (the image's "
             "real accelerator env, unchanged)",
    )
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    import tempfile

    log = lambda m: print(f"[mw] {m}", file=sys.stderr, flush=True)
    tmp = tempfile.mkdtemp(prefix="mw_bench_")

    if args.mode == "ingest":
        fleets = [int(x) for x in (args.fleets or "1,2").split(",")]
        n_tasks = args.tasks or 12
        results = [
            _run_ingest_fleet(n, n_tasks, tmp, log, args.platform)
            for n in fleets
        ]
        artifact = {
            "metric": "gang_ingest_e2e_examples_per_sec",
            "unit": "examples/sec",
            "harness": (
                f"cpu ({os.cpu_count()} core host), 1 fake device per "
                "worker process, real jax.distributed gang"
                if args.platform == "cpu" else "chip"
            ),
            "config": "deepfm AllReduce, criteo recordio via C++ bulk "
                      "read + decode, fused scan + task pipelining + "
                      "prep-ahead (group-eligible since r6)",
            "fleets": results,
            "note": "group-mode ingest was unmeasurable before r6 (the "
                    "control-plane mode deliberately starves the data "
                    "path); examples/sec is gang-aggregate — lockstep "
                    "peers train the SAME tasks collectively, so the "
                    "figure does not scale with fleet size, it must "
                    "HOLD as the gang grows",
        }
        # Pipeline shape (r9): the workers run JobConfig defaults for the
        # ingest/prep/lease knobs; numbers are only comparable at equal
        # shape (same rule as bench.py's record guard).
        from elasticdl_tpu.common.config import JobConfig
        from elasticdl_tpu.data.ingest_pool import resolve_threads

        _cfg = JobConfig()
        artifact["pipeline"] = {
            "ingest_threads": resolve_threads(_cfg.ingest_threads),
            "prep_depth": _cfg.prep_depth,
            "lease_batch": _cfg.lease_batch,
            "optimizer_sharding": _cfg.optimizer_sharding,
            "donate_train_state": _cfg.donate_train_state,
        }
        from tools.artifact import write_artifact

        if args.platform == "chip":
            # The module-scope cpu force aimed THIS (jax-free) process at
            # cpu; the workers ran on the image's real backend.  Restore it
            # before the artifact stamp — write_artifact records
            # JAX_PLATFORMS as the provenance guard, and an on-chip number
            # of record must not be stamped as a cpu smoke run.
            if _CHIP_JAX_PLATFORMS:
                os.environ["JAX_PLATFORMS"] = _CHIP_JAX_PLATFORMS
            else:
                os.environ.pop("JAX_PLATFORMS", None)
        write_artifact(
            artifact, "gang_ingest_r09.json", env_var="GANG_INGEST_OUT",
            path=args.out or None, log=log,
        )
        print(json.dumps(artifact["fleets"]), flush=True)
        return

    fleets = [int(x) for x in (args.fleets or "1,2,4").split(",")]
    results = [_run_fleet(n, args.tasks or 96, tmp, log) for n in fleets]
    # On this 1-core host every worker shares the CPU, so per-worker rate
    # falls ~1/N by CONTENTION alone; the control-plane bound is how much
    # of the AGGREGATE rate survives as workers multiply — a serializing
    # master would drop it, a clean one holds it flat.
    base = results[0]["tasks_per_sec"]
    for r in results:
        r["aggregate_retention_vs_1w"] = round(r["tasks_per_sec"] / base, 3)
    worst = min(r["aggregate_retention_vs_1w"] for r in results)
    artifact = {
        "metric": "control_plane_task_rate",
        "unit": "tasks/sec",
        "harness": f"cpu ({os.cpu_count()} core host), 1 fake device per "
                   "worker, task-bound job (1 minibatch of 16 per task)",
        "fleets": results,
        "control_plane_overhead_bound_pct": round((1 - worst) * 100, 1),
        "note": "per-step dispatch + prefetch off: every task is pure "
                "GetTask/feed/step/ReportTaskResult; aggregate retention "
                "~1.0 = the master adds no per-worker serialization at "
                "this scale (per-worker division is meaningless under "
                "full CPU sharing)",
    }
    from tools.artifact import write_artifact

    write_artifact(
        artifact, "multiworker_r05.json", path=args.out or None, log=log
    )
    print(json.dumps(artifact["fleets"]), flush=True)


if __name__ == "__main__":
    main()
