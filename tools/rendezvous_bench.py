"""Measure REAL-PROCESS elastic re-rendezvous latency (VERDICT r3 item 6).

tools/elastic_bench.py times the in-process resize (mesh re-form + restore +
recompile: 0.39-2.13 s).  Production takes the other path: a peer dies, the
survivor snapshots and exits RESTART_EXIT_CODE, the pod manager relaunches
it, the fresh process re-initializes jax.distributed in the new world,
restores the checkpoint, and trains.  This tool runs that exact sequence
with real worker processes on the localhost harness (2 procs x 4 fake CPU
devices — the latency measured is control-plane + process-boot + re-init +
restore work, none of which runs on the accelerator) and reports each
phase:

  kill -> eviction        heartbeat reaper notices the dead peer
  eviction -> restart     survivor snapshots + exits RESTART_EXIT_CODE
  restart -> first step   relaunch, process boot (python + jax import),
                          jax.distributed re-init, checkpoint restore,
                          recompile, first post-change task completes

Prints ONE JSON line with the phase split and total, and writes the same
dict (plus timestamp + command) to ``artifacts/rendezvous_r05.json`` — the
number of record docs/perf.md quotes (override the path with the
``RDZV_BENCH_OUT`` env var).
Usage: python tools/rendezvous_bench.py
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# FORCE cpu (not setdefault): the image exports JAX_PLATFORMS=axon, so a
# default would aim this CPU-harness tool at the real (possibly hung) chip.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")


def _free_port() -> int:
    # common.platform is jax-free: this master process never imports jax.
    from elasticdl_tpu.common.platform import free_port

    return free_port()


def _worker_env(config):
    env = dict(os.environ)
    env.update(config.to_env())
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never grab the real TPU tunnel
    return env


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_worker(worker_id, config, log_dir, incarnation):
    env = _worker_env(config)
    env["ELASTICDL_WORKER_ID"] = worker_id
    log = open(os.path.join(log_dir, f"{worker_id}.log.{incarnation}"), "w")
    return subprocess.Popen(
        [sys.executable, "-m", "elasticdl_tpu.worker.main"],
        env=env, stdout=log, stderr=subprocess.STDOUT, cwd=_REPO_ROOT,
    )


def _spawn_standby(config, log_dir, tag):
    """Park a warm spare (worker.main standby mode): imports paid up front,
    adopted later by writing its go-file — the production mechanism
    (ProcessPodBackend warm_standby), spawned directly here so the bench
    keeps per-incarnation log capture."""
    env = _worker_env(config)
    go_file = os.path.join(log_dir, f"standby.go.{tag}")
    env["ELASTICDL_STANDBY_GO_FILE"] = go_file
    log = open(os.path.join(log_dir, f"standby.log.{tag}"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "elasticdl_tpu.worker.main"],
        env=env, stdout=log, stderr=subprocess.STDOUT, cwd=_REPO_ROOT,
    )
    return proc, go_file


def _adopt_standby(proc, go_file, worker_id):
    from elasticdl_tpu.common import durable

    durable.atomic_publish_json(go_file, {"worker_id": worker_id, "env": {}})
    return proc


def main() -> None:
    import tempfile

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.worker import RESTART_EXIT_CODE

    tmp = tempfile.mkdtemp(prefix="rdzv_bench_")
    path = os.path.join(tmp, "train.rio")
    generate("mnist", path, 256)
    shards = create_data_reader(path).create_shards(32)
    dispatcher = TaskDispatcher(shards, num_epochs=200)
    rendezvous = RendezvousServer(heartbeat_timeout_s=3.0)
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    server = MasterServer(servicer, port=0).start()
    stop = threading.Event()

    def reap():
        while not stop.is_set():
            rendezvous.reap_dead()
            time.sleep(0.1)

    threading.Thread(target=reap, daemon=True).start()

    config = JobConfig(
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=path,
        minibatch_size=16,
        master_addr=server.address,
        multihost=True,
        coordinator_port=_free_port(),
        checkpoint_dir=os.path.join(tmp, "ckpt"),
        checkpoint_steps=4,
        num_epochs=200,
        # The dedicated-host setting (docs/perf.md): this bench measures the
        # best-tuned path; the shipped default is a starvation-tolerant 30 s.
        distributed_heartbeat_timeout_s=10.0,
    )

    def wait_for(cond, deadline_s, what):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            if cond():
                return time.time()
            time.sleep(0.02)
        raise RuntimeError(f"timed out waiting for {what}")

    log = lambda m: print(f"[rdzv] {m}", file=sys.stderr, flush=True)
    procs = {}
    standby = None
    try:
        procs["w-a"] = _spawn_worker("w-a", config, tmp, 0)
        procs["w-b"] = _spawn_worker("w-b", config, tmp, 0)
        # Park the warm spare while the world is healthy — exactly when the
        # ProcessPodBackend would (start_pod spawns the replacement spare).
        standby = _spawn_standby(config, tmp, "0")
        wait_for(
            lambda: rendezvous.membership()["world_size"] == 2
            and servicer.JobStatus({})["done"] >= 2,
            240, "2-process world making progress",
        )
        log("2-process world training; killing w-b")

        version0 = rendezvous.membership()["version"]
        t_kill = time.time()
        procs.pop("w-b").send_signal(signal.SIGKILL)

        t_evict = wait_for(
            lambda: rendezvous.membership()["version"] != version0
            and "w-b" not in rendezvous.membership()["workers"],
            60, "heartbeat eviction",
        )
        log(f"evicted after {t_evict - t_kill:.2f}s")

        def survivor_exited():
            rc = procs["w-a"].poll()
            if rc is None:
                return False
            if rc == RESTART_EXIT_CODE:
                return True
            # The jax.distributed runtime may abort the survivor itself
            # ("fatal errors ... another task died") before our graceful
            # RESTART path runs — the pod manager treats that marker as
            # relaunchable too (same classification as test_multihost).
            tail = open(os.path.join(tmp, "w-a.log.0")).read()[-4000:]
            if "JAX distributed service detected fatal errors" in tail:
                return True
            raise RuntimeError(f"survivor died rc={rc}:\n{tail[-2000:]}")

        t_restart = wait_for(survivor_exited, 120, "survivor exit")
        exit_kind = (
            "RESTART" if procs["w-a"].poll() == RESTART_EXIT_CODE else "fatal"
        )
        log(f"survivor exit ({exit_kind}) after {t_restart - t_evict:.2f}s")

        done_before = servicer.JobStatus({})["done"]
        # Relaunch by ADOPTING the warm spare (its python + jax imports are
        # already paid); fall back to a cold spawn if it died while parked.
        warm = standby is not None and standby[0].poll() is None
        if warm:
            procs["w-a"] = _adopt_standby(*standby, "w-a")
            standby = None
        else:
            procs["w-a"] = _spawn_worker("w-a", config, tmp, 1)
        t_first = wait_for(
            lambda: servicer.JobStatus({})["done"] > done_before
            and rendezvous.membership()["world_size"] == 1,
            240, "first post-restart task",
        )
        log(f"relaunch -> first completed task {t_first - t_restart:.2f}s "
            f"({'warm standby' if warm else 'cold spawn'})")

        result = {
            "metric": "real_process_re_rendezvous_s",
            "kill_to_eviction_s": round(t_evict - t_kill, 2),
            "eviction_to_restart_exit_s": round(t_restart - t_evict, 2),
            "relaunch_to_first_task_s": round(t_first - t_restart, 2),
            "total_s": round(t_first - t_kill, 2),
            "survivor_exit": exit_kind,
            "warm_standby": warm,
            "death_push_grace_s": config.death_push_grace_s,
            "heartbeat_timeout_s": 3.0,
            "note": "first task = relaunch (warm: restore+recompile only; "
                    "cold: + python/jax import) + distributed re-init + one "
                    "full task (2 steps)",
        }
        print(json.dumps(result), flush=True)
        from tools.artifact import write_artifact

        write_artifact(
            result, "rendezvous_r05.json", env_var="RDZV_BENCH_OUT", log=log
        )
    finally:
        stop.set()
        if standby is not None and standby[0].poll() is None:
            standby[0].kill()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        server.stop()


def main_pod() -> None:
    """Scenario B — the PRODUCTION detection + recovery path.

    Scenario A (``main``) measures the heartbeat-evicted degrade-to-1 path
    with hand-spawned processes.  Here the fleet runs under the real
    ``PodManager`` + ``ProcessPodBackend(warm_standby=True)`` exactly as
    ``elasticdl train`` wires it: the backend's watcher turns the SIGKILL
    into a FAILED pod event in ~a poll interval (0.2 s) — no heartbeat
    wait — the listener cascades it into the rendezvous eviction, the
    manager relaunches the slot (adopting the warm spare), the survivor's
    death push restarts it into the new world, and the job is RECOVERED
    when the 2-process world is training again.  Artifact:
    ``artifacts/rendezvous_pod_r05.json``.
    """
    import tempfile

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.master.pod_manager import (
        PodManager,
        PodPhase,
        ProcessPodBackend,
    )
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    tmp = tempfile.mkdtemp(prefix="rdzv_pod_")
    path = os.path.join(tmp, "train.rio")
    generate("mnist", path, 256)
    shards = create_data_reader(path).create_shards(32)
    dispatcher = TaskDispatcher(shards, num_epochs=500)
    rendezvous = RendezvousServer(heartbeat_timeout_s=3.0)
    rendezvous.set_expected(2)  # as Master.run does before starting pods
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    server = MasterServer(servicer, port=0).start()
    stop = threading.Event()

    def reap():
        while not stop.is_set():
            rendezvous.reap_dead()
            time.sleep(0.1)

    threading.Thread(target=reap, daemon=True).start()

    config = JobConfig(
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=path,
        minibatch_size=16,
        master_addr=server.address,
        multihost=True,
        coordinator_port=_free_port(),
        checkpoint_dir=os.path.join(tmp, "ckpt"),
        checkpoint_steps=4,
        num_epochs=500,
        num_workers=2,
        warm_worker_standby=True,
        distributed_heartbeat_timeout_s=10.0,
    )
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    # Pool of 2: a peer-death recovery relaunches the dead pod AND the
    # survivor (its RESTART exit) — both should boot warm.
    backend = ProcessPodBackend(warm_standby=True, standby_pool=2, log_dir=tmp)
    manager = PodManager(
        backend,
        config,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        },
    )
    # master/main.py's wiring: terminal pod -> rendezvous eviction.
    manager.add_listener(
        lambda name, phase: rendezvous.remove(name)
        if phase in PodPhase.TERMINAL
        else None
    )

    def wait_for(cond, deadline_s, what):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            if cond():
                return time.time()
            time.sleep(0.02)
        raise RuntimeError(f"timed out waiting for {what}")

    log = lambda m: print(f"[rdzv-pod] {m}", file=sys.stderr, flush=True)
    try:
        manager.start(2)
        wait_for(
            lambda: rendezvous.membership()["world_size"] == 2
            and servicer.JobStatus({})["done"] >= 2,
            300, "2-pod world making progress",
        )
        victim = manager.live_pods()[-1]
        pid = backend.pid(victim)
        version0 = rendezvous.membership()["version"]
        log(f"2-pod world training; SIGKILL {victim} (pid {pid})")
        t_kill = time.time()
        os.kill(pid, signal.SIGKILL)

        t_evict = wait_for(
            lambda: rendezvous.membership()["version"] != version0
            and victim not in rendezvous.membership()["workers"],
            60, "pod-event eviction",
        )
        log(f"evicted after {t_evict - t_kill:.2f}s (pod event, not heartbeat)")

        done_mark = servicer.JobStatus({})["done"]
        t_rec = wait_for(
            lambda: rendezvous.membership()["world_size"] == 2
            and servicer.JobStatus({})["done"] > done_mark,
            240, "2-process world training again",
        )
        log(f"full fleet recovered {t_rec - t_evict:.2f}s after eviction")

        result = {
            "metric": "pod_event_full_recovery_s",
            "kill_to_eviction_s": round(t_evict - t_kill, 2),
            "eviction_to_recovered_s": round(t_rec - t_evict, 2),
            "total_s": round(t_rec - t_kill, 2),
            "note": "PodManager + ProcessPodBackend(warm_standby) fleet; "
                    "eviction = backend watcher FAILED event (poll 0.2s), "
                    "recovered = 2-process world completing tasks again "
                    "(one relaunch adopts the warm spare, the peer's "
                    "RESTART relaunch follows)",
        }
        print(json.dumps(result), flush=True)
        from tools.artifact import write_artifact

        write_artifact(
            result, "rendezvous_pod_r05.json", env_var="RDZV_POD_BENCH_OUT",
            log=log,
        )
    finally:
        stop.set()
        manager.stop()
        server.stop()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "pod":
        main_pod()
    else:
        main()
