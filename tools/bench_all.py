"""Chip benchmarks for BASELINE configs #1-#3 (VERDICT r3 item 2).

bench.py owns the flagship DeepFM number; this tool covers the other three
reproducible configs — MNIST (AllReduce), ResNet-50/CIFAR-10 (AllReduce),
Wide&Deep/Census (ParameterServer) — plus an ImageNet-shaped ResNet-50
(224x224/1000-class, 7x7/s2 stem), and reports examples/sec/chip and MFU.
The >=40% MFU target is judged on resnet50_imagenet: it is the MXU-bound
workload — CIFAR's 32x32 convs are too small to tile the systolic array.

MFU method: FLOPs per step come from XLA's own compiled cost analysis
(``compiled.cost_analysis()['flops']``) — the count of what the compiled
program actually executes, not a hand-derived estimate — divided by
measured steady-state step time and the chip's bf16 peak (v5e: 197 TFLOP/s
per chip).  ResNet-50 is the proof the trainer sustains MXU utilization
when FLOPs dominate; the tabular models are embedding/HBM-bound by design
and their MFU is reported for completeness, not as a target.

Usage: python tools/bench_all.py [--configs mnist,resnet50,resnet50_imagenet,wide_deep]
Prints one JSON line per config; docs/perf.md carries the committed table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elasticdl_tpu.common.platform import apply_platform_env, enable_compile_cache

apply_platform_env()

V5E_BF16_PEAK = 197e12  # FLOP/s per chip

WARMUP = 5
MEASURE = 30

CONFIGS = {
    # BASELINE.json config #1: MNIST Keras functional ~ AllReduce.
    "mnist": dict(
        model_def="mnist.model_spec",
        params={},
        strategy="AllReduce",
        batch=4096,
    ),
    # Config #2: ResNet-50 on CIFAR-10, AllReduce — the BASELINE config.
    "resnet50": dict(
        model_def="cifar10_resnet.model_spec",
        params=dict(depth=50),
        strategy="AllReduce",
        batch=512,
    ),
    # ImageNet-shaped ResNet-50 (224x224, 1000 classes, 7x7/s2 stem) — the
    # honest MXU-utilization benchmark: CIFAR's 32x32 convs are too small
    # to tile the systolic array, so the >=40% MFU target is judged here.
    "resnet50_imagenet": dict(
        model_def="cifar10_resnet.model_spec",
        params=dict(
            depth=50, image_size=224, num_classes=1000, imagenet_stem=True
        ),
        strategy="AllReduce",
        batch=256,
        # Textbook training cost at the MAC=2 convention the peak is
        # quoted in: fwd ~4.1 GMACs at 224x224 = 8.2 GFLOP, x3 for
        # fwd+bwd = 24.6 GFLOP/example.  Reported alongside the
        # XLA-cost-analysis MFU as a cross-check (XLA measured ~26.7G on
        # the compiled step — same convention, plus norm/elementwise).
        analytic_flops_per_example=24.6e9,
    ),
    # Config #3: Wide&Deep on Census, ParameterServer + sharded embedding.
    "wide_deep": dict(
        model_def="wide_deep.model_spec",
        params=dict(buckets=65536),
        strategy="ParameterServer",
        batch=8192,
    ),
    # TPU-native capability extension (SURVEY §2 parallelism table: SP/CP
    # absent upstream): decoder-only transformer LM at a GPT-2-small shape
    # — the matmul-dominated workload.  remat off: the MFU bench wants the
    # no-recompute step (b=16, L=1024 activations fit HBM comfortably).
    "transformer_lm": dict(
        model_def="transformer_lm.model_spec",
        params=dict(
            vocab=32768, dim=768, n_heads=12, n_layers=12,
            seq_len=1024, max_seq=1024, remat=False,
        ),
        strategy="AllReduce",
        batch=16,
        # Per 1024-token sequence at MAC=2, fwd+bwd (x3 fwd):
        # dense blocks 6*N*L with N=12x12*768^2=84.9M -> 522 GFLOP;
        # attention 12 layers x 4L^2d x3 -> 116 GFLOP;
        # tied LM head 2LdV x3 -> 155 GFLOP  ==> ~0.79 TFLOP/example.
        # mfu_analytic_pct is the number of record for THIS config: the
        # attention runs in a Pallas kernel whose FLOPs XLA's
        # cost_analysis cannot see, so mfu_pct under-counts here.
        analytic_flops_per_example=0.79e12,
    ),
}


def _synth_batch(name: str, spec, n: int):
    import jax
    import jax.numpy as jnp

    k = jax.random.key(11)
    ks = jax.random.split(k, 3)
    if name == "mnist":
        return {
            "images": jax.random.uniform(ks[0], (n, 28, 28, 1), jnp.float32),
            "labels": jax.random.randint(ks[1], (n,), 0, 10),
        }
    if name == "resnet50":
        return {
            "images": jax.random.uniform(ks[0], (n, 32, 32, 3), jnp.float32),
            "labels": jax.random.randint(ks[1], (n,), 0, 10),
        }
    if name == "resnet50_imagenet":
        # Shapes derive from the SAME params dict the model is built from,
        # so a config edit cannot silently bench a mismatched workload.
        p = CONFIGS[name]["params"]
        size, classes = p["image_size"], p["num_classes"]
        return {
            "images": jax.random.uniform(
                ks[0], (n, size, size, 3), jnp.float32
            ),
            "labels": jax.random.randint(ks[1], (n,), 0, classes),
        }
    if name == "transformer_lm":
        p = CONFIGS[name]["params"]
        seqs = jax.random.randint(
            ks[0], (n, p["seq_len"] + 1), 0, p["vocab"]
        )
        return {
            "tokens": seqs[:, :-1],
            "labels": seqs[:, 1:],
        }
    if name == "wide_deep":
        return {
            "dense": jax.random.uniform(ks[0], (n, 5), jnp.float32, 0.0, 80.0),
            "cat": jax.random.randint(ks[1], (n, 9), 0, 1 << 30),
            "labels": jax.random.bernoulli(ks[2], 0.3, (n,)).astype(jnp.int32),
        }
    raise ValueError(name)


def bench_config(name: str, batch_override: int = 0, measure: int = MEASURE) -> dict:
    import jax

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    cfg = CONFIGS[name]
    devices = jax.devices()
    n_chips = len(devices)
    batch = batch_override or cfg["batch"]
    batch = max(batch // n_chips * n_chips, n_chips)
    spec = load_model_spec(
        "elasticdl_tpu.models", cfg["model_def"], **cfg["params"]
    )
    trainer = Trainer(
        spec,
        JobConfig(distribution_strategy=cfg["strategy"]),
        create_mesh(devices),
    )
    state = trainer.init_state(jax.random.key(0))
    host_batch = jax.device_get(_synth_batch(name, spec, batch))
    sharded = trainer.shard_batch(host_batch)
    state, metrics = trainer.train_step(state, sharded)  # builds + compiles
    jax.block_until_ready(metrics)

    # FLOPs of the compiled step, from XLA's own cost analysis (AOT lower +
    # compile hits the jit cache — same shapes — so this is cheap).  Fresh
    # batch placement: the executing call may have donated the first one.
    flops = None
    try:
        from elasticdl_tpu.common.platform import suspend_compile_cache

        sharded2 = trainer.shard_batch(host_batch)
        # Cache bypassed: an XLA:CPU AOT entry re-read by the process that
        # just wrote it hard-aborts in this jax build (platform.py).
        with suspend_compile_cache():
            cost = (
                # Third arg since r15: the graftreduce subgroup mask is a
                # traced input of every train step.
                trainer._train_step.lower(
                    state, sharded2, trainer._active_device()
                )
                .compile()
                .cost_analysis()
            )
        c = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops = float(c.get("flops", 0.0)) or None
        sharded = sharded2
    except Exception as e:  # cost analysis is best-effort; report without MFU
        print(f"  cost_analysis unavailable: {e}", file=sys.stderr)

    for _ in range(WARMUP):
        state, metrics = trainer.train_step(state, sharded)
    jax.block_until_ready(metrics)
    t0 = time.perf_counter()
    for _ in range(measure):
        state, metrics = trainer.train_step(state, sharded)
    jax.block_until_ready(metrics)
    step_s = (time.perf_counter() - t0) / measure

    out = {
        "config": name,
        "strategy": cfg["strategy"],
        "global_batch": batch,
        "examples_per_sec_per_chip": round(batch / step_s / n_chips),
        "step_ms": round(step_s * 1e3, 2),
        "chips": n_chips,
    }
    if flops:
        # cost_analysis() reports the PER-DEVICE executable's flops (the
        # SPMD module each chip runs), so per-chip MFU divides by step
        # time and peak only — dividing by n_chips again undercounted
        # multi-chip MFU by n (harmless on the 1-chip battery, wrong on a
        # mesh).  Verified: at global batch 8 on 8 devices the reported
        # count matches ~1 example's training flops, not 8.
        out["flops_per_step_per_device"] = flops
        out["mfu_pct"] = round(flops / step_s / V5E_BF16_PEAK * 100, 2)
    analytic = cfg.get("analytic_flops_per_example")
    if analytic:
        out["mfu_analytic_pct"] = round(
            analytic * (batch / n_chips) / step_s / V5E_BF16_PEAK * 100, 2
        )
    return out


def run_gauge_smoke() -> int:
    """The graftgauge CI check (bench_all --gauge-smoke): live endpoints
    answer mid-run with the instrumented families, watch_job renders a
    live scrape, instrumentation overhead holds the <2% budget, and the
    cross-rev trajectory gate passes non-empty.  Host-only (CPU-harness
    subprocess fleet, no chip probe): the smoke measures the metrics
    plane, not the accelerator."""
    import tempfile

    say = lambda m: print(f"[gauge-smoke] {m}", file=sys.stderr, flush=True)
    problems = []

    # 1. A real 1-worker job through the full master stack; chaos_bench's
    # fleet runner scrapes the master's live endpoint every second
    # mid-run and stamps the newest snapshot.
    from tools.chaos_bench import run_fleet

    tmp = tempfile.mkdtemp(prefix="gauge_smoke_")
    fleet = run_fleet(
        1, 6, tmp, say, "gauge", model="mnist", timeout_s=600.0
    )
    live = fleet.get("live_metrics") or {}
    snap = live.get("snapshot") or {}
    if not live.get("scrapes_ok"):
        problems.append(
            f"no successful mid-run scrape of the master endpoint "
            f"({live.get('last_error', 'endpoint never came up')})"
        )
    for family in ("edl_fleet_examples_per_sec", "edl_world_size",
                   "edl_dispatcher_done"):
        if family not in snap:
            problems.append(f"master family {family} missing from the "
                            f"mid-run snapshot")
    if not any(k.startswith("edl_examples_trained_total") for k in snap):
        problems.append(
            "no worker gauge envelope reached the fleet view "
            "(edl_examples_trained_total absent)"
        )

    # 2. watch_job one-shot against a LIVE endpoint (the CLI path, end to
    # end: bind, scrape, parse, render).
    from elasticdl_tpu.common import gauge
    from elasticdl_tpu.common.metrics_http import MetricsHTTPServer
    from tools.watch_job import main as watch_main

    reg = gauge.Registry()
    reg.counter("edl_smoke_total", "gauge-smoke probe").inc(3)
    probe_srv = MetricsHTTPServer(reg.render_prometheus, port=0).start()
    try:
        rc = watch_main([probe_srv.address])
    finally:
        probe_srv.stop()
    if rc != 0:
        problems.append(f"watch_job one-shot exited {rc}")

    # 3. Instrumentation + scrape overhead on the ingest A/B harness.
    from tools.ingest_bench import gauge_overhead_ab

    ab = gauge_overhead_ab(say)
    if ab["overhead_pct"] >= 2.0:
        problems.append(
            f"gauge overhead {ab['overhead_pct']}% >= 2% budget"
        )

    # 4. The cross-rev trajectory gate over the committed artifacts.
    from tools.bench_regress import run_gate

    trajectory = run_gate(log=say)
    if not trajectory["series"]:
        problems.append("bench_regress trajectory is EMPTY — the "
                        "artifact indexer found nothing")
    if not trajectory["compared"]:
        problems.append("bench_regress compared zero cross-rev pairs")
    if trajectory["regressions"]:
        problems.append(
            f"{len(trajectory['regressions'])} perf regression(s) in the "
            "committed trajectory"
        )

    result = {
        "metric": "gauge_smoke",
        "live_metrics": live,
        "fleet_tasks_done": fleet.get("tasks_done"),
        "overhead": ab,
        "trajectory_series": len(trajectory["series"]),
        "trajectory_compared": trajectory["compared"],
        "problems": problems,
    }
    from tools.artifact import write_artifact

    write_artifact(result, "GAUGE_r14.json", env_var="GAUGE_OUT", log=say)
    print(json.dumps(result), flush=True)
    if problems:
        for p in problems:
            say(f"FAIL: {p}")
        return 1
    say(
        f"PASS: {live.get('scrapes_ok')} live scrapes mid-run, overhead "
        f"{ab['overhead_pct']}% < 2%, trajectory "
        f"{len(trajectory['series'])} series / "
        f"{trajectory['compared']} compared"
    )
    return 0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="mnist,resnet50,resnet50_imagenet,wide_deep,transformer_lm")
    ap.add_argument("--batch", type=int, default=0, help="override global batch")
    ap.add_argument("--measure", type=int, default=MEASURE)
    ap.add_argument(
        "--optshard", action="store_true",
        help="also run the sharded-optimizer bytes/step bench "
        "(tools/optshard_bench.py) after the training configs; it stamps "
        "its own OPTSHARD artifact — per-replica optimizer bytes and step "
        "time, replicated vs sharded, at 1/2/4-way dp",
    )
    ap.add_argument(
        "--serving", action="store_true",
        help="also run the serving-tier latency/QPS bench "
        "(tools/serving_bench.py) after the training configs; it stamps "
        "its own SERVE artifact — the r10 latency surface alongside "
        "examples/sec",
    )
    ap.add_argument(
        "--chaos", action="store_true",
        help="also run the chaos bench (tools/chaos_bench.py) after the "
        "training configs; it stamps its own CHAOS artifact — recovery "
        "time decomposed over the splice timeline, goodput-under-churn "
        "vs a fault-free baseline, skip accounting, and the explicit "
        "zero-double-train check",
    )
    ap.add_argument(
        "--chaos-smoke", action="store_true",
        help="run ONLY the chaos smoke: a tiny 1-worker kill+recover "
        "through the full master stack asserting recovery completes and "
        "nothing trains twice — the tier-1-adjacent CI check that the "
        "fault path works without the full gang run",
    )
    ap.add_argument(
        "--masterfail", action="store_true",
        help="also run the r18 master-kill survivability fleet "
        "(tools/chaos_bench.py --masterfail) after the training configs; "
        "it stamps its own MASTERFAIL artifact — journal replay, worker "
        "ride-through, outage decomposition, exactly-once",
    )
    ap.add_argument(
        "--masterfail-smoke", action="store_true",
        help="run ONLY the masterfail smoke: 1-worker fleet, the master "
        "chaos-killed and restarted mid-job — asserts the worker rode "
        "through WITHOUT relaunch, the journal replayed, and nothing "
        "trained twice",
    )
    ap.add_argument(
        "--collective", action="store_true",
        help="also run the graftreduce bench (tools/collective_bench.py) "
        "after the training configs; it stamps its own COLLECT artifact — "
        "flat-vs-hierarchical parity + step-time sweep at 2/4/8-way, the "
        "analytic inter-host bytes cut, and the mid-collective-stall "
        "chaos fleets (blocking vs subgroup completion)",
    )
    ap.add_argument(
        "--collective-smoke", action="store_true",
        help="run ONLY the graftreduce smoke: one worker with a 2-shard "
        "dp mesh, one mid-collective stall — asserts the in-step deadline "
        "gate completes the job on the subgroup (skips > 0, live-scrape "
        "observable) with zero double-train",
    )
    ap.add_argument(
        "--mesh2d", action="store_true",
        help="also run the 2D hybrid-mesh bench (tools/mesh2d_bench.py) "
        "after the training configs; it stamps its own MESH2D artifact — "
        "1D-vs-2D parity, step time + analytic inter-host bytes across "
        "(dp, tp) shapes, and the elastic 4x2 -> 4x1 -> 4x2 chaos reform "
        "with bit-exact moments",
    )
    ap.add_argument(
        "--mesh2d-smoke", action="store_true",
        help="run ONLY the mesh2d smoke: the 1D-vs-2D parity probe plus "
        "the chaos reform (4x2 -> 4x1 -> 4x2, bit-exact moments, "
        "exactly-once, jitsan-armed zero over-budget retraces)",
    )
    ap.add_argument(
        "--trace-smoke", action="store_true",
        help="run ONLY the grafttrace overhead smoke: the ingest bench's "
        "--trace A/B (recorder off vs on, same workload) must land under "
        "2%% throughput delta — the recorded guarantee that tracing a "
        "production job is safe (docs/observability.md)",
    )
    ap.add_argument(
        "--gauge-smoke", action="store_true",
        help="run ONLY the graftgauge smoke: a 1-worker job whose live "
        "/metrics endpoints are scraped MID-RUN (fleet view + worker "
        "families must answer), a watch_job one-shot over a live "
        "endpoint, the gauge overhead A/B (<2%% budget), and the "
        "bench_regress trajectory gate over the committed artifacts "
        "(must be non-empty and regression-free)",
    )
    args = ap.parse_args()
    if args.gauge_smoke:
        raise SystemExit(run_gauge_smoke())
    if args.masterfail_smoke:
        # CPU-harness subprocess fleet, no chip probe (the chaos-smoke
        # stance): the smoke measures master crash survivability — the
        # journal replay + ride-through machinery — not the accelerator.
        from tools.chaos_bench import run_masterfail_smoke

        result = run_masterfail_smoke(
            lambda m: print(
                f"[masterfail-smoke] {m}", file=sys.stderr, flush=True
            )
        )
        print(json.dumps(result), flush=True)
        if result["problems"]:
            for p in result["problems"]:
                print(f"[masterfail-smoke] FAIL: {p}", file=sys.stderr)
            raise SystemExit(1)
        print(
            "[masterfail-smoke] PASS: worker rode the master restart out "
            f"without relaunch, {result['journal'].get('replayed_events')} "
            "journal event(s) replayed, zero double-train",
            file=sys.stderr,
        )
        return
    if args.chaos_smoke:
        # CPU-harness subprocess fleet, no chip probe: the smoke measures
        # the recovery machinery, not the accelerator.
        from tools.chaos_bench import run_smoke

        result = run_smoke(
            lambda m: print(f"[chaos-smoke] {m}", file=sys.stderr, flush=True)
        )
        print(json.dumps(result), flush=True)
        if result["problems"]:
            for p in result["problems"]:
                print(f"[chaos-smoke] FAIL: {p}", file=sys.stderr)
            raise SystemExit(1)
        print(
            "[chaos-smoke] PASS: recovery "
            f"{result['recovery'].get('recovery_time_ms')} ms, zero "
            "double-train", file=sys.stderr,
        )
        return
    if args.collective_smoke:
        # CPU-harness subprocess fleet (the chaos-smoke stance): the smoke
        # measures the in-collective exclusion machinery, not the chip.
        from tools.collective_bench import run_smoke as collective_smoke

        result = collective_smoke(
            lambda m: print(
                f"[collective-smoke] {m}", file=sys.stderr, flush=True
            )
        )
        print(json.dumps(result), flush=True)
        if result["problems"]:
            for p in result["problems"]:
                print(f"[collective-smoke] FAIL: {p}", file=sys.stderr)
            raise SystemExit(1)
        print(
            "[collective-smoke] PASS: subgroup completion with "
            f"{sum(result['collective_skips'].values())} skip(s), zero "
            "double-train", file=sys.stderr,
        )
        return
    if args.mesh2d_smoke:
        # Subprocess-driven children pin their own fake device counts (the
        # optshard stance): the smoke measures the 2D re-partitioner, not
        # the chip.
        from tools.mesh2d_bench import run_smoke as mesh2d_smoke

        result = mesh2d_smoke(
            lambda m: print(f"[mesh2d-smoke] {m}", file=sys.stderr, flush=True)
        )
        print(json.dumps(result), flush=True)
        if result["problems"]:
            for p in result["problems"]:
                print(f"[mesh2d-smoke] FAIL: {p}", file=sys.stderr)
            raise SystemExit(1)
        print(
            "[mesh2d-smoke] PASS: parity "
            f"{result['parity']['max_abs_loss_diff']:.2e}, chaos "
            f"{result['chaos']['path_tp_major']} bit-exact, zero "
            "over-budget retraces", file=sys.stderr,
        )
        return
    if args.trace_smoke:
        # Host-only (no chip probe): the smoke measures the recorder, not
        # the accelerator, and must run on any box.
        from tools.ingest_bench import trace_overhead_ab

        result = trace_overhead_ab(
            lambda m: print(f"[trace-smoke] {m}", file=sys.stderr, flush=True)
        )
        print(json.dumps(result), flush=True)
        if result["overhead_pct"] >= 2.0:
            print(
                f"[trace-smoke] FAIL: {result['overhead_pct']}% overhead "
                ">= 2% budget", file=sys.stderr,
            )
            raise SystemExit(1)
        print(
            f"[trace-smoke] PASS: {result['overhead_pct']}% overhead "
            "< 2% budget", file=sys.stderr,
        )
        return
    from elasticdl_tpu.common.platform import probe_devices

    # Killable-subprocess probe before the first in-process backend touch:
    # a hung chip costs bounded probe attempts, not the whole stage timeout
    # (bench.py's hang-proofing, applied battery-wide — VERDICT r4 Next #1).
    probe_devices(attempts=3, timeout_s=90)
    enable_compile_cache()
    results = []
    try:
        for name in args.configs.split(","):
            result = bench_config(name.strip(), args.batch, args.measure)
            results.append(result)
            print(json.dumps(result), flush=True)
            print(f"  {name}: {result['examples_per_sec_per_chip']:,} "
                  f"ex/s/chip, {result['step_ms']} ms/step, "
                  f"MFU {result.get('mfu_pct', '?')}%", file=sys.stderr)
    finally:
        if results:  # a mid-battery flake still deposits what was measured
            from tools.artifact import write_artifact

            # A subset/experiment run must not clobber the full-table
            # number of record (it did, twice, during r5 tuning) — and
            # neither must a short --measure smoke over the full list.
            names = {n.strip() for n in args.configs.split(",")}
            full = (
                names >= set(CONFIGS)
                and not args.batch
                and args.measure == MEASURE
            )
            # Subset/smoke runs never honor the env override either — with
            # BENCH_ALL_OUT pointed at the full-table file, the override
            # would reintroduce the clobber the name split prevents.
            write_artifact(
                {"metric": "bench_all_configs", "configs": results},
                "bench_all_r05.json" if full else "bench_all_partial.json",
                env_var="BENCH_ALL_OUT" if full else "",
            )
    if args.optshard:
        from tools.optshard_bench import main as optshard_main

        # Subprocess-driven (its children pin their own fake device
        # counts), so running it after the in-process configs is safe.
        optshard_main([])
    if args.chaos:
        from tools.chaos_bench import main as chaos_main

        # Subprocess-fleet driven (the bench process itself stays
        # jax-free), so running it after the in-process configs is safe.
        chaos_main([])
    if args.masterfail:
        from tools.chaos_bench import main as chaos_main

        # Master + workers all run as subprocesses; this process only
        # watches over gRPC, so it composes with the in-process configs.
        chaos_main(["--masterfail"])
    if args.mesh2d:
        from tools.mesh2d_bench import main as mesh2d_main

        # Subprocess-driven (its children pin their own fake device
        # counts), so running it after the in-process configs is safe.
        mesh2d_main([])
    if args.collective:
        from tools.collective_bench import main as collective_main

        # Subprocess-driven sweep children + subprocess worker fleets
        # (this process never re-initializes its backend), so running it
        # after the in-process configs is safe.
        collective_main([])
    if args.serving:
        from tools.serving_bench import run_bench

        serve = run_bench([50.0, 100.0, 200.0])
        for p in serve["points"]:
            print(f"  serving @{p['offered_qps']} QPS: "
                  f"p50 {p.get('p50_ms', '—')} ms, "
                  f"p99 {p.get('p99_ms', '—')} ms ({p['errors']} errors)",
                  file=sys.stderr)
    # Cross-rev trajectory gate (r14): every battery ends by re-indexing
    # the committed artifacts (including whatever this run just stamped)
    # into artifacts/TRAJECTORY.json; a same-config metric that regressed
    # past the threshold fails the run — the perf trajectory is a gated
    # number now, not a docs/perf.md narrative.
    from tools.bench_regress import run_gate

    if run_gate()["regressions"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
