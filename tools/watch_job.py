"""watch_job — render any live /metrics endpoint in the terminal.

The reading half of graftgauge's zero-infrastructure story: every process
of a job (master, workers, PS shards, the serving replica) serves
Prometheus text on its ``[graftgauge] serving /metrics on <addr>``
pod-log address, and this tool turns one of those endpoints into a
one-shot table or a polling dashboard — no Prometheus server, no
Grafana, jax-free, stdlib-only (it must run on the operator's laptop or
inside a CI step that never pays a jax import).

Usage:
  python tools/watch_job.py HOST:PORT                  # one-shot table
  python tools/watch_job.py HOST:PORT --interval 2     # poll every 2 s
  python tools/watch_job.py HOST:PORT --json           # parsed families
  python tools/watch_job.py HOST:PORT --families edl_fleet,edl_goodput
  python tools/watch_job.py HOST:PORT --healthz        # liveness JSON

The master's endpoint is the fleet view: per-worker families arrive with
a ``worker`` label, the goodput/SLO computer's gauges
(``edl_fleet_examples_per_sec``, ``edl_goodput_under_churn``,
``edl_gang_arrival_lag_seconds``, ...) sit beside them.  Histograms
render as count/sum plus the shared log-grid buckets' p50/p99 estimate
(the same arithmetic the registry's ``quantile`` uses).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# fetch_text / parse_prometheus moved to common/metrics_http.py (r19): the
# serving fleet controller scrapes replicas with the same pair, and a
# framework module cannot import from tools/.  Re-exported here so every
# existing consumer (benches, operators) keeps its import path; the module
# stays jax-free — metrics_http is stdlib-only by contract.
from elasticdl_tpu.common.metrics_http import (  # noqa: E402,F401
    fetch_text,
    parse_prometheus,
)


def _hist_stats(samples: List[dict], series_key: Tuple[Tuple[str, str], ...]):
    """count/sum/p50/p99 of one histogram series from its flat
    ``_bucket``/``_sum``/``_count`` samples (cumulative buckets; the
    quantile interpolates inside the owning bucket — the registry's own
    estimator)."""
    buckets: List[Tuple[float, float]] = []
    total = s = 0.0
    for sample in samples:
        labels = dict(sample["labels"])
        le = labels.pop("le", None)
        key = tuple(sorted(labels.items()))
        if key != series_key:
            continue
        if sample["name"].endswith("_bucket") and le is not None:
            edge = float("inf") if le == "+Inf" else float(le)
            buckets.append((edge, sample["value"]))
        elif sample["name"].endswith("_count"):
            total = sample["value"]
        elif sample["name"].endswith("_sum"):
            s = sample["value"]
    buckets.sort()

    def q(p: float) -> Optional[float]:
        if total <= 0 or not buckets:
            return None
        target = p * total
        prev_edge, prev_cum = 0.0, 0.0
        for edge, cum in buckets:
            if cum >= target:
                if edge == float("inf"):
                    return prev_edge
                frac = (target - prev_cum) / max(cum - prev_cum, 1e-12)
                return prev_edge + (edge - prev_edge) * frac
            prev_edge, prev_cum = (0.0 if edge == float("inf") else edge), cum
        return prev_edge
    return total, s, q(0.5), q(0.99)


def _scalar_sum(families: Dict[str, dict], name: str) -> Optional[float]:
    fam = families.get(name)
    if not fam or not fam["samples"]:
        return None
    return sum(s["value"] for s in fam["samples"])


def render_collectives(
    families: Dict[str, dict],
    prev: Optional[Dict[str, dict]] = None,
    dt_s: float = 0.0,
) -> Optional[str]:
    """One summary line for the graftreduce (r15) gauge families — skip
    total, current subgroup size, and the inter-host bytes rate — or
    None when the endpoint serves none of them (a PS shard, an old
    build).  The bytes RATE needs two scrapes (``prev`` + ``dt_s``, the
    polling mode); one-shot views show the cumulative total instead."""
    skips = _scalar_sum(families, "edl_collective_skip_total")
    sub = _scalar_sum(families, "edl_collective_subgroup_size")
    total = _scalar_sum(families, "edl_collective_interhost_bytes_total")
    if skips is None and sub is None and total is None:
        return None
    parts = []
    if skips is not None:
        parts.append(f"skips={skips:.0f}")
    if sub is not None:
        parts.append(f"subgroup={sub:.0f}")
    if total is not None:
        prev_total = (
            _scalar_sum(prev, "edl_collective_interhost_bytes_total")
            if prev else None
        )
        if prev_total is not None and dt_s > 0:
            rate = max(total - prev_total, 0.0) / dt_s
            parts.append(f"interhost={rate / 1e6:.2f} MB/s")
        else:
            parts.append(f"interhost_total={total / 1e6:.2f} MB")
    return "collectives: " + " ".join(parts)


def render_locks(families: Dict[str, dict], top: int = 3) -> Optional[str]:
    """One summary line for the locksan contention families (r16) —
    total sanitized acquires plus the ``top`` locks by p99 wait — or None
    when the endpoint serves none (sanitizer off, or an old build).  The
    full per-lock histogram still renders in the table below."""
    acquires = _scalar_sum(families, "edl_lock_acquire_total")
    hist = families.get("edl_lock_wait_ms")
    if acquires is None and hist is None:
        return None
    parts = []
    if acquires is not None:
        parts.append(f"acquires={acquires:.0f}")
    if hist is not None:
        keys = sorted({
            tuple(sorted(
                (k, v) for k, v in s["labels"].items() if k != "le"
            ))
            for s in hist["samples"]
        })
        waits = []
        for key in keys:
            count, _total, _p50, p99 = _hist_stats(hist["samples"], key)
            if count > 0 and p99 is not None:
                name = dict(key).get("lock", "?")
                waits.append((p99, name))
        for p99, name in sorted(waits, reverse=True)[:top]:
            parts.append(f"{name} p99~{p99:.2f}ms")
    return "locks: " + " ".join(parts)


def render_compiles(
    families: Dict[str, dict],
    prev: Optional[Dict[str, dict]] = None,
    top: int = 4,
) -> Optional[str]:
    """One summary line for the jitsan compile family (v6) — total XLA
    lowerings plus the ``top`` jit sites by count — or None when the
    endpoint serves none (jitsan off, or an old build).  In polling mode
    a count that grew since the previous scrape is marked ``+N RETRACE``:
    after warmup the steady state adds zero, so any live delta is the
    silent-throughput-halving retrace this family exists to surface."""
    fam = families.get("edl_jit_compiles_total")
    if not fam or not fam["samples"]:
        return None
    prev_by_fn: Dict[str, float] = {}
    if prev:
        for s in (prev.get("edl_jit_compiles_total") or {}).get(
            "samples", []
        ):
            prev_by_fn[s["labels"].get("fn", "?")] = s["value"]
    parts = [f"total={sum(s['value'] for s in fam['samples']):.0f}"]
    ranked = sorted(
        fam["samples"], key=lambda s: -s["value"]
    )
    for s in ranked[:top]:
        fn = s["labels"].get("fn", "?")
        cell = f"{fn}={s['value']:.0f}"
        before = prev_by_fn.get(fn)
        if before is not None and s["value"] > before:
            cell += f" (+{s['value'] - before:.0f} RETRACE)"
        parts.append(cell)
    return "compiles: " + " ".join(parts)


def render_mesh(families: Dict[str, dict]) -> Optional[str]:
    """One ``mesh: dp4xtp2`` line from the ``edl_mesh_shape`` gauge (r20:
    the worker publishes one sample per axis), or None when the endpoint
    serves none (pre-2D build, or the trainer not yet formed).  Elastic
    reforms move this line live — the watcher's view of a 4x2 -> 4x1
    re-partition."""
    fam = families.get("edl_mesh_shape")
    if not fam or not fam["samples"]:
        return None
    by_axis = {
        s["labels"].get("axis", "?"): s["value"] for s in fam["samples"]
    }
    parts = [
        f"{axis}{by_axis[axis]:.0f}"
        for axis in ("dp", "tp")
        if axis in by_axis
    ]
    if not parts:
        return None
    return "mesh: " + "x".join(parts)


def render_table(families: Dict[str, dict],
                 prefixes: Optional[List[str]] = None) -> str:
    """One aligned line per series; histograms summarize to
    count/mean/p50/p99."""
    lines: List[str] = []
    for name in sorted(families):
        if prefixes and not any(name.startswith(p) for p in prefixes):
            continue
        fam = families[name]
        samples = fam["samples"]
        if fam["type"] == "histogram":
            keys = sorted({
                tuple(sorted(
                    (k, v) for k, v in s["labels"].items() if k != "le"
                ))
                for s in samples
            })
            for key in keys:
                count, total, p50, p99 = _hist_stats(samples, key)
                label_s = (
                    "{" + ",".join(f"{k}={v}" for k, v in key) + "}"
                    if key else ""
                )
                mean = total / count if count else 0.0
                lines.append(
                    f"{name}{label_s:<28} n={count:<8.0f} "
                    f"mean={mean:<9.2f} p50~{0 if p50 is None else p50:<9.2f} "
                    f"p99~{0 if p99 is None else p99:.2f}"
                )
            continue
        for sample in samples:
            label_s = (
                "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(sample["labels"].items())
                ) + "}" if sample["labels"] else ""
            )
            v = sample["value"]
            v_s = str(int(v)) if v == int(v) else f"{v:.4g}"
            lines.append(f"{sample['name']}{label_s:<40} {v_s}")
    return "\n".join(lines)


def fetch(address: str, timeout_s: float = 5.0) -> Dict[str, dict]:
    """One scrape, parsed — the programmatic entry (benches stamp this
    as their ``live_metrics`` snapshot)."""
    return parse_prometheus(fetch_text(address, timeout_s=timeout_s))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("address", help="HOST:PORT (or full URL) of a "
                    "/metrics endpoint — the [graftgauge] pod-log line")
    ap.add_argument("--interval", type=float, default=0.0,
                    help="poll every N seconds (0 = one-shot)")
    ap.add_argument("--json", action="store_true",
                    help="emit the parsed families as JSON")
    ap.add_argument("--families", default="",
                    help="comma list of family-name prefixes to show")
    ap.add_argument("--healthz", action="store_true",
                    help="fetch /healthz instead of /metrics")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    prefixes = [p for p in args.families.split(",") if p]

    # Previous scrape (+ its time) for rate lines in polling mode.
    state: dict = {"prev": None, "t": 0.0}

    def once() -> None:
        if args.healthz:
            body = fetch_text(args.address, "/healthz", args.timeout)
            print(json.dumps(json.loads(body), indent=None if args.json else 1))
            return
        families = fetch(args.address, args.timeout)
        now = time.monotonic()
        if prefixes:
            families = {
                n: f for n, f in families.items()
                if any(n.startswith(p) for p in prefixes)
            }
        if args.json:
            print(json.dumps(families, sort_keys=True))
        else:
            summary = render_collectives(
                families, state["prev"],
                now - state["t"] if state["prev"] else 0.0,
            )
            if summary:
                print(summary)
            locks = render_locks(families)
            if locks:
                print(locks)
            compiles = render_compiles(families, state["prev"])
            if compiles:
                print(compiles)
            mesh = render_mesh(families)
            if mesh:
                print(mesh)
            print(render_table(families))
        state["prev"], state["t"] = families, now

    if args.interval <= 0:
        once()
        return 0
    try:
        while True:
            print(f"--- {args.address} @ "
                  f"{time.strftime('%H:%M:%S')} ---")
            try:
                once()
            except OSError as e:  # endpoint briefly unreachable: keep polling
                print(f"(scrape failed: {e})", file=sys.stderr)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
