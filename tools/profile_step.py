"""Profile the flagship DeepFM train step on the live chip and print a
per-HLO-op time breakdown parsed from the xplane trace.

Usage:
    python tools/profile_step.py [--steps N] [--batch B] [--impl IMPL]
                                 [--out DIR] [--top K]

This is the honest instrument VERDICT r2 demanded: per-op device time from a
``jax.profiler`` trace of the REAL step (wall-clock micros on the tunneled
chip are bimodal and untrustworthy — VERDICT r2 Weak #2).  The breakdown is
computed from the xplane proto via the installed ``xprof`` plugin's converter.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elasticdl_tpu.common.platform import apply_platform_env, enable_compile_cache

# jax imports live inside the functions that profile: --parse-only and
# --help must never touch (or hang on) the chip.


def run_profiled_steps(
    out_dir: str, steps: int, batch_size: int, impl: str, config: str = ""
):
    apply_platform_env()
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    enable_compile_cache()
    devices = jax.devices()
    print(f"devices: {len(devices)} x {devices[0].platform}", file=sys.stderr)

    if config:
        # Profile one of bench_all's configs (e.g. resnet50_imagenet) with
        # the same spec/strategy/synthetic batch the MFU table measures.
        from tools.bench_all import CONFIGS, _synth_batch

        cfg = CONFIGS[config]
        spec = load_model_spec(
            "elasticdl_tpu.models", cfg["model_def"], **cfg["params"]
        )
        trainer = Trainer(
            spec, JobConfig(distribution_strategy=cfg["strategy"]),
            create_mesh(devices),
        )
        bs = batch_size or cfg["batch"]
        bs = max(bs // len(devices) * len(devices), len(devices))
        batch = trainer.shard_batch(
            jax.device_get(_synth_batch(config, spec, bs))
        )
        return _profile_loop(trainer, batch, out_dir, steps)

    batch_size = batch_size or 8192
    spec = load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        buckets_per_feature=65536,
        embedding_dim=8,
        hidden=(400, 400),
    )
    mesh = create_mesh(devices)
    cfg = JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER)
    if impl:
        cfg = JobConfig(
            distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
            embedding_lookup_impl=impl,
        )
    trainer = Trainer(spec, cfg, mesh)
    print(f"resolved embedding impl: {trainer.ctx.embedding_impl}", file=sys.stderr)

    k = jax.random.key(7)
    k1, k2, k3 = jax.random.split(k, 3)
    batch = trainer.shard_batch({
        "dense": jax.random.uniform(k1, (batch_size, 13), jnp.float32, 0.0, 1000.0),
        "cat": jax.random.randint(k2, (batch_size, 26), 0, 1 << 30),
        "labels": jax.random.bernoulli(k3, 0.25, (batch_size,)).astype(jnp.int32),
    })

    return _profile_loop(trainer, batch, out_dir, steps)


def _profile_loop(trainer, batch, out_dir: str, steps: int):
    import time

    import jax

    state = trainer.init_state(jax.random.key(0))
    t0 = time.perf_counter()
    state, metrics = trainer.train_step(state, batch)
    jax.block_until_ready(metrics)
    print(f"compile+first step: {time.perf_counter()-t0:.1f}s", file=sys.stderr)
    # warmup
    for _ in range(2):
        state, metrics = trainer.train_step(state, batch)
    jax.block_until_ready(metrics)

    jax.profiler.start_trace(out_dir)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.train_step(state, batch)
    jax.block_until_ready(metrics)
    elapsed = time.perf_counter() - t0
    jax.profiler.stop_trace()
    print(f"measured: {elapsed/steps*1e3:.2f} ms/step over {steps} steps",
          file=sys.stderr)
    return elapsed / steps


def parse_op_stats(out_dir: str, top: int):
    """Extract per-op device-time from the trace's xplane proto."""
    paths = sorted(glob.glob(os.path.join(out_dir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        print("no xplane.pb found", file=sys.stderr)
        return
    xplane = paths[-1]
    print(f"parsing {xplane}", file=sys.stderr)
    from xprof.convert import raw_to_tool_data as rtd

    for tool in ("framework_op_stats", "op_profile"):
        try:
            data, _ = rtd.xspace_to_tool_data([xplane], tool, {})
        except Exception as e:
            print(f"{tool}: failed: {type(e).__name__}: {e}", file=sys.stderr)
            continue
        fname = os.path.join(out_dir, f"{tool}.json")
        if isinstance(data, bytes):
            data = data.decode("utf-8", errors="replace")
        with open(fname, "w") as f:
            f.write(data if isinstance(data, str) else json.dumps(data))
        print(f"wrote {fname}", file=sys.stderr)
    _summarize(out_dir, top)


def _summarize(out_dir: str, top: int):
    """Print the top-K device ops by total self-time from the parsed stats."""
    fname = os.path.join(out_dir, "framework_op_stats.json")
    if not os.path.exists(fname):
        return
    with open(fname) as f:
        tbl = json.load(f)[0]  # gviz [device_table, host_table]
    cols = [c["label"] for c in tbl["cols"]]
    i_name = cols.index("Operation Name")
    i_tot = cols.index("Total self-time (us)")
    i_occ = cols.index("#Occurrences")
    rows = []
    for r in tbl["rows"]:
        vals = [c.get("v") for c in r["c"]]
        rows.append((vals[i_tot], vals[i_occ], vals[i_name]))
    rows.sort(reverse=True)
    total = sum(t for t, _, name in rows if name != "IDLE")
    print(f"total device self-time: {total / 1000:.2f} ms (all steps)",
          file=sys.stderr)
    for t, occ, name in rows[:top]:
        print(f"  {t / 1000:9.3f} ms  x{int(occ):>8}  {name[:90]}",
              file=sys.stderr)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--impl", default="")
    ap.add_argument("--config", default="",
                    help="profile a tools/bench_all config instead of DeepFM")
    ap.add_argument("--out", default="/tmp/deepfm_profile")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--parse-only", action="store_true")
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)
    if not args.parse_only:
        run_profiled_steps(args.out, args.steps, args.batch,
                           args.impl, config=args.config)
    parse_op_stats(args.out, args.top)


if __name__ == "__main__":
    main()
