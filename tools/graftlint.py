"""graftlint CLI — the repo's static-analysis gate.

Usage:
    python tools/graftlint.py [paths...]         # default: elasticdl_tpu tools
    python tools/graftlint.py --changed          # git-diff-scoped fast mode
    python tools/graftlint.py --json             # findings + waiver inventory
    python tools/graftlint.py --callgraph        # dump the v2 call/lock graph
    python tools/graftlint.py --threadmap        # dump the v5 role map
    python tools/graftlint.py --durables         # dump the v7 durable inventory
    python tools/graftlint.py --wire             # dump the v8 wire inventory
    python tools/graftlint.py --update-wire-lock # regenerate the schema lock
    python tools/graftlint.py --artifact [PATH]  # stamp LINT artifact
    python tools/graftlint.py --list-rules

Exit code 0 = clean, 1 = findings, 2 = usage/internal error.  Pure stdlib
and jax-free by design (the import-hygiene pass guards this file too): the
pre-commit path must cost milliseconds, never a backend init.

``--changed`` scopes reporting to files changed vs HEAD (plus untracked)
AND their module-level DEPENDENTS: the project-wide passes (import-hygiene,
lock-order, blocking-propagation) judge whole-graph properties, so a change
to a helper module must re-lint every module that imports it.  Install as a
pre-commit hook with tools/precommit.sh (see docs/static_analysis.md).

Waiver syntax (inline, same line as the finding or the comment-only line
above): ``# graftlint: allow[<rule>] <reason>`` — reason mandatory; a
waiver that suppresses nothing is itself a finding (``stale-waiver``); see
docs/static_analysis.md for the invariant catalogue.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from collections import Counter
from typing import List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

DEFAULT_PATHS = ("elasticdl_tpu", "tools")
ARTIFACT_NAME = "LINT_r22.json"

#: jitsan runtime stats (common/jitsan.py dump, GRAFT_JITSAN_DUMP) merged
#: into the artifact when present: the static tool stays jax-free, so the
#: measured compile counts come from a jitsan-armed run's dump file.
JITSAN_STATS_DEFAULT = os.path.join("artifacts", "jitsan_stats.json")

#: crashsan matrix summary (tools/crashsan_matrix.py) merged into the
#: artifact when present — same stance as the jitsan dump: the static tool
#: proves the write routing, the matrix proves the crash states recover.
CRASHSAN_MATRIX_DEFAULT = os.path.join("artifacts", "crashsan_matrix.json")

#: version-skew roundtrip verdict (tools/wire_skew.py) merged into the
#: artifact when present — same stance again: the static wire rules prove
#: the field-access grammar, the skew run proves a v1-masked worker
#: completes a real gRPC job against a current master with zero wire
#: violations and zero double-trains.
WIRE_SKEW_DEFAULT = os.path.join("artifacts", "wire_skew.json")


def _changed_files(repo: str) -> Optional[List[str]]:
    """Repo-relative .py files touched vs HEAD (worktree + index) plus
    untracked — the pre-commit scope.  None when git itself failed: the
    caller must fail LOUD (exit 2), because 'git broke' reported as
    'nothing changed' would let a violating commit through the gate."""
    out: List[str] = []
    for args in (
        ["git", "diff", "--name-only", "HEAD", "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            r = subprocess.run(
                args, cwd=repo, capture_output=True, text=True, timeout=20
            )
        except Exception:
            return None
        if r.returncode != 0:
            return None
        out.extend(line.strip() for line in r.stdout.splitlines())
    return sorted({p for p in out if p.endswith(".py")})


def _callgraph_dump(sources) -> dict:
    """The v2 interprocedural model, machine-readable: function/edge
    counts, blocking roots, and the lock graph with its annotations."""
    from elasticdl_tpu.analysis.callgraph import shared_graph

    g = shared_graph(sources)
    edges = g.lock_edges()
    return {
        "functions": sum(1 for f in g.functions.values() if f.resolvable),
        "call_edges": sum(
            len(f.calls) for f in g.functions.values() if f.resolvable
        ),
        "hot_path_functions": sorted(
            q for q, f in g.functions.items() if f.hot_path
        ),
        "blocking_roots": g.blocking_roots(),
        "locks": {
            lock_id: {
                "declared_at": f"{d.path}:{d.line}",
                "locksan": d.is_locksan,
                "leaf": d.rt_leaf,
                "before": list(d.rt_before),
                "reentrant": d.reentrant,
            }
            for lock_id, d in sorted(g.locks.items())
        },
        "lock_edges": [
            {"held": a, "acquired": b, "witness": w}
            for (a, b), w in sorted(edges.items())
        ],
    }


def _threadmap_dump(sources) -> dict:
    """The v5 role model, machine-readable: role -> functions plus the
    inferred entry points (``--threadmap``, mirroring ``--callgraph``)."""
    from elasticdl_tpu.analysis.thread_map import shared_thread_map

    return shared_thread_map(sources).dump()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "paths", nargs="*", default=list(DEFAULT_PATHS),
        help="files/directories to lint (default: elasticdl_tpu tools)",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only files changed vs HEAD (plus untracked) under the "
        "given paths, PLUS modules that import them — pre-commit fast "
        "mode; project-wide passes still see the full file set",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit {findings: [...], waivers: [...]} as JSON",
    )
    parser.add_argument(
        "--callgraph", action="store_true",
        help="dump the interprocedural model (functions, blocking roots, "
        "lock graph) as JSON and exit",
    )
    parser.add_argument(
        "--threadmap", action="store_true",
        help="dump the v5 thread-role map (role -> functions, entry "
        "points) as JSON and exit",
    )
    parser.add_argument(
        "--durables", action="store_true",
        help="dump the v7 durable-file inventory (constant -> writers -> "
        "recovery readers) as JSON and exit",
    )
    parser.add_argument(
        "--wire", action="store_true",
        help="dump the v8 wire inventory (method -> request/response "
        "schema -> sender/receiver sites) as JSON and exit",
    )
    parser.add_argument(
        "--update-wire-lock", action="store_true",
        help="regenerate artifacts/wire_schema.lock.json from the current "
        "MessageSchema tables (the wire-evolution baseline) and exit — "
        "run it in the SAME diff as any schema change",
    )
    parser.add_argument(
        "--artifact", nargs="?", const="", default=None, metavar="PATH",
        help="write a LINT artifact (findings + per-rule counts + waiver "
        "inventory + lock-graph/blocking-root stats + code_rev) via "
        f"tools/artifact.py; optional explicit path, else "
        f"artifacts/{ARTIFACT_NAME} (env override LINT_OUT)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)

    from elasticdl_tpu.analysis import all_passes, collect_waivers
    from elasticdl_tpu.analysis.core import iter_file_paths, run_lint_full

    passes = all_passes()
    if args.list_rules:
        for p in passes:
            print(f"{p.name:20s} {p.description}")
        print(f"{'stale-waiver':20s} a waiver that suppresses no finding is "
              "itself a finding")
        print(f"{'waiver-syntax':20s} waivers must be "
              "'# graftlint: allow[<rule>] <reason>' with a known rule")
        return 0

    # Resolve paths relative to the repo root so display paths (and the
    # import-hygiene module names derived from them) are stable no matter
    # where the tool is invoked from.
    roots = [
        p if os.path.isabs(p) else os.path.join(_REPO_ROOT, p)
        for p in args.paths
    ]
    missing = [p for p in roots if not os.path.exists(p)]
    if missing:
        print(f"graftlint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    all_files = iter_file_paths(roots)
    only_paths = None
    preloaded = None
    n_changed = n_dependents = 0
    if args.changed:
        changed = _changed_files(_REPO_ROOT)
        if changed is None:
            print(
                "graftlint: --changed could not read the git state; "
                "refusing to report a clean pass (run without --changed)",
                file=sys.stderr,
            )
            return 2
        changed_set = set(changed)
        only_paths = {
            os.path.relpath(fp, _REPO_ROOT)
            for fp in all_files
            if os.path.relpath(fp, _REPO_ROOT) in changed_set
        }
        n_changed = len(only_paths)
        # Project-wide passes judge whole-graph properties: re-lint every
        # module that imports a changed one, or a helper edit could break
        # an unchanged root silently (import-hygiene chains, lock-order
        # edges, blocking propagation — and since v5, thread-role
        # propagation and shared-state judgements, whose typed call edges
        # ride the same import graph — all cross module boundaries).
        from elasticdl_tpu.analysis.core import load_sources
        from elasticdl_tpu.analysis.import_hygiene import module_dependents

        preloaded = load_sources(all_files, rel_to=_REPO_ROOT)
        deps = module_dependents(preloaded[0], only_paths)
        n_dependents = len(deps - only_paths)
        only_paths |= deps

    if args.update_wire_lock:
        # A pure regenerator: findings must not block it — the whole point
        # is to clear a wire-evolution finding in the same diff.
        from elasticdl_tpu.analysis.core import load_sources
        from elasticdl_tpu.analysis.wire_discipline import (
            WIRE_LOCK_PATH, wire_fingerprint,
        )
        from elasticdl_tpu.common import durable

        srcs = (preloaded or load_sources(all_files, rel_to=_REPO_ROOT))[0]
        lock_path = os.path.join(_REPO_ROOT, WIRE_LOCK_PATH)
        durable.atomic_publish_json(
            lock_path, wire_fingerprint(srcs), indent=1
        )
        print(f"wire-schema lock written to {lock_path}", file=sys.stderr)
        return 0

    findings, sources = run_lint_full(
        roots, passes, rel_to=_REPO_ROOT, only_paths=only_paths,
        preloaded=preloaded,
    )
    waivers = collect_waivers(sources, only_paths=only_paths)

    if args.callgraph or args.threadmap or args.durables or args.wire:
        # Findings still gate the exit code — render them (stderr, so the
        # stdout JSON stays parseable) or a failing dump is undiagnosable.
        for f in findings:
            print(f.render(), file=sys.stderr)
        if args.callgraph:
            dump = _callgraph_dump(sources)
        elif args.threadmap:
            dump = _threadmap_dump(sources)
        elif args.wire:
            from elasticdl_tpu.analysis.wire_discipline import wire_inventory

            dump = wire_inventory(sources)
        else:
            from elasticdl_tpu.analysis.durability import durables_inventory

            dump = durables_inventory(sources)
        print(json.dumps(dump, indent=1, sort_keys=True))
        return 1 if findings else 0

    if args.as_json:
        print(json.dumps(
            {
                "findings": [f.__dict__ for f in findings],
                "waivers": waivers,
            },
            indent=1, sort_keys=True,
        ))
    else:
        for f in findings:
            print(f.render())
        scope = (
            f"{n_changed} changed (+{n_dependents} dependent)"
            if only_paths is not None else str(len(all_files))
        )
        print(
            f"graftlint: {len(findings)} finding(s) across {scope} file(s)",
            file=sys.stderr,
        )

    if args.artifact is not None:
        from elasticdl_tpu.analysis.jit_discipline import declared_sites
        from tools.artifact import code_rev, write_artifact

        by_rule = Counter(f.rule for f in findings)
        waivers_by_rule = Counter(w["rule"] for w in waivers)
        cg = _callgraph_dump(sources)
        tm = _threadmap_dump(sources)
        # v6 jitsan section: the statically declared name/budget table,
        # plus the runtime lowering counts when a jitsan-armed run left a
        # dump (env JITSAN_STATS overrides the default path).  The
        # bench_regress trajectory gate reads the runtime half: any
        # compile count past its declared budget gates outright.
        stats_path = os.environ.get(
            "JITSAN_STATS", os.path.join(_REPO_ROOT, JITSAN_STATS_DEFAULT)
        )
        jitsan_runtime = None
        jitsan_meta: dict = {}
        if os.path.exists(stats_path):
            try:
                with open(stats_path, encoding="utf-8") as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict):
                    meta = loaded.pop("_meta", None)
                    jitsan_runtime = loaded
                    if isinstance(meta, dict):
                        jitsan_meta = dict(meta)
            except (OSError, ValueError):
                pass  # a torn dump must not fail the lint artifact
        if jitsan_runtime is not None:
            # Staleness flag: a dump written before the last CODE commit
            # measured different code — stamp the mismatch rather than
            # silently certifying old counts as this revision's (the
            # consumer decides; the honest default is to re-run the
            # armed suite with GRAFT_JITSAN_DUMP and re-stamp).  The
            # reference excludes artifacts/-only commits: the stamp
            # workflow (commit code, refresh dump, commit artifacts)
            # must not mark its own dump stale — committing artifacts
            # changes no measured code.
            dumped_s = jitsan_meta.get("utc_s") or os.path.getmtime(stats_path)
            try:
                r = subprocess.run(
                    ["git", "log", "-1", "--format=%ct", "--",
                     ".", ":(exclude)artifacts"],
                    cwd=_REPO_ROOT, capture_output=True, text=True,
                    timeout=10,
                )
                code_s = int(r.stdout.strip()) if r.returncode == 0 else None
            except Exception:
                code_s = None
            jitsan_meta["stale_vs_code"] = (
                bool(code_s is not None and dumped_s < code_s)
            )
        # v7 crashsan section: the matrix driver's summary (crash points
        # injected / recovered / contract class per scenario) when a run
        # left one (env CRASHSAN_MATRIX overrides the default path).
        # bench_regress gates crashsan_unrecovered at zero.
        matrix_path = os.environ.get(
            "CRASHSAN_MATRIX",
            os.path.join(_REPO_ROOT, CRASHSAN_MATRIX_DEFAULT),
        )
        crashsan_summary = None
        if os.path.exists(matrix_path):
            try:
                with open(matrix_path, encoding="utf-8") as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict):
                    crashsan_summary = loaded.get("summary", loaded)
            except (OSError, ValueError):
                pass  # a torn matrix file must not fail the lint artifact
        # v8 wire section: the static inventory (methods, schemas,
        # resolved sender/receiver sites) plus the version-skew roundtrip
        # verdict when a tools/wire_skew.py run left one (env WIRE_SKEW
        # overrides the default path).  bench_regress gates
        # wire_unknown_fields at zero alongside the finding counts.
        from elasticdl_tpu.analysis.wire_discipline import wire_inventory

        skew_path = os.environ.get(
            "WIRE_SKEW", os.path.join(_REPO_ROOT, WIRE_SKEW_DEFAULT)
        )
        skew_verdict = None
        if os.path.exists(skew_path):
            try:
                with open(skew_path, encoding="utf-8") as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict):
                    skew_verdict = loaded
            except (OSError, ValueError):
                pass  # a torn skew dump must not fail the lint artifact
        wire_inv = wire_inventory(sources)
        unknown_fields = (
            (skew_verdict.get("wiresan") or {}).get("unknown_fields") or {}
            if skew_verdict else {}
        )
        from elasticdl_tpu.analysis.durability import durables_inventory

        write_artifact(
            {
                # The trajectory gate (tools/bench_regress.py) indexes
                # this family by findings count, direction=down.
                "metric": "lint_findings",
                "findings": len(findings),
                "by_rule": dict(sorted(by_rule.items())),
                "waivers": len(waivers),
                "waivers_by_rule": dict(sorted(waivers_by_rule.items())),
                "files_scanned": len(all_files),
                "changed_only": bool(args.changed),
                "rules": sorted(p.name for p in passes),
                "blocking_roots": {
                    "count": len(cg["blocking_roots"]),
                    "functions": cg["blocking_roots"],
                },
                "lock_graph": {
                    "locks": len(cg["locks"]),
                    "locksan_wrapped": sum(
                        1 for d in cg["locks"].values() if d["locksan"]
                    ),
                    "leaf": sorted(
                        k for k, d in cg["locks"].items() if d["leaf"]
                    ),
                    "edges": [
                        [e["held"], e["acquired"]] for e in cg["lock_edges"]
                    ],
                },
                "hot_path_functions": len(cg["hot_path_functions"]),
                "jitsan": {
                    "declared": declared_sites(sources),
                    "runtime": jitsan_runtime,
                    "runtime_meta": jitsan_meta,
                    "stats_file": (
                        os.path.relpath(stats_path, _REPO_ROOT)
                        if jitsan_runtime is not None else None
                    ),
                },
                "durables": durables_inventory(sources),
                "wire": {
                    "protocol_version": wire_inv["protocol_version"],
                    "methods": len(wire_inv["methods"]),
                    "lock_file": "artifacts/wire_schema.lock.json",
                    "unknown_total": sum(unknown_fields.values()),
                    "skew": skew_verdict,
                    "skew_file": (
                        os.path.relpath(skew_path, _REPO_ROOT)
                        if skew_verdict is not None else None
                    ),
                },
                "crashsan": {
                    "summary": crashsan_summary,
                    "matrix_file": (
                        os.path.relpath(matrix_path, _REPO_ROOT)
                        if crashsan_summary is not None else None
                    ),
                },
                "thread_map": {
                    "roles": len(tm["roles"]),
                    "entries": len(tm["entries"]),
                    "functions_with_role": tm["functions_with_role"],
                    "functions_total": tm["functions_total"],
                    "entries_by_kind": dict(sorted(Counter(
                        e["kind"] for e in tm["entries"]
                    ).items())),
                },
                "code_rev": code_rev(),
            },
            ARTIFACT_NAME,
            env_var="LINT_OUT",
            path=args.artifact or None,
        )

    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
