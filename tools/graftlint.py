"""graftlint CLI — the repo's static-analysis gate.

Usage:
    python tools/graftlint.py [paths...]         # default: elasticdl_tpu tools
    python tools/graftlint.py --changed          # git-diff-scoped fast mode
    python tools/graftlint.py --json             # machine-readable findings
    python tools/graftlint.py --artifact [PATH]  # stamp LINT artifact
    python tools/graftlint.py --list-rules

Exit code 0 = clean, 1 = findings, 2 = usage/internal error.  Pure stdlib
and jax-free by design (the import-hygiene pass guards this file too): the
pre-commit path must cost milliseconds, never a backend init.

Waiver syntax (inline, same line as the finding or the comment-only line
above): ``# graftlint: allow[<rule>] <reason>`` — reason mandatory; see
docs/static_analysis.md for the invariant catalogue.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from collections import Counter
from typing import List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

DEFAULT_PATHS = ("elasticdl_tpu", "tools")


def _changed_files(repo: str) -> Optional[List[str]]:
    """Repo-relative .py files touched vs HEAD (worktree + index) plus
    untracked — the pre-commit scope.  None when git itself failed: the
    caller must fail LOUD (exit 2), because 'git broke' reported as
    'nothing changed' would let a violating commit through the gate."""
    out: List[str] = []
    for args in (
        ["git", "diff", "--name-only", "HEAD", "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            r = subprocess.run(
                args, cwd=repo, capture_output=True, text=True, timeout=20
            )
        except Exception:
            return None
        if r.returncode != 0:
            return None
        out.extend(line.strip() for line in r.stdout.splitlines())
    return sorted({p for p in out if p.endswith(".py")})


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "paths", nargs="*", default=list(DEFAULT_PATHS),
        help="files/directories to lint (default: elasticdl_tpu tools)",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only files changed vs HEAD (plus untracked) under the "
        "given paths — pre-commit fast mode; project-wide passes still "
        "see the full file set",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as a JSON array",
    )
    parser.add_argument(
        "--artifact", nargs="?", const="", default=None, metavar="PATH",
        help="write a LINT artifact (findings count + per-rule counts + "
        "code_rev) via tools/artifact.py; optional explicit path, else "
        "artifacts/LINT_r07.json (env override LINT_OUT)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)

    from elasticdl_tpu.analysis import all_passes
    from elasticdl_tpu.analysis.core import iter_file_paths, run_lint

    passes = all_passes()
    if args.list_rules:
        for p in passes:
            print(f"{p.name:18s} {p.description}")
        print(f"{'waiver-syntax':18s} waivers must be "
              "'# graftlint: allow[<rule>] <reason>' with a known rule")
        return 0

    # Resolve paths relative to the repo root so display paths (and the
    # import-hygiene module names derived from them) are stable no matter
    # where the tool is invoked from.
    roots = [
        p if os.path.isabs(p) else os.path.join(_REPO_ROOT, p)
        for p in args.paths
    ]
    missing = [p for p in roots if not os.path.exists(p)]
    if missing:
        print(f"graftlint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    all_files = iter_file_paths(roots)
    only_paths = None
    if args.changed:
        changed = _changed_files(_REPO_ROOT)
        if changed is None:
            print(
                "graftlint: --changed could not read the git state; "
                "refusing to report a clean pass (run without --changed)",
                file=sys.stderr,
            )
            return 2
        changed_set = set(changed)
        only_paths = {
            os.path.relpath(fp, _REPO_ROOT)
            for fp in all_files
            if os.path.relpath(fp, _REPO_ROOT) in changed_set
        }
    findings = run_lint(
        roots, passes, rel_to=_REPO_ROOT, only_paths=only_paths
    )

    if args.as_json:
        print(json.dumps(
            [f.__dict__ for f in findings], indent=1, sort_keys=True
        ))
    else:
        for f in findings:
            print(f.render())
        scope = (
            f"{len(only_paths)} changed" if only_paths is not None
            else str(len(all_files))
        )
        print(
            f"graftlint: {len(findings)} finding(s) across {scope} file(s)",
            file=sys.stderr,
        )

    if args.artifact is not None:
        from tools.artifact import code_rev, write_artifact

        by_rule = Counter(f.rule for f in findings)
        write_artifact(
            {
                "findings": len(findings),
                "by_rule": dict(sorted(by_rule.items())),
                "files_scanned": len(all_files),
                "changed_only": bool(args.changed),
                "rules": sorted(p.name for p in passes),
                "code_rev": code_rev(),
            },
            "LINT_r07.json",
            env_var="LINT_OUT",
            path=args.artifact or None,
        )

    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
