"""chaos_bench — recovery time and goodput-under-churn as numbers of record.

ROADMAP item 3 wants tail tolerance stamped "as first-class perf numbers
alongside examples/sec".  This tool drives REAL multi-worker jobs through
the full master stack (Master -> PodManager -> ProcessPodBackend worker
subprocesses, warm standby on) under a graftchaos fault plan
(chaos/inject.py), and stamps ``artifacts/CHAOS_r13.json`` with:

- **recovery_time_ms**, decomposed over the master-clock splice timeline:
  ``elastic:splice`` stage=detect (the pod watcher saw the death) ->
  stage=adopt (a warm spare took the identity) -> ``elastic:reformed``
  (every member confirmed the new membership) -> the first successful
  ``lease:report`` after the fault (trained-again).  All four instants are
  emitted IN the master process, so no cross-process clock alignment can
  blur the decomposition.
- **goodput-under-churn**: examples/sec of the faulted run divided by the
  fault-free baseline at identical shape (same data, fleet, pipeline).
- **skip accounting**: the dispatcher's per-task skip counts and the
  servicer's per-rank deadline skips (--gang_deadline_ms).
- **zero-double-train**: done == expected tasks, zero rejected late
  SUCCESS reports (TaskDispatcher's duplicate_done counter), zero
  abandoned — the explicit exactly-once check, not an assumption.

Fleets (CPU harness — chaos is a control-plane property; the fault paths
exercised are identical on chip).  Each faulted fleet has a SHAPE-MATCHED
baseline (same data, model, workers, pipeline) as its goodput
denominator:

    baseline_pool / kill    2 independent (non-gang) deepfm workers
                            sharing the dispatcher; chaos kills one
                            mid-job and the warm standby splices the
                            replacement in (worker= addressing, so the
                            relaunched incarnation cannot re-kill
                            itself).  Both share one compile cache — the
                            baseline warms it, so the kill fleet's churn
                            wall measures recovery, not XLA.
    baseline_gang / stall   a 2-rank mnist lockstep gang; chaos stalls
                            worker 0 mid-job far past --gang_deadline_ms
                            AND blacks out its RPCs from the same step.
                            The boundary skips the straggler (gang:skip,
                            skip-accounted requeue, eviction); the
                            blackout means the evicted rank can neither
                            heartbeat its way back into membership nor
                            death-push itself into a RESTART relaunch,
                            and max_worker_relaunch=0 keeps its slot
                            down — so the survivor death-pushes out of
                            the wedged collective, settles past the
                            15 s gate into a world of ONE, and drains
                            the log solo.

The stall fleet's shape is deliberate: on this box a RE-FORMED 2-process
jax.distributed world dies of timing-sensitive heap corruption in
jaxlib/gloo at its first post-(re)compile collective dispatch (the @slow
test_multihost reform churn noted since CHANGES r8 — model-independent,
worst with deepfm's embedding host paths), so any design where recovery
means "form a second multi-process world" would stamp that box flake as
recovery time.  Skip-then-degrade-to-solo needs NO second gang: initial
2-rank mnist formation is the reliably-passing tier-1 configuration, and
everything after the skip is single-process.  Gang fleets use PRIVATE
per-fleet compile caches (no world ever starts on another world's cached
collective executables — the corruption's most reliable trigger);
pool fleets share one.  Exactly-once accounting holds through all of it
either way (that is the point).

Usage:
    python tools/chaos_bench.py [--workers 2] [--tasks 8] [--fleets ...]
    python tools/chaos_bench.py --smoke     # tiny 1-worker kill+recover
                                            # (bench_all --chaos-smoke)
    python tools/chaos_bench.py --masterfail        # r18 master-kill
                                            # fleet -> MASTERFAIL_r18.json
    python tools/chaos_bench.py --masterfail-smoke  # 1-worker master
                                            # kill+restart CI check
                                            # (bench_all --masterfail-smoke)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# FORCE cpu (the multiworker_bench stance): this harness must never aim a
# chaos run at a possibly-hung tunneled chip, and the master is jax-free.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ARTIFACT_NAME = "CHAOS_r13.json"

#: r18 master-kill survivability artifact (``--masterfail``): the master
#: process is chaos-killed mid-job (kill:target=master,step=N fires in
#: the servicer AFTER a report is applied+journaled), the worker fleet
#: rides the outage out on the proxy reconnect WITHOUT relaunch, a fresh
#: master process replays the journal, adopts the orphan pods, and the
#: job completes exactly-once.  Decomposition on wall-anchored trace
#: instants: kill -> restart spawn -> master:replay -> worker:reconnect
#: -> first post-restart lease:handout.
MASTERFAIL_ARTIFACT = "MASTERFAIL_r18.json"

_MB = 1024
_MB_PER_TASK = 2
_RECORDS_PER_TASK = _MB * _MB_PER_TASK

#: Hard wall bound per fleet: a wedged chaos run must fail loud, not hang
#: the battery (the whole point of the subsystem is bounded tails).
FLEET_TIMEOUT_S = 900.0


def _splice_timeline(events: List[dict]) -> dict:
    """Recovery decomposition from the master-clock instants (see module
    docstring).  Returns {} when no fault was detected (baseline)."""
    detect = adopt = reformed = skip = skip_trained = None
    survivor_trained = replacement_trained = None
    relaunch = None
    for e in sorted(events, key=lambda e: e.get("ts") or 0):
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        name = e.get("name")
        args = e.get("args") or {}
        if name == "gang:skip" and skip is None:
            skip = ts
        elif skip is not None and skip_trained is None and (
            name == "lease:report" and args.get("success")
        ):
            skip_trained = ts
        if name == "elastic:splice" and args.get("stage") == "detect":
            if detect is None:
                detect = ts
                relaunch = args.get("relaunch")
        elif detect is not None and adopt is None and (
            name == "elastic:splice" and args.get("stage") == "adopt"
        ):
            adopt = ts
        elif detect is not None and reformed is None and (
            name == "elastic:reformed"
        ):
            reformed = ts
        elif detect is not None and (
            name == "lease:report" and args.get("success")
        ):
            # Two distinct recoveries: the POOL keeps making progress (any
            # worker's next success — continuity), and the LOST CAPACITY
            # comes back (the spliced replacement's first success — the
            # recovery_time the artifact headlines).
            if survivor_trained is None:
                survivor_trained = ts
            if replacement_trained is None and relaunch and (
                args.get("worker") == relaunch
            ):
                replacement_trained = ts
    if skip is not None and (detect is None or skip <= detect):
        # Deadline-skip fleets: the straggler is EVICTED, never a FAILED
        # pod, so the timeline anchors on the gang:skip instant.  The
        # anchor is whichever fired FIRST — a skip fleet's severed
        # straggler is killed at teardown, and that post-job FAILED
        # detect is noise, not recovery (stamped as late_detect_ms so
        # the artifact shows it was seen and excluded).
        out = {"detected": detect is not None, "skipped": True}
        if skip_trained is not None:
            out["skip_to_trained_ms"] = round((skip_trained - skip) / 1e3, 1)
        if detect is not None:
            out["late_detect_ms"] = round((detect - skip) / 1e3, 1)
        return out
    if detect is None:
        return {}
    out = {"detected": True}
    if adopt is not None:
        out["detect_to_adopt_ms"] = round((adopt - detect) / 1e3, 1)
    if reformed is not None:
        out["detect_to_reformed_ms"] = round((reformed - detect) / 1e3, 1)
        if adopt is not None:
            out["adopt_to_reformed_ms"] = round((reformed - adopt) / 1e3, 1)
    if survivor_trained is not None:
        out["survivor_trained_ms"] = round(
            (survivor_trained - detect) / 1e3, 1
        )
    if replacement_trained is not None:
        out["recovery_time_ms"] = round(
            (replacement_trained - detect) / 1e3, 1
        )
        if reformed is not None:
            out["reformed_to_trained_ms"] = round(
                (replacement_trained - reformed) / 1e3, 1
            )
    return out


def _chaos_event_counts(dump: dict, pod_log_dir: str = "") -> Dict[str, int]:
    """The injection audit — a chaos artifact whose faults never fired
    measures nothing.  Two channels: chaos:*/gang:skip instants across
    every shipped trace buffer, and ``[graftchaos]`` stderr lines in the
    pod logs (``log:<kind>`` keys) — the only evidence a SEVERED process
    leaves: a kill's ring dies with it, and a drop_rpc blackout cuts the
    heartbeat channel its ring would have shipped over."""
    counts: Dict[str, int] = {}
    buffers = [dump.get("master_events") or []]
    for proc in (dump.get("processes") or {}).values():
        buffers.append(proc.get("events") or [])
    for events in buffers:
        for e in events:
            name = e.get("name", "")
            if name.startswith("chaos:") or name == "gang:skip":
                counts[name] = counts.get(name, 0) + 1
    if pod_log_dir and os.path.isdir(pod_log_dir):
        for fn in os.listdir(pod_log_dir):
            if not fn.endswith(".log"):
                continue
            try:
                with open(os.path.join(pod_log_dir, fn)) as f:
                    for line in f:
                        if line.startswith("[graftchaos] "):
                            kind = line.split()[1]
                            key = f"log:{kind}"
                            counts[key] = counts.get(key, 0) + 1
            except OSError:
                pass
    return counts


def _scrape_loop(address: str, stop, box: dict) -> None:
    """Poll ``address``'s /metrics once a second until ``stop``; bank the
    newest parsed snapshot (scalar edl_* families flattened to
    name{labels} -> value) plus ok/failed tallies.  Runs while the fleet
    is faulted ON PURPOSE: a scrape that only works on a healthy job
    proves nothing."""
    from tools.watch_job import fetch

    while not stop.is_set():
        try:
            families = fetch(address, timeout_s=2.0)
        except Exception as e:  # noqa: BLE001 — tallied; the job goes on
            box["scrapes_failed"] = box.get("scrapes_failed", 0) + 1
            box["last_error"] = f"{type(e).__name__}: {e}"
        else:
            flat = {}
            for name, fam in sorted(families.items()):
                if not name.startswith("edl_") or fam.get("type") == "histogram":
                    continue
                for s in fam["samples"]:
                    labels = ",".join(
                        f"{k}={v}" for k, v in sorted(s["labels"].items())
                    )
                    flat[f"{name}{{{labels}}}" if labels else name] = s["value"]
            box["snapshot"] = flat
            box["scrapes_ok"] = box.get("scrapes_ok", 0) + 1
        stop.wait(1.0)


def run_fleet(
    n_workers: int,
    n_tasks: int,
    tmp: str,
    log,
    label: str,
    chaos: str = "",
    warm_standby: bool = False,
    gang_deadline_ms: float = 0.0,
    model: str = "deepfm",
    multihost: bool = False,
    timeout_s: float = FLEET_TIMEOUT_S,
    cache: str = "shared",
    max_relaunch: int = 8,
) -> dict:
    """One job through the full master stack; returns goodput + accounting
    + the splice timeline (and leaves the raw dump beside the tmp data)."""
    from elasticdl_tpu.common import trace
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.common.platform import free_port
    from elasticdl_tpu.data.synthetic import generate, synthetic_criteo
    from elasticdl_tpu.master.main import Master

    if model == "deepfm":
        path = os.path.join(tmp, "chaos_criteo.rio")
        if not os.path.exists(path):
            synthetic_criteo(
                path, _RECORDS_PER_TASK * n_tasks, seed=13,
                container="recordio",
            )
        model_def = "deepfm.model_spec"
        model_params = (
            "buckets_per_feature=4096;embedding_dim=4;"
            "hidden=[64,64];compute_dtype=float32"
        )
        mb, mb_per_task = _MB, _MB_PER_TASK
    else:  # mnist: the smoke's cheap workload
        mb, mb_per_task = 16, 2
        path = os.path.join(tmp, "chaos_mnist.rio")
        if not os.path.exists(path):
            generate("mnist", path, mb * mb_per_task * n_tasks)
        model_def = "mnist.model_spec"
        model_params = "compute_dtype=float32"

    # Compile-cache policy (workers inherit the env).  Pool fleets SHARE
    # one cache — the baseline warms it, so the kill fleet's churn wall
    # measures recovery, not XLA.  Gang fleets each get a PRIVATE cache
    # (cache="fleet"): on this box a multi-process world that LOADS a
    # cached collective executable dies of heap corruption at its first
    # dispatch (the warm-cache face of the pre-existing CHANGES r8
    # multi-process flake), so no gang world may ever start on another
    # world's cache — each compiles its collectives exactly once, cold,
    # shape-matched with its baseline.
    if os.environ.get("CHAOS_NO_CACHE"):
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    else:
        sub = "jax_cache" if cache == "shared" else f"jax_cache_{label}"
        os.environ["JAX_COMPILATION_CACHE_DIR"] = os.path.join(tmp, sub)
    config = JobConfig(
        job_name=f"chaos-{label}",
        model_def=model_def,
        model_params=model_params,
        distribution_strategy="AllReduce",
        training_data=path,
        minibatch_size=mb,
        num_minibatches_per_task=mb_per_task,
        num_epochs=1,
        num_workers=n_workers,
        multihost=multihost and n_workers > 1,
        coordinator_port=free_port(),
        distributed_heartbeat_timeout_s=100.0,
        # Relaunch headroom: an injected kill costs budget BY DESIGN, and
        # on this box a gang fleet's post-fault REFORMATION churns through
        # the known jaxlib segfault (module docstring) before converging
        # or degrading the world — the budget must outlast that.
        max_worker_relaunch=max_relaunch,
        warm_worker_standby=warm_standby,
        standby_pool=1,
        trace=True,
        chaos=chaos,
        gang_deadline_ms=gang_deadline_ms,
        checkpoint_steps=0,
        pod_log_dir=os.path.join(tmp, f"pods-{label}"),
        # graftgauge (r14): every process of the fleet serves /metrics on
        # an ephemeral port; the bench scrapes the MASTER's endpoint
        # mid-run (below) — the fleet-aggregated view must answer while a
        # fault is in flight, which is the whole claim.
        gauge_port=0,
    )
    # Isolate each fleet's trace window: the process recorder is global,
    # and a previous fleet's instants must not leak into this timeline.
    trace.configure(enabled=True)
    trace.default().clear()

    master = Master(config)
    result_box: dict = {}

    def _run():
        try:
            result_box["status"] = master.run()
        except Exception as e:  # surfaced after the join below
            result_box["error"] = e

    t0 = time.perf_counter()
    runner = threading.Thread(target=_run, name=f"chaos-{label}", daemon=True)
    runner.start()
    # Live mid-run scrape (r14): poll the master's /metrics every second
    # WHILE the fleet runs (including while a stall has the gang wedged —
    # the scrape server's daemon threads are the availability claim) and
    # keep the newest snapshot for the artifact.
    scrape_box: dict = {}
    scrape_stop = threading.Event()
    scraper = None
    if master.metrics_server is not None:
        scraper = threading.Thread(
            target=_scrape_loop,
            args=(master.metrics_server.address, scrape_stop, scrape_box),
            name=f"chaos-scrape-{label}", daemon=True,
        )
        scraper.start()
    runner.join(timeout=timeout_s)
    scrape_stop.set()
    if scraper is not None:
        scraper.join(timeout=5.0)
    wall = time.perf_counter() - t0
    if runner.is_alive():
        # The watchdog IS part of the experiment: a chaos run that wedges
        # has disproven the tolerance claim — tear down and fail loud.
        master.shutdown()
        runner.join(timeout=30)
        raise RuntimeError(
            f"chaos fleet {label!r} still running after {timeout_s:.0f}s "
            f"(workers={n_workers}, chaos={chaos!r})"
        )
    if "error" in result_box:
        raise RuntimeError(
            f"chaos fleet {label!r} failed: {result_box['error']}"
        ) from result_box["error"]
    status = result_box["status"]
    # The servicer outlives run() in-process: its banked worker buffers +
    # the master's own recorder are the timeline source.
    dump = master.servicer.DumpTrace({})
    with open(os.path.join(tmp, f"dump-{label}.json"), "w") as f:
        json.dump(dump, f)

    done = int(status.get("done", 0))
    eps = done * mb * mb_per_task / wall if wall > 0 else 0.0
    out = {
        "label": label,
        "workers": n_workers,
        "group_mode": bool(multihost and n_workers > 1),
        "chaos": chaos,
        "gang_deadline_ms": gang_deadline_ms,
        "warm_standby": warm_standby,
        "wall_s": round(wall, 2),
        "tasks_done": done,
        "tasks_expected": n_tasks,
        "examples_per_sec": round(eps, 1),
        "abandoned": int(status.get("abandoned", 0)),
        "skipped": int(status.get("skipped", 0)),
        "skip_counts": status.get("skip_counts") or {},
        "skipped_ranks": status.get("skipped_ranks") or {},
        "duplicate_done": int(status.get("duplicate_done", 0)),
        "chaos_events": _chaos_event_counts(
            dump, os.path.join(tmp, f"pods-{label}")
        ),
        # The newest mid-run scrape of the master's live endpoint: proof
        # the fleet view answered DURING the injected faults.
        "live_metrics": {
            "endpoint": (
                master.metrics_server.address
                if master.metrics_server is not None else None
            ),
            "scrapes_ok": scrape_box.get("scrapes_ok", 0),
            "scrapes_failed": scrape_box.get("scrapes_failed", 0),
            **(
                {"last_error": scrape_box["last_error"]}
                if "last_error" in scrape_box else {}
            ),
            "snapshot": scrape_box.get("snapshot") or {},
        },
        "recovery": _splice_timeline(dump.get("master_events") or []),
        # The explicit exactly-once verdict the artifact is judged on.
        "zero_double_train": (
            done == n_tasks
            and int(status.get("duplicate_done", 0)) == 0
            and int(status.get("abandoned", 0)) == 0
        ),
    }
    log(f"fleet {label}: {json.dumps(out)}")
    return out


def _masterfail_config(
    tmp: str, label: str, port: int, n_workers: int, n_tasks: int,
    kill_after_done: int,
):
    """One masterfail fleet's JobConfig: mnist over a REAL gRPC master on
    a FIXED port (the restarted master must answer at the address the
    riding-through workers already hold), process-backend workers, the
    journal + pod registry in checkpoint_dir, and — when kill_after_done
    > 0 — the master-kill fault armed."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.synthetic import generate

    mb, mb_per_task = 16, 2
    path = os.path.join(tmp, "masterfail_mnist.rio")
    if not os.path.exists(path):
        generate("mnist", path, mb * mb_per_task * n_tasks)
    return JobConfig(
        job_name=f"mfail-{label}",
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        distribution_strategy="AllReduce",
        training_data=path,
        minibatch_size=mb,
        num_minibatches_per_task=mb_per_task,
        num_epochs=1,
        num_workers=n_workers,
        master_addr=f"localhost:{port}",
        master_port=port,
        master_outage_tolerance_s=120.0,
        checkpoint_dir=os.path.join(tmp, f"ckpt-{label}"),
        checkpoint_steps=2,
        max_worker_relaunch=3,
        trace=True,
        chaos=(
            f"kill:target=master,step={kill_after_done}"
            if kill_after_done > 0 else ""
        ),
        pod_log_dir=os.path.join(tmp, f"pods-{label}"),
        gauge_port=0,
    )


def _spawn_master(config, tmp: str, label: str, generation: int):
    """One master process over the config bus (python -m master.main),
    stdout+stderr captured per generation."""
    import subprocess

    env = dict(os.environ)
    env.update(config.to_env())
    log_path = os.path.join(tmp, f"master-{label}-g{generation}.log")
    f = open(log_path, "w")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.master.main"],
            env=env, stdout=f, stderr=subprocess.STDOUT,
        )
    finally:
        f.close()
    return proc, log_path


def _offline_replay_counts(config) -> dict:
    """Replay the fleet's journal IN THIS PROCESS (jax-free) — the
    bench-side proof that the WAL alone reconstructs the dispatcher."""
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.master import journal as journal_mod

    reader = create_data_reader(
        config.training_data, config.parsed_data_reader_params()
    )
    shards = reader.create_shards(
        config.minibatch_size * config.num_minibatches_per_task
    )
    rr = journal_mod.replay(
        os.path.join(config.checkpoint_dir, journal_mod.JOURNAL_FILENAME),
        shards,
        num_epochs=config.num_epochs,
        task_type="training",
        task_timeout_s=config.task_timeout_s,
        task_skip_budget=config.gang_skip_budget,
    )
    counts = rr.dispatcher.counts()
    counts["replayed_events"] = rr.events_applied
    counts["restarts"] = rr.restarts
    counts["torn_tail"] = rr.torn_tail
    return counts


def _masterfail_timeline(dump: dict, t_kill: float, t_spawn2: float) -> dict:
    """Decompose outage -> restart -> replay -> reconcile -> first task on
    the wall-anchored trace clocks (master:replay and lease:handout are
    master-2 instants; worker:reconnect ships from the worker with its
    RTT-midpoint offset applied when known)."""
    replay_ts = replay_ms = first_task_ts = None
    for e in dump.get("master_events") or []:
        ts, name = e.get("ts"), e.get("name")
        if not isinstance(ts, (int, float)):
            continue
        if name == "master:replay" and replay_ts is None:
            replay_ts = ts
            replay_ms = (e.get("args") or {}).get("replay_ms")
        elif (
            name == "lease:handout" and replay_ts is not None
            and first_task_ts is None and ts >= replay_ts
        ):
            first_task_ts = ts
    reconnect_ts = None
    for proc in (dump.get("processes") or {}).values():
        offset = proc.get("clock_offset_us") or 0.0
        for e in proc.get("events") or []:
            if e.get("name") == "worker:reconnect" and isinstance(
                e.get("ts"), (int, float)
            ):
                ts = e["ts"] + offset
                if reconnect_ts is None or ts < reconnect_ts:
                    reconnect_ts = ts
    out = {}
    kill_us, spawn_us = t_kill * 1e6, t_spawn2 * 1e6
    out["outage_hold_ms"] = round((spawn_us - kill_us) / 1e3, 1)
    if replay_ts is not None:
        out["spawn_to_replay_ms"] = round((replay_ts - spawn_us) / 1e3, 1)
        out["replay_ms"] = replay_ms
    if reconnect_ts is not None and replay_ts is not None:
        out["replay_to_reconnect_ms"] = round(
            (reconnect_ts - replay_ts) / 1e3, 1
        )
    if first_task_ts is not None:
        out["replay_to_first_task_ms"] = round(
            (first_task_ts - replay_ts) / 1e3, 1
        )
        out["recovery_ms"] = round((first_task_ts - kill_us) / 1e3, 1)
    return out


def run_masterfail_fleet(
    n_workers: int,
    n_tasks: int,
    tmp: str,
    log,
    label: str,
    kill_after_done: int = 0,
    outage_hold_s: float = 2.0,
    timeout_s: float = FLEET_TIMEOUT_S,
) -> dict:
    """One master-kill fleet: master in a SUBPROCESS (it must die for
    real), workers spawned by ITS PodManager (process backend) so the
    restart exercises the pod reattach registry, and this bench process
    watching from outside over the same gRPC surface the workers use.
    ``kill_after_done`` = 0 runs the fault-free baseline."""
    import json as _json

    from elasticdl_tpu.chaos.inject import CHAOS_KILL_EXIT_CODE
    from elasticdl_tpu.common.platform import free_port
    from elasticdl_tpu.common.rpc import JsonRpcClient

    os.environ["JAX_COMPILATION_CACHE_DIR"] = os.path.join(tmp, "jax_cache")
    port = free_port()
    config = _masterfail_config(
        tmp, label, port, n_workers, n_tasks, kill_after_done
    )
    addr = f"localhost:{port}"
    from elasticdl_tpu.master.pod_manager import REGISTRY_FILENAME

    registry_path = os.path.join(config.checkpoint_dir, REGISTRY_FILENAME)

    t0 = time.perf_counter()
    master1, m1_log = _spawn_master(config, tmp, label, 1)
    client = JsonRpcClient(addr)
    client.wait_ready(90.0)

    def _poll_status(cli, box: dict) -> None:
        try:
            box["status"] = cli.call("JobStatus", {}, timeout_s=5.0)
        except Exception:
            pass

    def _poll_dump(cli, box: dict) -> None:
        try:
            box["dump"] = cli.call("DumpTrace", {}, timeout_s=10.0)
        except Exception:
            pass

    box: Dict[str, dict] = {}
    deadline = time.time() + timeout_s
    worker_pids: Dict[str, int] = {}
    while master1.poll() is None:
        if time.time() > deadline:
            master1.kill()
            raise RuntimeError(f"masterfail fleet {label}: master 1 wedged")
        _poll_status(client, box)
        if not worker_pids and os.path.exists(registry_path):
            from elasticdl_tpu.common import durable

            reg = durable.read_json_tolerant(registry_path, default={})
            try:
                worker_pids = {
                    v["name"]: v["pid"] for v in reg["slots"].values()
                }
            except (KeyError, TypeError, AttributeError):
                pass
        time.sleep(0.15)
    rc1 = master1.returncode
    t_kill = time.time()
    pre_kill_status = dict(box.get("status") or {})

    if kill_after_done <= 0:
        # Baseline: one master generation to completion.
        wall = time.perf_counter() - t0
        final = _offline_replay_counts(config)
        eps = (
            final["done"] * config.minibatch_size
            * config.num_minibatches_per_task / wall
            if wall > 0 else 0.0
        )
        out = {
            "label": label, "workers": n_workers, "wall_s": round(wall, 2),
            "tasks_done": final["done"], "tasks_expected": n_tasks,
            "examples_per_sec": round(eps, 1),
            "duplicate_done": final["duplicate_done"],
            "abandoned": final["abandoned"],
            "master_rc": rc1,
        }
        log(f"fleet {label}: {json.dumps(out)}")
        return out

    if rc1 != CHAOS_KILL_EXIT_CODE:
        raise RuntimeError(
            f"masterfail fleet {label}: master 1 exited rc={rc1}, expected "
            f"the chaos kill ({CHAOS_KILL_EXIT_CODE}) — see {m1_log}"
        )
    log(
        f"fleet {label}: master killed (rc={rc1}) after "
        f"done={pre_kill_status.get('done')} — replaying journal offline"
    )

    # Worker ride-through, part 1: every registered pod is still alive
    # with the master DOWN (they are riding the proxy backoff).
    orphans_alive = {
        name: _pid_alive(pid) for name, pid in worker_pids.items()
    }
    # Offline journal replay IN THE OUTAGE WINDOW: the WAL alone must
    # reconstruct the dispatcher the pre-kill JobStatus described.  The
    # kill fires at the first report whose done count reaches
    # kill_after_done (step= matches >=), but concurrent report handlers
    # can journal past it before the exiting thread's os._exit lands, and
    # the bench's last pre-kill poll can lag by in-flight reports — so
    # the invariant is a band, not equality: kill step <= replayed done
    # <= kill step + (workers - 1) in-flight handlers, and never behind
    # the last thing JobStatus showed us.
    replayed = _offline_replay_counts(config)
    replay_matches = (
        kill_after_done
        <= replayed["done"]
        <= kill_after_done + max(0, n_workers - 1)
        and replayed["done"] >= int(pre_kill_status.get("done", 0))
    )

    time.sleep(outage_hold_s)
    config2 = type(config).from_json(config.to_json())
    config2.chaos = ""  # generation 2 must not re-kill itself
    master2, m2_log = _spawn_master(config2, tmp, label, 2)
    t_spawn2 = time.time()
    client2 = JsonRpcClient(addr)
    # Readiness-wait BEFORE polling: fail-fast probes against the booting
    # master would park this fresh channel in gRPC's no-redial
    # TRANSIENT_FAILURE state (the exact pathology the worker proxy's
    # post-failure probe exists for) and every later poll would lie.
    client2.wait_ready(90.0)
    box2: Dict[str, dict] = {}
    last_dump = 0.0
    while master2.poll() is None:
        if time.time() > deadline:
            master2.kill()
            raise RuntimeError(f"masterfail fleet {label}: master 2 wedged")
        # client2, never the gen-1 channel: a poll that raced the kill
        # can park THAT channel in gRPC's no-redial TRANSIENT_FAILURE
        # state, and every later poll through it would silently fail.
        _poll_status(client2, box2)
        if time.monotonic() - last_dump > 1.0:
            _poll_dump(client2, box2)
            last_dump = time.monotonic()
        time.sleep(0.15)
    wall = time.perf_counter() - t0
    rc2 = master2.returncode
    if rc2 != 0:
        raise RuntimeError(
            f"masterfail fleet {label}: master 2 exited rc={rc2} — see "
            f"{m2_log}"
        )
    dump = box2.get("dump") or {}
    with open(os.path.join(tmp, f"dump-{label}.json"), "w") as f:
        _json.dump(dump, f)

    # Worker ride-through, part 2: the SAME worker processes finished the
    # job — no relaunch pod logs (-rN incarnations) ever appeared.
    relaunch_logs = sorted(
        fn for fn in os.listdir(config.pod_log_dir)
        if "-r" in fn and fn.endswith(".log")
    )
    final = _offline_replay_counts(config)
    status2 = box2.get("status") or {}
    eps = (
        final["done"] * config.minibatch_size
        * config.num_minibatches_per_task / wall
        if wall > 0 else 0.0
    )
    timeline = _masterfail_timeline(dump, t_kill, t_spawn2)
    out = {
        "label": label,
        "workers": n_workers,
        "kill_after_done": kill_after_done,
        "outage_hold_s": outage_hold_s,
        "wall_s": round(wall, 2),
        "tasks_done": final["done"],
        "tasks_expected": n_tasks,
        "examples_per_sec": round(eps, 1),
        "duplicate_done": final["duplicate_done"],
        "stale_reports": int(status2.get("stale_reports", 0)),
        "abandoned": final["abandoned"],
        "master_rcs": [rc1, rc2],
        "pre_kill_status": {
            k: pre_kill_status.get(k) for k in ("done", "doing", "todo")
        },
        "replay_at_kill": {
            k: replayed[k]
            for k in ("done", "doing", "todo", "replayed_events")
        },
        "replay_matches_prekill": replay_matches,
        "journal": status2.get("journal") or {},
        "worker_ride_through": {
            "pids": worker_pids,
            "alive_during_outage": orphans_alive,
            "relaunch_logs": relaunch_logs,
            "no_relaunch": not relaunch_logs and all(orphans_alive.values()),
        },
        "recovery": timeline,
        "zero_double_train": (
            final["done"] == n_tasks
            and final["duplicate_done"] == 0
            and final["abandoned"] == 0
        ),
    }
    log(f"fleet {label}: {json.dumps(out)}")
    return out


def _pid_alive(pid: int) -> bool:
    # The one shared probe (zombie- and reuse-aware): pod_manager owns it.
    from elasticdl_tpu.master.pod_manager import pid_alive

    return pid_alive(pid)


def run_masterfail_smoke(log, tmp: Optional[str] = None) -> dict:
    """Tiny master-kill+restart (bench_all --masterfail-smoke): ONE mnist
    worker, master chaos-killed once its dispatcher counts 2 done tasks,
    restarted ~2 s later — asserts the worker rode through WITHOUT
    relaunch, the journal replayed (> 0 events), and nothing trained
    twice."""
    import tempfile

    tmp = tmp or tempfile.mkdtemp(prefix="masterfail_smoke_")
    result = run_masterfail_fleet(
        1, 6, tmp, log, "smoke", kill_after_done=2, timeout_s=600.0
    )
    problems = []
    if not result["zero_double_train"]:
        problems.append(
            f"exactly-once violated: done={result['tasks_done']}/"
            f"{result['tasks_expected']}, duplicate_done="
            f"{result['duplicate_done']}, abandoned={result['abandoned']}"
        )
    if not result["worker_ride_through"]["no_relaunch"]:
        problems.append(
            "worker did not ride through: "
            f"{result['worker_ride_through']}"
        )
    if not int((result.get("journal") or {}).get("replayed_events", 0)):
        problems.append("master 2 reported no replayed journal events")
    if not result["replay_matches_prekill"]:
        problems.append(
            f"offline replay at kill time diverged: "
            f"{result['replay_at_kill']} vs pre-kill "
            f"{result['pre_kill_status']}"
        )
    result["problems"] = problems
    return result


def run_smoke(log, tmp: Optional[str] = None) -> dict:
    """Tiny kill+recover (bench_all --chaos-smoke): ONE mnist worker,
    killed by chaos at its third dispatched step, relaunched into a warm
    standby — asserts recovery completed and nothing trained twice.
    Small enough for tier-1-adjacent CI; the full gang fleets stay in the
    artifact run."""
    import tempfile

    tmp = tmp or tempfile.mkdtemp(prefix="chaos_smoke_")
    result = run_fleet(
        1, 6, tmp, log, "smoke", model="mnist",
        chaos="kill:worker=chaos-smoke-worker-0,step=3",
        warm_standby=True, timeout_s=600.0,
    )
    problems = []
    if not result["zero_double_train"]:
        problems.append(
            f"exactly-once violated: done={result['tasks_done']}/"
            f"{result['tasks_expected']}, duplicate_done="
            f"{result['duplicate_done']}, abandoned={result['abandoned']}"
        )
    if not result["recovery"].get("detected"):
        problems.append("no elastic:splice detect instant — the kill never fired?")
    if "recovery_time_ms" not in result["recovery"]:
        problems.append("no post-fault successful lease:report — never trained again")
    result["problems"] = problems
    return result


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument(
        "--tasks", type=int, default=24,
        help="pool-fleet tasks: long enough that the job OUTLASTS the "
        "spliced replacement's warmup, so recovery_time_ms (the "
        "replacement's first trained task) exists",
    )
    ap.add_argument(
        "--gang-tasks", type=int, default=8,
        help="gang-fleet tasks (the lockstep gang trains every task "
        "collectively, so its wall grows linearly with this)",
    )
    ap.add_argument(
        "--fleets", default="baseline_pool,kill,baseline_gang,stall",
        help="comma-separated subset of "
        "baseline_pool,kill,baseline_gang,stall",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="tiny 1-worker kill+recover; exit 1 on any failed check",
    )
    ap.add_argument(
        "--masterfail", action="store_true",
        help="run the r18 master-kill survivability fleet instead of the "
        "r13 families: chaos-kill the master subprocess mid-job, restart "
        "it, and stamp MASTERFAIL (journal replay + worker ride-through "
        "+ outage decomposition + exactly-once)",
    )
    ap.add_argument(
        "--masterfail-smoke", action="store_true",
        help="tiny 1-worker master kill+restart; exit 1 on any failed "
        "check (bench_all --masterfail-smoke)",
    )
    ap.add_argument(
        "--masterfail-tasks", type=int, default=12,
        help="masterfail fleet tasks: enough that the job OUTLASTS the "
        "restart and the post-replay master dispatches real work",
    )
    ap.add_argument(
        "--kill-after-done", type=int, default=4,
        help="kill the master once its dispatcher counts this many done "
        "tasks (fires AFTER that report is applied+journaled)",
    )
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    log = lambda m: print(f"[chaos] {m}", file=sys.stderr, flush=True)

    # code_rev at ENTRY (tools/artifact.ArtifactRun): this tool's run
    # writes dump files and the artifact itself — the measured code is the
    # tree as it stood when the run started.
    from tools.artifact import ArtifactRun

    run = ArtifactRun()

    if args.masterfail_smoke:
        result = run_masterfail_smoke(log)
        print(json.dumps(result), flush=True)
        if result["problems"]:
            for p in result["problems"]:
                log(f"FAIL: {p}")
            return 1
        log(
            "PASS: master kill+restart rode through — recovery "
            f"{result['recovery'].get('recovery_ms')} ms, "
            f"{result['journal'].get('replayed_events')} journal events "
            "replayed, zero double-train, no worker relaunch"
        )
        return 0

    if args.masterfail:
        import tempfile

        tmp = tempfile.mkdtemp(prefix="masterfail_bench_")
        n = args.workers
        baseline = run_masterfail_fleet(
            n, args.masterfail_tasks, tmp, log, "baseline"
        )
        faulted = run_masterfail_fleet(
            n, args.masterfail_tasks, tmp, log, "masterkill",
            kill_after_done=args.kill_after_done,
        )
        goodput = (
            round(
                faulted["examples_per_sec"] / baseline["examples_per_sec"], 3
            )
            if baseline["examples_per_sec"] else None
        )
        artifact = {
            "metric": "master_kill_survivability",
            "harness": (
                f"cpu ({os.cpu_count()} core host), master as a killable "
                "subprocess on a fixed port, ProcessPodBackend worker "
                "subprocesses ADOPTED across the restart via the pod "
                "registry, real gRPC throughout"
            ),
            "workers": n,
            "tasks": args.masterfail_tasks,
            "kill_after_done": args.kill_after_done,
            "fleets": {"baseline": baseline, "masterkill": faulted},
            "goodput_under_restart": goodput,
            "zero_double_train": {
                "baseline": baseline["tasks_done"]
                == args.masterfail_tasks
                and baseline["duplicate_done"] == 0,
                "masterkill": faulted["zero_double_train"],
            },
            "note": (
                "kill fires in the servicer AFTER a report is applied AND "
                "journaled (the hardest crash point for exactly-once: the "
                "worker's unanswered report retries through the proxy and "
                "must dedup by seq, never double-count).  recovery_ms = "
                "kill -> first post-replay lease:handout on wall-anchored "
                "trace clocks; replay/reconnect stages from the "
                "master:replay and worker:reconnect instants.  "
                "worker_ride_through proves the SAME worker pids finished "
                "the job (registry pids alive during the outage, zero "
                "relaunch pod logs).  replay_at_kill is this bench "
                "process replaying the WAL OFFLINE in the outage window "
                "and matching it against the last pre-kill JobStatus."
            ),
        }
        run.write(
            artifact, MASTERFAIL_ARTIFACT, env_var="MASTERFAIL_OUT",
            path=args.out or None, log=log,
        )
        print(json.dumps(artifact), flush=True)
        ok = (
            faulted["zero_double_train"]
            and faulted["worker_ride_through"]["no_relaunch"]
            and faulted["replay_matches_prekill"]
        )
        return 0 if ok else 1

    if args.smoke:
        result = run_smoke(log)
        print(json.dumps(result), flush=True)
        if result["problems"]:
            for p in result["problems"]:
                log(f"FAIL: {p}")
            return 1
        log(
            "PASS: recovery "
            f"{result['recovery'].get('recovery_time_ms')} ms, "
            "zero double-train"
        )
        return 0

    import tempfile

    tmp = tempfile.mkdtemp(prefix="chaos_bench_")
    n = args.workers
    wanted = [f.strip() for f in args.fleets.split(",") if f.strip()]
    fleets: Dict[str, dict] = {}
    fault_step = _MB_PER_TASK * 2 + 1
    if "baseline_pool" in wanted:
        fleets["baseline_pool"] = run_fleet(
            n, args.tasks, tmp, log, "baseline-pool"
        )
    if "kill" in wanted:
        # Kill the last worker at its SECOND task boundary (step >= 1
        # fires once the first task's steps are dispatched — a later
        # threshold can miss when the pool's dynamic sharding gives the
        # target few tasks); worker= addressing keeps the relaunched -rN
        # incarnation alive, and the warm standby splices the replacement
        # in (non-gang fleet: see module docstring).
        fleets["kill"] = run_fleet(
            n, args.tasks, tmp, log, "kill",
            chaos=f"kill:worker=chaos-kill-worker-{n - 1},step=1",
            warm_standby=True,
        )
    if "baseline_gang" in wanted:
        fleets["baseline_gang"] = run_fleet(
            n, args.gang_tasks, tmp, log, "baseline-gang", multihost=True,
            model="mnist", cache="fleet",
        )
    if "stall" in wanted:
        # Sever-and-solo-drain (module docstring): stall worker 0 at a
        # mid-job task boundary for longer than the whole run can last,
        # and from the SAME step black out every RPC its process sends
        # (count=0 = unlimited; the injector's step mirror gates rpc
        # faults on worker-loop progress).  The stall freezes its
        # lockstep gang_seq while the survivor's heartbeats keep feeding
        # the boundary, so the master skips + evicts it at the deadline;
        # the blackout then keeps the evicted rank OUT — its liveness
        # beats (which would revive the membership) and its death-push
        # (which would RESTART-relaunch it into a doomed 2-world reform)
        # both die client-side as ChaosRpcDropped, swallowed by the beat
        # thread's retry loop.  max_relaunch=0: an injected fault's slot
        # must stay down (the survivor's own death-push RESTART is
        # budget-free by design, so the budget only pins the straggler).
        # worker= addressing (not rank=): post-skip rank numbers
        # reshuffle, and a relaunched -rN incarnation must never
        # re-match.  The 10 s deadline is compile-safe for mnist: both
        # ranks block in their first jit compile at the SAME seq, so
        # neither lags the head while the other advances.
        fleets["stall"] = run_fleet(
            n, args.gang_tasks, tmp, log, "stall",
            chaos=(
                f"stall:worker=chaos-stall-worker-0,point=task,"
                f"step={fault_step},ms={int(FLEET_TIMEOUT_S * 1e3)},count=1;"
                f"drop_rpc:worker=chaos-stall-worker-0,"
                f"step={fault_step},count=0"
            ),
            gang_deadline_ms=10000.0,
            multihost=True,
            model="mnist", cache="fleet", max_relaunch=0,
        )

    artifact = {
        "metric": "chaos_recovery_and_goodput_under_churn",
        "harness": (
            f"cpu ({os.cpu_count()} core host), 1 fake device per worker "
            "process, real gRPC master + PodManager(process backend, warm "
            "standby), jax.distributed gang for multi-worker fleets"
        ),
        "workers": n,
        "pool_tasks": args.tasks,
        "gang_tasks": args.gang_tasks,
        "records_per_task": _RECORDS_PER_TASK,
        "fleets": fleets,
        "note": (
            "kill recovery decomposed over master-clock instants: "
            "elastic:splice detect -> adopt -> elastic:reformed -> the "
            "spliced replacement's first successful lease:report; stall "
            "recovery is gang:skip -> first successful lease:report "
            "after the survivor degrades to a solo world (no second "
            "multi-process world is ever formed: re-formed 2-process "
            "worlds hit this box's jaxlib/gloo heap corruption — the "
            "pre-existing CHANGES r8 @slow reform churn — so the bench "
            "measures the subsystem, not the flake).  "
            "goodput_under_churn = faulted examples/sec / its "
            "shape-matched baseline.  Pool fleets share one compile "
            "cache (the baseline warms it, so the kill fleet's churn "
            "wall measures recovery, not XLA); gang fleets use private "
            "per-fleet caches (no world ever loads another world's "
            "cached collective executables) and the stall fleet's "
            "post-skip wall includes the survivor's solo re-settle + "
            "one fresh solo compile, stamped as such"
        ),
    }
    ratios = {}
    for faulted, base in (("kill", "baseline_pool"), ("stall", "baseline_gang")):
        base_eps = (fleets.get(base) or {}).get("examples_per_sec") or 0
        if faulted in fleets and base_eps:
            ratios[faulted] = round(
                fleets[faulted]["examples_per_sec"] / base_eps, 3
            )
    if ratios:
        artifact["goodput_under_churn"] = ratios
    artifact["zero_double_train"] = {
        k: v["zero_double_train"] for k, v in fleets.items()
    }
    run.write(
        artifact, ARTIFACT_NAME, env_var="CHAOS_OUT",
        path=args.out or None, log=log,
    )
    print(json.dumps(artifact), flush=True)
    return 0 if all(artifact["zero_double_train"].values()) else 1


if __name__ == "__main__":
    sys.exit(main())
