"""Operational tools: benches, profilers, experiment harnesses.

Importable as a package so bench.py can reuse tools/bench_e2e.py; each tool
also runs standalone (``python tools/<name>.py``).
"""
