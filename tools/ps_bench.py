"""Measure PS service tier throughput: pull/push rows/sec over localhost
gRPC, single shard and sharded fleets.

The host tier exists for tables too large for HBM; its practical ceiling is
the RPC path (binary frames — ps/service.py), not the C++ store (the local
store sustains tens of millions of rows/sec).  This tool quantifies the gap
so capacity planning ("can the PS fleet feed a step every N ms?") has a
number, the same way docs/perf.md quantifies the mesh tier.

Usage: python tools/ps_bench.py [--rows 212992] [--dim 8] [--iters 20]
                                [--shards 1,2,4]
Prints one JSON line per fleet size:
  {"shards": n, "pull_rows_per_s": ..., "push_rows_per_s": ...,
   "pull_ms": ..., "push_ms": ...}

(212992 rows of dim 8 is exactly the flagship DeepFM step's id volume —
8192 examples x 26 features.)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from elasticdl_tpu.models.spec import HostTableIO
from elasticdl_tpu.ps.service import PSServer, RemoteEmbeddingStore


def bench_fleet(n_shards: int, rows: int, dim: int, iters: int) -> dict:
    io = HostTableIO(ids_fn=lambda b: b, dim=dim, optimizer="adagrad")
    servers = [
        PSServer({"t": io}, shard=s, num_shards=n_shards).start()
        for s in range(n_shards)
    ]
    store = RemoteEmbeddingStore("t", dim, [s.address for s in servers])
    store.wait_ready()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1 << 30, size=(rows,)).astype(np.int64)
    grads = rng.randn(rows, dim).astype(np.float32)
    try:
        store.pull(ids)  # materialize rows once (lazy init off the clock)
        t0 = time.perf_counter()
        for _ in range(iters):
            store.pull(ids)
        pull_s = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            store.push_grad(ids, grads)
        push_s = (time.perf_counter() - t0) / iters
    finally:
        store.close()
        for s in servers:
            s.stop()
    return {
        "shards": n_shards,
        "pull_rows_per_s": round(rows / pull_s),
        "push_rows_per_s": round(rows / push_s),
        "pull_ms": round(pull_s * 1e3, 2),
        "push_ms": round(push_s * 1e3, 2),
    }


def bench_concurrent(
    n_threads: int, rows: int, dim: int, iters: int, n_shards: int = 1
) -> dict:
    """N client threads pulling EXISTING rows from one fleet concurrently —
    the multi-worker steady state.  Scaling here is what the per-table
    reader-writer locks bought (pre-r4 a single shard mutex serialized the
    16-thread executor; VERDICT r3 Weak #3 / item 5)."""
    import threading

    io = HostTableIO(ids_fn=lambda b: b, dim=dim, optimizer="adagrad")
    servers = [
        PSServer({"t": io}, shard=s, num_shards=n_shards).start()
        for s in range(n_shards)
    ]
    addresses = [s.address for s in servers]
    rng = np.random.RandomState(0)
    per_thread = rows // n_threads
    id_sets = [
        rng.randint(0, 1 << 30, size=(per_thread,)).astype(np.int64)
        for _ in range(n_threads)
    ]
    warm = RemoteEmbeddingStore("t", dim, addresses)
    warm.wait_ready()
    for ids in id_sets:
        warm.pull(ids)  # materialize: measured pulls are read-only
    warm.close()

    def worker(ids, store, out, i):
        t0 = time.perf_counter()
        for _ in range(iters):
            store.pull(ids)
        out[i] = time.perf_counter() - t0

    stores = [RemoteEmbeddingStore("t", dim, addresses) for _ in range(n_threads)]
    times = [0.0] * n_threads
    threads = [
        threading.Thread(target=worker, args=(id_sets[i], stores[i], times, i))
        for i in range(n_threads)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    for s in stores:
        s.close()
    for s in servers:
        s.stop()
    total_rows = per_thread * n_threads * iters
    return {
        "mode": "concurrent_pull",
        "threads": n_threads,
        "shards": n_shards,
        "rows_per_s": round(total_rows / wall),
        "wall_s": round(wall, 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=8192 * 26)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--shards", default="1,2,4")
    ap.add_argument(
        "--concurrency", default="",
        help="comma list of client-thread counts; runs the concurrent-pull "
             "scaling mode instead of the fleet sweep (e.g. 1,2,4,8)",
    )
    args = ap.parse_args()
    if args.concurrency:
        for n in (int(s) for s in args.concurrency.split(",")):
            result = bench_concurrent(n, args.rows, args.dim, args.iters)
            print(json.dumps(result), flush=True)
            print(f"  {n} thread(s): {result['rows_per_s']:,} rows/s",
                  file=sys.stderr)
        return
    for n in (int(s) for s in args.shards.split(",")):
        result = bench_fleet(n, args.rows, args.dim, args.iters)
        print(json.dumps(result), flush=True)
        print(f"  {n} shard(s): pull {result['pull_ms']} ms, "
              f"push {result['push_ms']} ms for {args.rows} rows",
              file=sys.stderr)


if __name__ == "__main__":
    main()
