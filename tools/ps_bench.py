"""Measure PS service tier throughput: pull/push rows/sec over localhost
gRPC, single shard and sharded fleets.

The host tier exists for tables too large for HBM; its practical ceiling is
the RPC path (binary frames — ps/service.py), not the C++ store (the local
store sustains tens of millions of rows/sec).  This tool quantifies the gap
so capacity planning ("can the PS fleet feed a step every N ms?") has a
number, the same way docs/perf.md quantifies the mesh tier.

Usage: python tools/ps_bench.py [--rows 212992] [--dim 8] [--iters 20]
                                [--shards 1,2,4]
Prints one JSON line per fleet size:
  {"shards": n, "pull_rows_per_s": ..., "push_rows_per_s": ...,
   "pull_ms": ..., "push_ms": ..., "pull_p50_ms": ..., "pull_p99_ms": ...,
   "push_p50_ms": ..., "push_p99_ms": ...}
and stamps the sweep into ``artifacts/ps_bench_r10.json`` (env override
PS_BENCH_OUT).  Per-REQUEST p50/p99 — not just the aggregate mean — is the
number the serving tier plans against: its pull path rides this RPC, and a
latency SLO is a percentile, not an average (r10 satellite).

(212992 rows of dim 8 is exactly the flagship DeepFM step's id volume —
8192 examples x 26 features.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elasticdl_tpu.models.spec import HostTableIO
from elasticdl_tpu.ps.service import PSServer, RemoteEmbeddingStore


def _lat_stats(prefix: str, samples_s: list) -> dict:
    from tools.artifact import latency_stats

    # buckets=True: the shared histogram grid (tools/artifact.py) so the
    # artifact carries the tail SHAPE, not just p50/p99 points.
    return latency_stats(
        [s * 1e3 for s in samples_s], prefix=f"{prefix}_", buckets=True
    )


def bench_fleet(n_shards: int, rows: int, dim: int, iters: int) -> dict:
    io = HostTableIO(ids_fn=lambda b: b, dim=dim, optimizer="adagrad")
    servers = [
        PSServer({"t": io}, shard=s, num_shards=n_shards).start()
        for s in range(n_shards)
    ]
    store = RemoteEmbeddingStore("t", dim, [s.address for s in servers])
    store.wait_ready()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1 << 30, size=(rows,)).astype(np.int64)
    grads = rng.randn(rows, dim).astype(np.float32)
    pull_lat, push_lat = [], []
    try:
        store.pull(ids)  # materialize rows once (lazy init off the clock)
        for _ in range(iters):
            t0 = time.perf_counter()
            store.pull(ids)
            pull_lat.append(time.perf_counter() - t0)
        for _ in range(iters):
            t0 = time.perf_counter()
            store.push_grad(ids, grads)
            push_lat.append(time.perf_counter() - t0)
    finally:
        store.close()
        for s in servers:
            s.stop()
    pull_s, push_s = sum(pull_lat) / iters, sum(push_lat) / iters
    return {
        "shards": n_shards,
        "pull_rows_per_s": round(rows / pull_s),
        "push_rows_per_s": round(rows / push_s),
        "pull_ms": round(pull_s * 1e3, 2),
        "push_ms": round(push_s * 1e3, 2),
        **_lat_stats("pull", pull_lat),
        **_lat_stats("push", push_lat),
    }


def bench_concurrent(
    n_threads: int, rows: int, dim: int, iters: int, n_shards: int = 1
) -> dict:
    """N client threads pulling EXISTING rows from one fleet concurrently —
    the multi-worker steady state.  Scaling here is what the per-table
    reader-writer locks bought (pre-r4 a single shard mutex serialized the
    16-thread executor; VERDICT r3 Weak #3 / item 5)."""
    import threading

    io = HostTableIO(ids_fn=lambda b: b, dim=dim, optimizer="adagrad")
    servers = [
        PSServer({"t": io}, shard=s, num_shards=n_shards).start()
        for s in range(n_shards)
    ]
    addresses = [s.address for s in servers]
    rng = np.random.RandomState(0)
    per_thread = rows // n_threads
    id_sets = [
        rng.randint(0, 1 << 30, size=(per_thread,)).astype(np.int64)
        for _ in range(n_threads)
    ]
    warm = RemoteEmbeddingStore("t", dim, addresses)
    warm.wait_ready()
    for ids in id_sets:
        warm.pull(ids)  # materialize: measured pulls are read-only
    warm.close()

    lat_lock = threading.Lock()
    latencies = []  # per-REQUEST seconds, pooled across client threads

    def worker(ids, store, out, i):
        local = []
        t0 = time.perf_counter()
        for _ in range(iters):
            t1 = time.perf_counter()
            store.pull(ids)
            local.append(time.perf_counter() - t1)
        out[i] = time.perf_counter() - t0
        with lat_lock:
            latencies.extend(local)

    stores = [RemoteEmbeddingStore("t", dim, addresses) for _ in range(n_threads)]
    times = [0.0] * n_threads
    threads = [
        threading.Thread(target=worker, args=(id_sets[i], stores[i], times, i))
        for i in range(n_threads)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    for s in stores:
        s.close()
    for s in servers:
        s.stop()
    total_rows = per_thread * n_threads * iters
    return {
        "mode": "concurrent_pull",
        "threads": n_threads,
        "shards": n_shards,
        "rows_per_s": round(total_rows / wall),
        "wall_s": round(wall, 3),
        **_lat_stats("pull", latencies),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=8192 * 26)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--shards", default="1,2,4")
    ap.add_argument(
        "--concurrency", default="",
        help="comma list of client-thread counts; runs the concurrent-pull "
             "scaling mode instead of the fleet sweep (e.g. 1,2,4,8)",
    )
    args = ap.parse_args()
    results = []
    if args.concurrency:
        for n in (int(s) for s in args.concurrency.split(",")):
            result = bench_concurrent(n, args.rows, args.dim, args.iters)
            results.append(result)
            print(json.dumps(result), flush=True)
            print(f"  {n} thread(s): {result['rows_per_s']:,} rows/s, "
                  f"pull p50 {result['pull_p50_ms']} / p99 "
                  f"{result['pull_p99_ms']} ms", file=sys.stderr)
    else:
        for n in (int(s) for s in args.shards.split(",")):
            result = bench_fleet(n, args.rows, args.dim, args.iters)
            results.append(result)
            print(json.dumps(result), flush=True)
            print(f"  {n} shard(s): pull p50 {result['pull_p50_ms']} / p99 "
                  f"{result['pull_p99_ms']} ms, push p50 "
                  f"{result['push_p50_ms']} / p99 {result['push_p99_ms']} ms "
                  f"for {args.rows} rows", file=sys.stderr)
    from tools.artifact import code_rev, write_artifact

    write_artifact(
        {
            "metric": "ps_latency",
            "rows": args.rows,
            "dim": args.dim,
            "iters": args.iters,
            "results": results,
            "code_rev": code_rev(),
        },
        "ps_bench_r10.json",
        env_var="PS_BENCH_OUT",
    )


if __name__ == "__main__":
    main()
