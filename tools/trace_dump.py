"""trace_dump — merge a live job's per-process trace buffers into ONE
Chrome-trace/Perfetto JSON.

The recording half is ``common/trace.py`` (per-process ring buffers;
workers ship bounded slices to the master on the heartbeat/report channel);
this tool is the reading half: call the master's ``DumpTrace`` RPC, align
every process's clock onto the master's via the worker-measured RTT-
midpoint offsets, and write a file ``chrome://tracing`` / ui.perfetto.dev
loads directly — one row of process tracks per worker plus the master,
with phase spans, RPC client/server pairs, gang-boundary waits, lease
lifecycle instants and elastic transitions on a single timeline.

Clock alignment: each worker estimates ``offset = master_clock -
worker_clock`` as ``server_ts - (t0 + t1) / 2`` around its Heartbeat RPC
(the server stamps its clock mid-call; the midpoint assumption's error is
bounded by RTT asymmetry) and ships the estimate with its slices.  Merging
ADDS the offset to that process's timestamps, so every track reads in
master time.  A process that never measured an offset (e.g. a dump taken
before its second heartbeat) merges unshifted with a loud note.

Usage:
    python tools/trace_dump.py --master HOST:PORT [--out trace.json]
    python tools/trace_dump.py --input dump.json  [--out trace.json]
        (--input: a saved raw DumpTrace response — offline re-merge)
    add --raw PATH to also save the unmerged DumpTrace response

jax-free by design: dumping a live job must never pay a backend init.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def fetch_dump(address: str, timeout_s: float = 30.0) -> dict:
    """One DumpTrace RPC against a running master."""
    from elasticdl_tpu.common.rpc import JsonRpcClient

    client = JsonRpcClient(address)
    try:
        client.wait_ready(timeout_s)
        return client.call("DumpTrace", {}, timeout_s=timeout_s)
    finally:
        client.close()


def merge(dump: dict) -> dict:
    """DumpTrace response -> Chrome trace object (the ``traceEvents``
    array format both chrome://tracing and Perfetto load).

    Process ids are small ints with ``process_name`` metadata naming the
    worker (Chrome's legacy viewer insists on integer pids); the master is
    always pid 0 — its clock is the reference every offset aims at.
    """
    events: List[dict] = []
    notes: List[str] = []

    def emit(src_events, pid: int, name: str, offset_us: float) -> None:
        events.append({
            "ph": "M", "pid": pid, "tid": 0, "ts": 0,
            "name": "process_name", "args": {"name": name},
        })
        for e in src_events or ():
            # A malformed shipped event must not kill the merge — and the
            # guard must cover the VALUE, not just the key (ts=null from a
            # truncated/hand-edited raw dump would otherwise raise in the
            # very arithmetic this skip protects).
            if not isinstance(e, dict) or isinstance(e.get("ts"), bool) or \
                    not isinstance(e.get("ts"), (int, float)):
                continue
            ev = dict(e)
            ev["ts"] = float(ev["ts"]) + offset_us
            ev["pid"] = pid
            ev.setdefault("tid", 0)
            events.append(ev)

    emit(dump.get("master_events"), 0, "master", 0.0)
    processes = dump.get("processes") or {}
    for pid, wid in enumerate(sorted(processes), start=1):
        p = processes[wid] or {}
        offset = p.get("clock_offset_us")
        if offset is None:
            notes.append(
                f"process {wid!r} shipped no clock offset; merged unshifted"
            )
            offset = 0.0
        if p.get("dropped"):
            notes.append(
                f"process {wid!r} overwrote ~{p['dropped']} oldest events "
                "(bounded ring) — its track starts later than the others"
            )
        emit(p.get("events"), pid, wid, float(offset))

    out = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "elasticdl_tpu tools/trace_dump.py",
            "clock": "master-aligned wall microseconds (RTT-midpoint offsets)",
        },
    }
    if notes:
        out["otherData"]["notes"] = notes
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_dump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--master", default="", help="master HOST:PORT to dump")
    ap.add_argument(
        "--input", default="",
        help="saved raw DumpTrace response JSON (offline re-merge)",
    )
    ap.add_argument("--out", default="trace.json", help="merged trace path")
    ap.add_argument(
        "--raw", default="", help="also save the raw DumpTrace response here"
    )
    args = ap.parse_args(argv)
    if bool(args.master) == bool(args.input):
        print("trace_dump: exactly one of --master/--input", file=sys.stderr)
        return 2

    if args.master:
        dump = fetch_dump(args.master)
    else:
        with open(args.input) as f:
            dump = json.load(f)
    if args.raw:
        with open(args.raw, "w") as f:
            json.dump(dump, f)
    merged = merge(dump)
    with open(args.out, "w") as f:
        json.dump(merged, f)
    n_proc = 1 + len(dump.get("processes") or {})
    print(
        f"trace_dump: {len(merged['traceEvents'])} events across {n_proc} "
        f"process(es) -> {args.out} (load in chrome://tracing or "
        "ui.perfetto.dev)",
        file=sys.stderr,
    )
    for note in merged["otherData"].get("notes", ()):
        print(f"trace_dump: note: {note}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
