"""Host-side ingest stage costs — as a committed artifact (VERDICT r4
Weak #2 / Next #5: the r4 stage table lived only as prose in docs/perf.md).

Measures, per MINIBATCH-record criteo batch on this host:
- recordio bulk range read (``read_records_packed``: one read + slice-by-8
  CRC verify in C++ — the worker's ``_read_records`` fast path);
- raw decode (``criteo_feed``: C++ parse to f32/i32, 160 B/example wire);
- preprocessed decode (``criteo_feed_pre``: hash bucketing + log1p pushed
  into the C++ parse, u16/f16/u8 — 79 B/example wire);
- read + pre decode combined (the training hot path's host share).

Pure host work — runs identically on the CPU harness and the TPU host.
Writes ONE JSON artifact (default ``artifacts/ingest_stages_r05.json``);
docs/perf.md quotes the file.

``--threads N`` (r9) switches to the parallel-ingest sweep: the worker's
chunked read+decode path (data/ingest_pool.py — minibatch-aligned
sub-chunks, bulk C++ range read + preprocessed criteo decode per chunk,
ordered reassembly) measured at pool widths 1, 2, ..., N (powers of two
plus N), reporting host-side examples/sec and speedup vs the 1-thread
serial path.  Artifact: ``artifacts/INGEST_r09.json``.

Usage: python tools/ingest_bench.py [--threads N] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# FORCE cpu (not setdefault): the image exports JAX_PLATFORMS=axon, so a
# default would aim this CPU-harness tool at the real (possibly hung) chip.
os.environ["JAX_PLATFORMS"] = "cpu"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MINIBATCH = 8192
BATCHES = 16          # distinct shards measured (cold page cache effects
REPEATS = 3           # amortized); best-of-REPEATS per stage.
BUCKETS = 65536


def _time(fn, *args):
    best = float("inf")
    out = None
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = fn(*args)
        best = min(best, time.perf_counter() - t0)
    return best, out


def _wire_bytes(batch: dict) -> int:
    import numpy as np

    return sum(np.asarray(v).nbytes for v in batch.values())


def _chunked_task(reader, path, pool, start: int, task_records: int,
                  phases=None) -> None:
    """ONE worker-shaped ingest task: minibatch-aligned chunk plan + pooled
    bulk read + preprocessed decode — Worker._prep_fused_host's hot path
    minus the stacking.  The width sweep and the trace-overhead A/B both
    measure THIS (one definition, so neither can silently drift onto a
    different workload than the other claims comparability with);
    ``phases`` wraps each chunk decode in the PhaseTimers accounting
    boundary the A/B needs (the boundary that doubles as a trace span)."""
    import contextlib

    from elasticdl_tpu.data.codecs import criteo_feed_pre
    from elasticdl_tpu.data.ingest_pool import plan_chunks
    from elasticdl_tpu.data.reader import Shard

    def _decode_chunk(span):
        ctx = (
            phases.phase("decode_parallel")
            if phases is not None
            else contextlib.nullcontext()
        )
        with ctx:
            recs = reader.read_records_packed(Shard(path, span[0], span[1]))
            return criteo_feed_pre(recs, BUCKETS)

    chunks = plan_chunks(start, start + task_records, MINIBATCH, pool.threads)
    pool.map_ordered(_decode_chunk, chunks)


def _thread_sweep(max_threads: int, out: str, log) -> None:
    """Parallel-ingest sweep: the worker's chunked read+decode
    (``_chunked_task``) at pool widths 1..max_threads over task-sized
    ranges (the e2e shard size), with per-width examples/sec and speedup
    vs serial — comparable to the r5 ``host_side_examples_per_sec``."""
    from elasticdl_tpu.data.ingest_pool import IngestPool
    from elasticdl_tpu.data.reader import create_data_reader
    from tools.bench_e2e import _dataset

    task_records = MINIBATCH * 8  # the e2e bench's records-per-task
    path = _dataset()
    reader = create_data_reader(path)
    log(f"dataset {path} ({os.path.getsize(path) >> 20} MiB), "
        f"{task_records}-record tasks, host cores: {os.cpu_count()}")

    widths = sorted({1, *(
        w for w in (2, 4, 8, 16) if w < max_threads
    ), max_threads})
    n_tasks = 8
    rows = []
    for width in widths:
        pool = IngestPool(width)
        best = float("inf")
        try:
            for _ in range(REPEATS):
                t0 = time.perf_counter()
                for b in range(n_tasks):
                    _chunked_task(
                        reader, path, pool, b * task_records, task_records
                    )
                best = min(best, time.perf_counter() - t0)
        finally:
            pool.shutdown()
        eps = task_records * n_tasks / best
        rows.append({
            "threads": width,
            "examples_per_sec": round(eps, 1),
            "ms_per_task": round(best / n_tasks * 1e3, 3),
        })
        log(f"threads={width}: {eps:,.0f} examples/sec host-side")
    base = rows[0]["examples_per_sec"]
    for r in rows:
        r["speedup_vs_1"] = round(r["examples_per_sec"] / base, 3)
    artifact = {
        "metric": "parallel_ingest_host_examples_per_sec",
        "unit": f"examples/sec, {task_records}-record criteo tasks "
                f"(read_records_packed + criteo_feed_pre per chunk, best "
                f"of {REPEATS} x {n_tasks} tasks)",
        "host_cpu_count": os.cpu_count(),
        "sweep": rows,
        "note": "speedup ceiling is min(threads, host cores): the chunk "
                "decode is CPU-bound GIL-releasing C++, so a 2-core "
                "harness tops out near 2x regardless of pool width",
    }
    from tools.artifact import write_artifact

    write_artifact(artifact, "INGEST_r09.json", path=out, log=log)
    print(json.dumps(rows), flush=True)


def trace_overhead_ab(log=None) -> dict:
    """The --trace overhead measurement on the ingest workload (chunk plan
    + pooled read/decode, every chunk inside a ``PhaseTimers`` phase — the
    accounting boundary that doubles as a trace span when the recorder is
    on).  Two numbers come back:

    - ``overhead_pct`` — the ASSERTABLE bound: events-per-run counted from
      the real traced workload x per-event cost measured in isolation
      (100k-rep microbench), over the measured run wall.  Deterministic
      arithmetic over stable measurements.
    - ``ab_delta_pct`` — the raw interleaved wall-clock A/B, recorded for
      transparency.  On this shared 2-core box the run-to-run weather is
      +/-10-25% (co-tenant CPU steal; even process_time swings with cache
      pollution) while the true effect is ~0.1%, so the raw delta is a
      weather report — measured and stamped, never asserted on.

    The smoke gate (<2%, asserted by bench_all --trace-smoke and stamped
    into TRACE_r12.json) is what makes "--trace on a production job is
    safe" a recorded number instead of a hope."""
    log = log or (lambda m: print(f"[ingest] {m}", file=sys.stderr, flush=True))
    import time as _time

    from elasticdl_tpu.common import trace
    from elasticdl_tpu.common.metrics import PhaseTimers
    from elasticdl_tpu.data.ingest_pool import IngestPool
    from elasticdl_tpu.data.reader import create_data_reader
    from tools.bench_e2e import _dataset

    task_records = MINIBATCH * 8
    n_tasks = 6
    path = _dataset()
    reader = create_data_reader(path)
    pool = IngestPool(min(2, os.cpu_count() or 1))
    phases = PhaseTimers()

    def _run_once() -> float:
        t0 = _time.perf_counter()
        for b in range(n_tasks):
            with phases.phase("prep_wait"):
                _chunked_task(
                    reader, path, pool, b * task_records, task_records,
                    phases=phases,
                )
            # The control-plane event load of one task boundary (lease/
            # report instants) rides along so the accounting covers
            # instants too, not just phase spans.
            trace.instant("bench:task", cat="lease", task=b)
        return _time.perf_counter() - t0

    was_enabled = trace.enabled()
    try:
        _run_once()  # warm the page cache outside every measurement
        # Traced run: count the REAL event load and the wall it rode on.
        trace.configure(enabled=True, capacity=65536)
        trace.default().clear()
        traced_wall = _run_once()
        events = trace.default().export()
        n_spans = sum(1 for e in events if e.get("ph") == "X")
        n_instants = len(events) - n_spans
        # Interleaved wall A/B (best-of per arm), recorded as-is.
        best_off = float("inf")
        best_on = traced_wall
        for _ in range(3):
            trace.configure(enabled=False)
            best_off = min(best_off, _run_once())
            trace.configure(enabled=True)
            trace.default().clear()
            best_on = min(best_on, _run_once())
        # Primitive costs, isolated: 100k span enter/exits and instants.
        n = 100_000
        t0 = _time.perf_counter()
        for _ in range(n):
            with trace.span("x", cat="bench"):
                pass
        span_ns = (_time.perf_counter() - t0) / n * 1e9
        t0 = _time.perf_counter()
        for _ in range(n):
            trace.instant("x", cat="bench")
        instant_ns = (_time.perf_counter() - t0) / n * 1e9
        trace.default().clear()
    finally:
        trace.configure(enabled=was_enabled)
        pool.shutdown()
    event_cost_s = (n_spans * span_ns + n_instants * instant_ns) / 1e9
    overhead_pct = event_cost_s / traced_wall * 100.0
    ab_delta_pct = (best_on - best_off) / best_off * 100.0
    out = {
        "overhead_pct": round(overhead_pct, 4),
        "events_per_run": len(events),
        "spans_per_run": n_spans,
        "instants_per_run": n_instants,
        "run_wall_s": round(traced_wall, 4),
        "span_ns": round(span_ns, 1),
        "instant_ns": round(instant_ns, 1),
        "examples_per_sec_trace_on": round(
            task_records * n_tasks / best_on, 1
        ),
        "examples_per_sec_trace_off": round(
            task_records * n_tasks / best_off, 1
        ),
        "ab_delta_pct": round(ab_delta_pct, 2),
        "ab_note": "raw interleaved wall A/B on a shared box: +/-10-25% "
                   "co-tenant weather over a ~0.1% true effect — recorded "
                   "for transparency; overhead_pct (event count x measured "
                   "per-event cost over run wall) is the assertable bound",
        "workload": f"{n_tasks} x {task_records}-record criteo tasks, "
                    f"chunked read+decode on a {pool.threads}-thread pool; "
                    "spans via PhaseTimers phases + one instant per task",
    }
    log(f"trace overhead: {len(events)} events/run x "
        f"({span_ns:.0f} ns/span, {instant_ns:.0f} ns/instant) over "
        f"{traced_wall*1e3:.0f} ms = {overhead_pct:.4f}% "
        f"(raw wall A/B {ab_delta_pct:+.2f}%, weather-dominated)")
    return out


def gauge_overhead_ab(log=None) -> dict:
    """The graftgauge overhead measurement on the ingest workload — the
    trace_overhead_ab method applied to the r14 metrics plane (same
    workload definition, same assertable-bound arithmetic, same <2%
    budget):

    - the workload runs with a live ``gauge.Registry`` wired into
      ``PhaseTimers`` (every phase entry observes into the per-phase
      histogram) plus the worker-shaped hot-path counter updates (one
      examples inc + one steps inc per task — Worker._dispatch_batches'
      sites);
    - ``overhead_pct`` = updates-per-run counted from the real
      instrumented workload x per-update cost measured in isolation
      (100k-rep microbench), PLUS one scrape per second
      (``render_prometheus`` wall x 1 Hz — a Prometheus-typical cadence),
      over the measured run wall;
    - the raw interleaved wall A/B is stamped for transparency and never
      asserted on (the co-tenant-weather caveat in trace_overhead_ab).
    """
    log = log or (lambda m: print(f"[ingest] {m}", file=sys.stderr, flush=True))
    import time as _time

    from elasticdl_tpu.common import gauge
    from elasticdl_tpu.common.metrics import PhaseTimers
    from elasticdl_tpu.data.ingest_pool import IngestPool
    from elasticdl_tpu.data.reader import create_data_reader
    from tools.bench_e2e import _dataset

    task_records = MINIBATCH * 8
    n_tasks = 6
    path = _dataset()
    reader = create_data_reader(path)
    pool = IngestPool(min(2, os.cpu_count() or 1))

    def _run_once(phases, g_examples, g_steps) -> float:
        t0 = _time.perf_counter()
        for b in range(n_tasks):
            with phases.phase("prep_wait"):
                _chunked_task(
                    reader, path, pool, b * task_records, task_records,
                    phases=phases,
                )
            # The worker task loop's own hot-path counter sites, one task
            # boundary's worth (examples + steps + task done).
            g_examples.inc(task_records)
            g_steps.inc(task_records // MINIBATCH)
        return _time.perf_counter() - t0

    try:
        reg = gauge.Registry()
        phases_on = PhaseTimers(gauges=reg)
        g_examples = reg.counter(gauge.EXAMPLES_TRAINED)
        g_steps = reg.counter(gauge.STEPS_DISPATCHED)
        _run_once(phases_on, g_examples, g_steps)  # warm the page cache
        warm_counts = sum(phases_on.counts().values())
        gauged_wall = _run_once(phases_on, g_examples, g_steps)
        # Updates per run, from the instrumented run itself: every phase
        # entry observed into a histogram, plus the two counter incs per
        # task.  PhaseTimers counts are CUMULATIVE — diff against the
        # warm run's tally or the per-run number doubles.
        n_observes = sum(phases_on.counts().values()) - warm_counts
        n_incs = 2 * n_tasks
        # Interleaved wall A/B (best-of per arm), recorded as-is.
        phases_off = PhaseTimers()
        off_c = gauge.Counter(enabled=False)
        best_off, best_on = float("inf"), gauged_wall
        for _ in range(3):
            best_off = min(best_off, _run_once(phases_off, off_c, off_c))
            best_on = min(
                best_on, _run_once(phases_on, g_examples, g_steps)
            )
        # Primitive costs, isolated.
        n = 100_000
        hist = reg.histogram("edl_phase_ms", labels={"phase": "prep_wait"})
        t0 = _time.perf_counter()
        for _ in range(n):
            hist.observe(1.0)
        observe_ns = (_time.perf_counter() - t0) / n * 1e9
        ctr = reg.counter(gauge.EXAMPLES_TRAINED)
        t0 = _time.perf_counter()
        for _ in range(n):
            ctr.inc()
        inc_ns = (_time.perf_counter() - t0) / n * 1e9
        # Scrape cost: one full render (collectors + every family), the
        # per-scrape price an operator's 1 Hz poll pays.
        t0 = _time.perf_counter()
        for _ in range(50):
            reg.render_prometheus()
        scrape_ms = (_time.perf_counter() - t0) / 50 * 1e3
    finally:
        pool.shutdown()
    update_cost_s = (n_observes * observe_ns + n_incs * inc_ns) / 1e9
    scrape_hz = 1.0
    overhead_pct = (
        update_cost_s / gauged_wall + scrape_ms / 1e3 * scrape_hz
    ) * 100.0
    ab_delta_pct = (best_on - best_off) / best_off * 100.0
    out = {
        "overhead_pct": round(overhead_pct, 4),
        "updates_per_run": n_observes + n_incs,
        "observes_per_run": n_observes,
        "incs_per_run": n_incs,
        "run_wall_s": round(gauged_wall, 4),
        "observe_ns": round(observe_ns, 1),
        "inc_ns": round(inc_ns, 1),
        "scrape_ms": round(scrape_ms, 3),
        "scrape_hz_assumed": scrape_hz,
        "ab_delta_pct": round(ab_delta_pct, 2),
        "ab_note": "raw interleaved wall A/B on a shared box — weather-"
                   "dominated, recorded for transparency; overhead_pct "
                   "(update count x measured per-update cost + 1 Hz "
                   "scrape render, over run wall) is the assertable "
                   "bound (the trace_overhead_ab method)",
        "workload": f"{n_tasks} x {task_records}-record criteo tasks, "
                    f"chunked read+decode on a {pool.threads}-thread "
                    "pool; histogram observes via PhaseTimers phases + 2 "
                    "counter incs per task; scrape = full "
                    "render_prometheus",
    }
    log(f"gauge overhead: {n_observes + n_incs} updates/run x "
        f"({observe_ns:.0f} ns/observe, {inc_ns:.0f} ns/inc) + "
        f"{scrape_ms:.2f} ms/scrape @1 Hz over {gauged_wall*1e3:.0f} ms "
        f"= {overhead_pct:.4f}% (raw wall A/B {ab_delta_pct:+.2f}%, "
        "weather-dominated)")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--out", default=""
    )
    ap.add_argument(
        "--threads", type=int, default=0,
        help="run the parallel-ingest sweep up to this pool width "
             "(stamps artifacts/INGEST_r09.json) instead of the serial "
             "stage breakdown",
    )
    ap.add_argument(
        "--trace-ab", action="store_true",
        help="run the --trace overhead A/B (recorder off vs on over the "
             "chunked ingest workload) and print the result JSON",
    )
    ap.add_argument(
        "--gauge-ab", action="store_true",
        help="run the graftgauge overhead A/B (registry + scrape over "
             "the chunked ingest workload) and print the result JSON",
    )
    args = ap.parse_args()
    log = lambda m: print(f"[ingest] {m}", file=sys.stderr, flush=True)

    if args.gauge_ab:
        result = gauge_overhead_ab(log)
        if args.out:
            from tools.artifact import write_artifact

            write_artifact(
                {"metric": "gauge_overhead_ingest_ab", **result},
                "gauge_ab_r14.json", path=args.out, log=log,
            )
        print(json.dumps(result), flush=True)
        return

    if args.trace_ab:
        result = trace_overhead_ab(log)
        if args.out:
            from tools.artifact import write_artifact

            write_artifact(
                {"metric": "trace_overhead_ingest_ab", **result},
                "trace_ab_r12.json", path=args.out, log=log,
            )
        print(json.dumps(result), flush=True)
        return

    if args.threads > 0:
        _thread_sweep(
            args.threads,
            args.out or os.path.join(_REPO_ROOT, "artifacts",
                                     "INGEST_r09.json"),
            log,
        )
        return
    args.out = args.out or os.path.join(
        _REPO_ROOT, "artifacts", "ingest_stages_r05.json"
    )

    from elasticdl_tpu.data.codecs import criteo_feed, criteo_feed_pre
    from elasticdl_tpu.data.reader import Shard, create_data_reader
    from tools.bench_e2e import _dataset

    path = _dataset()
    reader = create_data_reader(path)
    log(f"dataset {path} ({os.path.getsize(path) >> 20} MiB)")

    read_s = dec_raw_s = dec_pre_s = combo_s = 0.0
    raw_bytes = pre_bytes = 0
    for b in range(BATCHES):
        shard = Shard(name=path, start=b * MINIBATCH, end=(b + 1) * MINIBATCH)
        t, records = _time(reader.read_records_packed, shard)
        read_s += t
        t, raw = _time(criteo_feed, records)
        dec_raw_s += t
        t, pre = _time(criteo_feed_pre, records, BUCKETS)
        dec_pre_s += t
        t, _ = _time(
            lambda s: criteo_feed_pre(reader.read_records_packed(s), BUCKETS),
            shard,
        )
        combo_s += t
        raw_bytes, pre_bytes = _wire_bytes(raw), _wire_bytes(pre)

    n = BATCHES
    per_batch = lambda s: round(s / n * 1e3, 3)  # ms per 8192-record batch
    artifact = {
        "metric": "ingest_stage_ms_per_batch",
        "unit": f"ms per {MINIBATCH}-record criteo batch (best of "
                f"{REPEATS}, mean over {BATCHES} shards)",
        "stages": {
            "recordio_range_read_ms": per_batch(read_s),
            "decode_raw_ms": per_batch(dec_raw_s),
            "decode_pre_ms": per_batch(dec_pre_s),
            "read_plus_pre_decode_ms": per_batch(combo_s),
        },
        "derived": {
            "decode_pre_us_per_record": round(
                dec_pre_s / n / MINIBATCH * 1e6, 3
            ),
            "host_side_examples_per_sec": round(
                MINIBATCH / (combo_s / n), 1
            ),
            "wire_bytes_per_example_raw": raw_bytes // MINIBATCH,
            "wire_bytes_per_example_pre": pre_bytes // MINIBATCH,
        },
    }
    from tools.artifact import write_artifact

    write_artifact(artifact, "ingest_stages_r05.json", path=args.out, log=log)
    print(json.dumps({**artifact["stages"], **artifact["derived"]}),
          flush=True)


if __name__ == "__main__":
    main()
