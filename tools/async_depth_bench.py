"""Host-tier DeepFM throughput vs async-PS staleness depth (VERDICT r3
item 7).

The host-tier step is: pull batch rows from the PS fleet (RPC) -> jitted
device step -> push sparse cotangents (RPC).  --use_async overlaps the pull
with the in-flight step; ``--async_staleness D`` lets up to D pushes ride
behind device steps.  This tool trains host-tier DeepFM against a real
local PS fleet at depth 0 (sync) / 1 / 2 / 4 and prints one JSON line per
depth, so the default depth is chosen by measurement, not by assumption.

Usage: python tools/async_depth_bench.py [--steps 30] [--shards 2]
(Runs on whatever jax.devices() offers; the RELATIVE depth effect is about
hiding RPC latency, which exists on any backend.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elasticdl_tpu.common.platform import apply_platform_env, enable_compile_cache

apply_platform_env()


def bench_depth(depth: int, steps: int, n_shards: int, batch: int) -> dict:
    import jax
    import numpy as np

    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer
    from elasticdl_tpu.ps.service import PSServer

    spec = load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        buckets_per_feature=65536,
        embedding_dim=8,
        hidden=(400, 400),
        host_tier=True,
    )
    servers = [
        PSServer(spec.host_io, shard=s, num_shards=n_shards).start()
        for s in range(n_shards)
    ]
    config = JobConfig(
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        ps_addresses=",".join(s.address for s in servers),
        use_async=depth > 0,
        async_staleness=max(depth, 1),
    )
    rng = np.random.RandomState(0)

    def mk():
        return {
            "dense": rng.rand(batch, 13).astype(np.float32) * 100,
            "cat": rng.randint(0, 1 << 30, (batch, 26)).astype(np.int32),
            "labels": rng.randint(0, 2, (batch,)).astype(np.int32),
        }

    try:
        trainer = Trainer(spec, config, create_mesh(jax.devices()))
        state = trainer.init_state(jax.random.key(0))
        warm = [mk() for _ in range(3)]
        state, _ = trainer.run_train_steps(state, warm, use_async=depth > 0)
        jax.block_until_ready(state.step)
        batches = [mk() for _ in range(steps)]
        t0 = time.perf_counter()
        state, metrics = trainer.run_train_steps(
            state, batches, use_async=depth > 0
        )
        jax.block_until_ready(state.step)
        elapsed = time.perf_counter() - t0
    finally:
        for s in servers:
            s.stop()
    return {
        "mode": "sync" if depth == 0 else f"async_depth_{depth}",
        "depth": depth,
        "examples_per_s": round(batch * steps / elapsed),
        "step_ms": round(elapsed / steps * 1e3, 1),
        "shards": n_shards,
        "batch": batch,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--depths", default="0,1,2,4")
    args = ap.parse_args()
    from elasticdl_tpu.common.platform import probe_devices

    # Hang-proof init: see bench.py (VERDICT r4 Next #1).
    probe_devices(attempts=3, timeout_s=90)
    enable_compile_cache()
    # The sweep's verdict flips with the wire's mood (a stall-window sweep
    # ranks sync > any async depth because the pull RTT dominates), so the
    # artifact must carry the link quality it was measured under.
    from tools.bench_e2e import _link_probe

    link = _link_probe(log=lambda m: print(m, file=sys.stderr, flush=True))
    results = []
    try:
        for d in (int(s) for s in args.depths.split(",")):
            result = bench_depth(d, args.steps, args.shards, args.batch)
            results.append(result)
            print(json.dumps(result), flush=True)
            print(f"  depth {d}: {result['examples_per_s']:,} ex/s "
                  f"({result['step_ms']} ms/step)", file=sys.stderr)
    finally:
        if results:  # a mid-sweep flake still deposits what was measured
            from tools.artifact import write_artifact

            write_artifact(
                {
                    "metric": "async_staleness_depth_sweep",
                    "depths": results,
                    **link,
                },
                "async_depth_r05.json", env_var="ASYNC_DEPTH_OUT",
            )


if __name__ == "__main__":
    main()
