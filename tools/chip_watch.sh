#!/bin/bash
# Poll the chip with killable probes until it answers, then exit 0 so the
# operator (or a wrapper) can fire tools/chip_battery.sh immediately.
# Exit 4 after --max-minutes of failure.  Log: one line per probe.
set -u
MAX_MIN=${1:-600}
LOG=${2:-/tmp/chip_watch.log}
start=$(date +%s)
n=0
while :; do
  n=$((n+1))
  if python -c "from elasticdl_tpu.common.platform import probe_devices as p; p(attempts=1, timeout_s=120)" >>"$LOG" 2>&1; then
    echo "chip UP at probe $n $(date -u +%H:%M:%S)" | tee -a "$LOG"
    exit 0
  fi
  echo "probe $n: chip down $(date -u +%H:%M:%S)" >> "$LOG"
  now=$(date +%s)
  if [ $(( (now - start) / 60 )) -ge "$MAX_MIN" ]; then
    echo "chip still down after ${MAX_MIN}m; giving up" | tee -a "$LOG"
    exit 4
  fi
  sleep 180
done
