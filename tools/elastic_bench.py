"""Measure elastic re-rendezvous latency — the SECOND driver-defined target
(BASELINE.md: "re-converge within one step after a worker preemption").

Scenario (in-process, 8 fake CPU devices — the same harness the elastic
tests use; the latency being measured is control-plane + re-shard +
recompile work, none of which runs on the accelerator):

  1. a DeepFM hybrid job trains on an 8-device mesh with periodic
     checkpoints;
  2. a membership bump simulates losing half the fleet (8 -> 4);
  3. the worker re-forms the mesh, re-places state from the latest
     checkpoint, and runs the next training step.

Reported: seconds from the membership bump to the FIRST completed
post-resize training step, split into re-form (mesh + state re-placement)
and step (incl. recompile — with the persistent compile cache warm, a
repeat topology skips XLA).  "Re-converge within one step" is satisfied by
construction — the first post-resize step trains on restored weights; this
tool puts a NUMBER on how long that step takes to arrive.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
           python tools/elastic_bench.py
Prints one JSON line: {"reform_s": ..., "first_step_s": ..., "total_s": ...,
"cold": {...}} (cold = first resize, warm = resized back to a seen size).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# FORCE cpu (not setdefault): the image exports JAX_PLATFORMS=axon, so a
# default would aim this CPU-harness tool at the real (possibly hung) chip.
os.environ["JAX_PLATFORMS"] = "cpu"

from elasticdl_tpu.common.platform import apply_platform_env, enable_compile_cache

import numpy as np  # noqa: E402

# jax is imported inside main(): importing this module (lint/CLI paths)
# must never pay a backend init — apply_platform_env itself imports jax
# when JAX_PLATFORMS is set, so it is deferred too.


def _batch(n=64, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "dense": rng.rand(n, 13).astype(np.float32) * 100,
        "cat": rng.randint(0, 1 << 20, (n, 26)).astype(np.int64),
        "labels": rng.randint(0, 2, (n,)).astype(np.int32),
    }


def main() -> None:
    apply_platform_env()
    import jax

    enable_compile_cache()
    from elasticdl_tpu.common.checkpoint import CheckpointManager
    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    devices = jax.devices()
    assert len(devices) >= 8, f"need 8 fake devices, have {len(devices)}"
    spec = load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        buckets_per_feature=4096, embedding_dim=8, hidden=(64, 64),
        compute_dtype="float32",
    )
    config = JobConfig(
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        embedding_lookup_impl="ragged_emulated",
    )
    ckpt_dir = tempfile.mkdtemp(prefix="elastic_bench_")
    ckpt = CheckpointManager(ckpt_dir)

    trainer = Trainer(spec, config, create_mesh(devices, num_devices=8))
    state = trainer.init_state(jax.random.key(0))
    for s in range(3):
        state, metrics = trainer.train_step(state, trainer.shard_batch(_batch(seed=s)))
    jax.block_until_ready(metrics)
    ckpt.save(int(state.step), jax.device_get(state), wait=True)
    print("[elastic-bench] trained 3 steps on 8 devices, checkpointed",
          file=sys.stderr)

    def resize(n_devices, seed):
        """Membership bump -> re-form -> restore -> first step; timed."""
        t0 = time.perf_counter()
        trainer.set_mesh(create_mesh(devices, num_devices=n_devices))
        # Canonical bridge (trainer.host_state): with --optimizer_sharding
        # the live opt leaves are dp-flat and must canonicalize before
        # re-placement; the checkpoint itself is canonical in every mode.
        template = trainer.shard_state(trainer.host_state(state))
        restored = trainer.adopt_restored(
            ckpt.restore(trainer.restore_template(template))
        )
        t_reform = time.perf_counter() - t0
        t1 = time.perf_counter()
        new_state, m = trainer.train_step(
            restored, trainer.shard_batch(_batch(seed=seed))
        )
        jax.block_until_ready(m)
        t_step = time.perf_counter() - t1
        return {
            "devices": n_devices,
            "reform_s": round(t_reform, 3),
            "first_step_s": round(t_step, 3),
            "total_s": round(t_reform + t_step, 3),
        }

    cold = resize(4, seed=10)   # unseen topology: pays re-shard + compile
    print(f"[elastic-bench] cold 8->4: {cold}", file=sys.stderr)
    back = resize(8, seed=11)   # seen topology: compile cache warm
    print(f"[elastic-bench] warm 4->8: {back}", file=sys.stderr)
    again = resize(4, seed=12)  # seen 4-dev topology too
    print(f"[elastic-bench] warm 8->4: {again}", file=sys.stderr)

    result = {
        "metric": "elastic_rerendezvous_latency_s",
        "cold_8_to_4": cold,
        "warm_4_to_8": back,
        "warm_8_to_4": again,
        "value": again["total_s"],
        "unit": "seconds (membership bump -> first post-resize step done)",
    }
    print(json.dumps(result))
    from tools.artifact import write_artifact

    # Number-of-record artifact (docs/perf.md quotes the file).
    write_artifact(
        result, "elastic_inprocess_r05.json", env_var="ELASTIC_BENCH_OUT",
        log=lambda m: print(f"[elastic-bench] {m}", file=sys.stderr),
    )


if __name__ == "__main__":
    main()
