"""Shared number-of-record artifact writer for the bench tools.

Every perf tool commits its measurement as a JSON file under
``artifacts/`` stamped with the command line and UTC time (docs/perf.md
quotes the files; VERDICT r4 Next #5).  One definition so the write idiom
— env override, directory creation, stamping — cannot drift per tool.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Callable, Optional, Sequence

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def code_rev(repo: Optional[str] = None) -> str:
    """Commit hash of the code producing an artifact (best-effort).

    Stamped into bench/lint artifacts so trend consumers (and bench.py's
    best-run-wins record guard) can tell "another run of the same code"
    from "the first run of NEW code".  A dirty tree gets a "-dirty" suffix
    — uncommitted changes are NEW code under the same HEAD, and two dirty
    runs may differ from each other too, so dirty never matches anything.
    Untracked files count as dirt: a new not-yet-added module is importable
    code the committed rev does not describe (ignored files still don't
    count).  Returns "" when git is unavailable.
    """
    try:
        import subprocess

        repo = repo or _REPO_ROOT
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=repo, capture_output=True, text=True, timeout=10,
        )
        if out.returncode != 0:
            return ""
        rev = out.stdout.strip()
        st = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=repo, capture_output=True, text=True, timeout=10,
        )
        if st.returncode != 0 or st.stdout.strip():
            rev += "-dirty"
        return rev
    except Exception:
        return ""


class ArtifactRun:
    """Capture ``code_rev`` at TOOL ENTRY and stamp it at write time.

    The pattern c5125b1 fixed by hand in straggler_report.py, made
    un-regressable: a tool whose RUN rewrites committed outputs (merged
    traces, prior artifacts) dirties its own tree, so a stamp-time
    ``code_rev()`` would mark every artifact "-dirty" from the tool's OWN
    output files.  The code that produced the measurement is the tree as
    it stood on entry — construct one of these FIRST, write through it
    LAST.  A caller-supplied ``code_rev`` key in the result still wins
    (setdefault), so tools measuring a different tree can override.
    """

    def __init__(self, repo: Optional[str] = None):
        self.code_rev = code_rev(repo)

    def write(
        self,
        result: dict,
        default_name: str,
        env_var: str = "",
        path: Optional[str] = None,
        log: Optional[Callable[[str], None]] = None,
    ) -> str:
        stamped = dict(result)
        stamped.setdefault("code_rev", self.code_rev)
        return write_artifact(
            stamped, default_name, env_var=env_var, path=path, log=log
        )


#: Shared log-spaced histogram bucket edges (MILLISECONDS) for
#: ``latency_stats(..., buckets=True)``.  One FIXED grid across every
#: artifact (serving_bench, ps_bench, straggler_report) so tail shapes are
#: comparable file to file and round to round — per-run adaptive edges
#: would make two artifacts' histograms incomparable.  Canonical home is
#: ``common/gauge.py`` since r14: the LIVE registry histograms bucket on
#: the same grid, so a scrape and a stamped artifact agree bin-for-bin
#: (gauge.py is stdlib-only, so this import keeps the artifact path
#: jax-free).  Re-exported here for the existing consumers.
from elasticdl_tpu.common.gauge import DEFAULT_BUCKET_EDGES_MS  # noqa: E402,F401


def latency_stats(
    samples_ms: Sequence[float], prefix: str = "", buckets=None
) -> dict:
    """p50/p99/mean/max over per-request latencies in MILLISECONDS — the
    one definition every latency consumer (ps_bench, serving_bench,
    straggler_report) stamps, so percentile conventions cannot drift per
    tool.  Empty input returns {} (a point with zero completed requests has
    no latency distribution; callers report their error tallies instead).

    ``buckets``: True for the shared ``DEFAULT_BUCKET_EDGES_MS`` grid, or
    an explicit ascending edge sequence — adds ``{prefix}hist`` with
    ``edges_ms`` and ``counts`` (``len(edges)+1`` entries: counts[i] holds
    samples in ``(edges[i-1], edges[i]]`` with counts[0] the under-first-
    edge bin and counts[-1] the overflow), so artifacts carry the TAIL
    SHAPE, not just two percentile points.
    """
    if not samples_ms:
        return {}
    import numpy as np  # local: keep the module import jax-/numpy-free
                        # (graftlint's artifact path must cost milliseconds)

    arr = np.asarray(samples_ms, np.float64)
    out = {
        f"{prefix}p50_ms": round(float(np.percentile(arr, 50)), 2),
        f"{prefix}p99_ms": round(float(np.percentile(arr, 99)), 2),
        f"{prefix}mean_ms": round(float(arr.mean()), 2),
        f"{prefix}max_ms": round(float(arr.max()), 2),
    }
    if buckets is not None and buckets is not False:
        edges = (
            DEFAULT_BUCKET_EDGES_MS
            if buckets is True
            else tuple(float(e) for e in buckets)
        )
        idx = np.searchsorted(np.asarray(edges, np.float64), arr, side="left")
        counts = np.bincount(idx, minlength=len(edges) + 1)
        out[f"{prefix}hist"] = {
            "edges_ms": list(edges),
            "counts": [int(c) for c in counts],
        }
    return out


def write_artifact(
    result: dict,
    default_name: str,
    env_var: str = "",
    path: Optional[str] = None,
    log: Optional[Callable[[str], None]] = None,
) -> str:
    """Write ``result`` (+ command/utc stamp) and return the path.

    Resolution order: explicit ``path`` arg, then ``env_var`` if set in the
    environment, then ``artifacts/<default_name>`` at the repo root.  A
    bare filename (no directory part) writes to the current directory.
    """
    out = (
        path
        or (os.environ.get(env_var, "") if env_var else "")
        or os.path.join(_REPO_ROOT, "artifacts", default_name)
    )
    # Atomic since r21 (durable.atomic_publish): a tool killed mid-stamp
    # used to leave a truncated JSON file that bench_regress parses as a
    # corrupt artifact — a number of record must commit whole or not at
    # all, same as any durable state.
    from elasticdl_tpu.common import durable

    durable.atomic_publish_json(
        out,
        {
            **result,
            "command": " ".join(sys.argv),
            # Which backend the process was aimed at — so a CPU smoke
            # run can never masquerade as an on-chip number of record.
            "jax_platforms": os.environ.get(
                "JAX_PLATFORMS", "(default: axon tpu)"
            ),
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        },
        indent=1,
    )
    say = log or (lambda m: print(m, file=sys.stderr, flush=True))
    say(f"artifact written to {out}")
    return out
