"""Shared number-of-record artifact writer for the bench tools.

Every perf tool commits its measurement as a JSON file under
``artifacts/`` stamped with the command line and UTC time (docs/perf.md
quotes the files; VERDICT r4 Next #5).  One definition so the write idiom
— env override, directory creation, stamping — cannot drift per tool.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_artifact(
    result: dict,
    default_name: str,
    env_var: str = "",
    path: Optional[str] = None,
    log: Optional[Callable[[str], None]] = None,
) -> str:
    """Write ``result`` (+ command/utc stamp) and return the path.

    Resolution order: explicit ``path`` arg, then ``env_var`` if set in the
    environment, then ``artifacts/<default_name>`` at the repo root.  A
    bare filename (no directory part) writes to the current directory.
    """
    out = (
        path
        or (os.environ.get(env_var, "") if env_var else "")
        or os.path.join(_REPO_ROOT, "artifacts", default_name)
    )
    d = os.path.dirname(out)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out, "w") as f:
        json.dump(
            {
                **result,
                "command": " ".join(sys.argv),
                # Which backend the process was aimed at — so a CPU smoke
                # run can never masquerade as an on-chip number of record.
                "jax_platforms": os.environ.get(
                    "JAX_PLATFORMS", "(default: axon tpu)"
                ),
                "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            },
            f,
            indent=1,
        )
    say = log or (lambda m: print(m, file=sys.stderr, flush=True))
    say(f"artifact written to {out}")
    return out
