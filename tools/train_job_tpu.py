"""One full `elasticdl train` job on the real TPU (VERDICT r3 item 3).

The whole SURVEY §3.1-3.3 stack, on hardware, once: an embedded Master
(gRPC servicer + TaskDispatcher + RendezvousServer + PodManager — the
master itself never touches jax) launches a REAL worker process via
ProcessPodBackend; the worker grabs the chip, reads criteo recordio shards
through the C++ bulk reader, decodes with the C++ pre-processing codec,
and trains hybrid DeepFM with periodic checkpoints until the dispatcher
drains.  The tool polls JobStatus to timestamp task completions and writes
a committed artifact (TRAINJOB_r04.json) with wall-clock and end-to-end
examples/sec/chip.

Usage: python tools/train_job_tpu.py [--epochs 16] [--out TRAINJOB_r04.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.bench_e2e import (  # noqa: E402
    MINIBATCH,
    MINIBATCHES_PER_TASK,
    RECORDS_PER_TASK,
    _dataset,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=16)
    ap.add_argument("--out", default="TRAINJOB_r05.json")
    args = ap.parse_args()
    from elasticdl_tpu.common.platform import probe_devices

    # Hang-proof init: see bench.py (VERDICT r4 Next #1).
    probe_devices(attempts=3, timeout_s=90)

    import tempfile

    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.master.main import Master

    path = _dataset()
    ckpt = tempfile.mkdtemp(prefix="trainjob_ckpt_")
    config = JobConfig(
        job_name="trainjob-tpu",
        model_def="deepfm.model_spec",
        model_params="buckets_per_feature=65536;embedding_dim=8;hidden=[400,400]",
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        training_data=path,
        minibatch_size=MINIBATCH,
        num_minibatches_per_task=MINIBATCHES_PER_TASK,
        num_epochs=args.epochs,
        num_workers=1,
        pod_backend="process",
        checkpoint_dir=ckpt,
        checkpoint_steps=64,
    )
    master = Master(config)
    status_box: dict = {}

    def run_master():
        try:
            status_box["status"] = master.run()
        except Exception as e:  # noqa: BLE001
            status_box["error"] = repr(e)

    t_start = time.time()
    thread = threading.Thread(target=run_master, daemon=True)
    thread.start()

    timeline = []  # (t, done_count)
    last = -1
    phase_times: dict = {}  # worker_id -> {phase: cumulative seconds}
    while thread.is_alive():
        try:
            status_now = master.servicer.JobStatus({})
            done = status_now["done"]
            # Cumulative per-worker phase decomposition (rides every
            # ReportTaskResult/ReportCheckpoint); latest snapshot wins.
            if status_now.get("phase_times"):
                phase_times = status_now["phase_times"]
        except Exception:
            done = last
        if done != last:
            timeline.append((time.time(), done))
            last = done
            print(f"[job] {done} tasks done at +{time.time() - t_start:.1f}s",
                  file=sys.stderr, flush=True)
        time.sleep(0.2)
    thread.join()
    try:  # final snapshot: the worker's last report lands before run() ends
        final_status = master.servicer.JobStatus({})
        if final_status.get("phase_times"):
            phase_times = final_status["phase_times"]
    except Exception:
        pass
    t_total = time.time() - t_start
    if "error" in status_box:
        raise SystemExit(f"master failed: {status_box['error']}")
    status = status_box["status"]

    # Steady-state e2e throughput: exclude the first 2 tasks (worker boot +
    # XLA compile); measure task 2 -> last.
    warm = 2
    steady = [(t, d) for t, d in timeline if d >= warm]
    if len(steady) >= 2:
        (t0, d0), (t1, d1) = steady[0], steady[-1]
        eps = (d1 - d0) * RECORDS_PER_TASK / max(t1 - t0, 1e-9)
    else:
        eps = None

    ckpt_steps = sorted(
        int(s) for s in os.listdir(ckpt) if s.isdigit()
    ) if os.path.isdir(ckpt) else []

    # Attribute the job wall to named worker phases (VERDICT r5 Weak #1:
    # the 5.4x job-vs-bench gap was guessed, not measured).  The snapshot
    # is cumulative seconds per phase per worker; the critical-path sum
    # should land near the worker's share of wall_total_s — the remainder
    # is boot/compile/exit and anything not yet instrumented.
    from elasticdl_tpu.common.metrics import critical_path_seconds

    phase_summary = None
    if phase_times:
        totals: dict = {}
        for per_worker in phase_times.values():
            for k, v in per_worker.items():
                totals[k] = round(totals.get(k, 0.0) + float(v), 3)
        crit = critical_path_seconds(totals)
        phase_summary = {
            "per_worker": phase_times,
            "totals_s": totals,
            "critical_path_s": round(crit, 1),
            "critical_path_frac_of_wall": (
                round(crit / t_total, 3) if t_total > 0 else None
            ),
        }

    result = {
        "metric": "full_train_job_e2e_examples_per_sec_per_chip",
        "value": round(eps) if eps else None,
        "unit": "examples/sec/chip",
        "job_status": {k: v for k, v in status.items() if k != "eval_metrics"},
        "wall_total_s": round(t_total, 1),
        "tasks": timeline[-1][1] if timeline else 0,
        "records_per_task": RECORDS_PER_TASK,
        "warm_tasks_excluded": warm,
        "checkpoint_steps_on_disk": ckpt_steps,
        # prep_wait / dispatch / step_wait / metrics / checkpoint / control
        # / lease_wait (+ off-path checkpoint_bg, decode_parallel) — see
        # common/metrics.py PhaseTimers.
        "phase_times": phase_summary,
        "stack": "Master(gRPC)+ProcessPodBackend worker on TPU, recordio "
                 "input via C++ bulk reader + preprocessing codec, "
                 "periodic+final checkpoints",
    }
    print(json.dumps(result), flush=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[job] artifact written to {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
