"""Serving-tier latency/QPS bench — the r10 perf surface.

Drives the online prediction service (serving/server.ServingServer) the way
production traffic would: a tiny host-tier DeepFM whose sparse rows live in
a real in-process PS shard (ps/service.PSServer), real gRPC on loopback,
open-loop arrivals at several offered-QPS points, and — mid-run — a hot
checkpoint reload that must complete with ZERO failed requests.

Latency is measured per request against its SCHEDULED arrival (open-loop):
a backlogged server shows up as queueing delay in the percentiles instead
of silently throttling the offered load — the honest way to read "can this
replica hold N QPS at a p99".

Stamps p50/p99 per offered-QPS point plus the reload's live-path downtime
into ``artifacts/SERVE_r10.json`` (env override SERVE_OUT) — the second
first-class perf surface alongside examples/sec (docs/perf.md).

Usage:
  python tools/serving_bench.py [--qps 50,100,200] [--duration 4]
      [--max_batch 32] [--max_delay_ms 5] [--clients 8] [--no_reload]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_DENSE = 13
NUM_CAT = 26




class _RequestFeed:
    """Zipf-ish single-example feature generator: most categorical values
    draw from a small hot pool (the cache's reason to exist), the tail from
    the full bucket range — pre-generated so the load loop costs nothing."""

    def __init__(self, n: int, buckets: int, hot_pool: int = 200,
                 hot_frac: float = 0.8, seed: int = 0):
        rng = np.random.RandomState(seed)
        hot = rng.randint(0, buckets, size=(hot_pool, NUM_CAT))
        self.features: List[Dict[str, list]] = []
        for i in range(n):
            if rng.rand() < hot_frac:
                cat = hot[rng.randint(hot_pool)]
            else:
                cat = rng.randint(0, buckets, size=(NUM_CAT,))
            dense = rng.rand(NUM_DENSE) * 100.0
            self.features.append({
                "dense": [dense.round(3).tolist()],
                "cat": [cat.tolist()],
            })

    def __getitem__(self, i: int) -> Dict[str, list]:
        return self.features[i % len(self.features)]


def _drive_point(
    address: str,
    feed: _RequestFeed,
    offered_qps: float,
    duration_s: float,
    n_clients: int,
    timeout_s: float = 30.0,
) -> Dict:
    """Open-loop load: ``offered_qps * duration_s`` requests on a fixed
    schedule, striped over ``n_clients`` threads (each with its own channel
    — one client serializing everything would close the loop)."""
    from elasticdl_tpu.serving.client import ServingClient

    total = max(int(offered_qps * duration_s), 1)
    interval = 1.0 / offered_qps
    lat_ms: List[Optional[float]] = [None] * total
    errors: List[str] = []
    err_lock = threading.Lock()

    def run_client(cid: int) -> None:
        client = ServingClient(address)
        try:
            client.wait_ready(10.0)
            for i in range(cid, total, n_clients):
                target = t0 + i * interval
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    client.predict(feed[i], timeout_s=timeout_s)
                    lat_ms[i] = (time.perf_counter() - target) * 1e3
                except Exception as e:  # noqa: BLE001 — tallied, not fatal
                    with err_lock:
                        errors.append(f"req {i}: {type(e).__name__}: {e}")
        except Exception as e:  # noqa: BLE001 — a client thread dying
            # pre-loop (wait_ready timeout) must not vanish its whole
            # request stripe: the accounting below turns every UNISSUED
            # request into an error, or 'zero failed requests' could
            # false-pass with 1/n_clients of the load never sent.
            with err_lock:
                errors.append(f"client {cid} died: {type(e).__name__}: {e}")
        finally:
            client.close()

    threads = [
        threading.Thread(target=run_client, args=(c,), daemon=True)
        for c in range(n_clients)
    ]
    t0 = time.perf_counter() + 0.05  # shared schedule epoch
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    done = [l for l in lat_ms if l is not None]
    from tools.artifact import latency_stats

    # Every scheduled request is accounted: completed, individually
    # errored, or unissued (a dead client thread's stripe) — the error
    # count is total minus completed, so "0 errors" really means every
    # request was sent AND answered.  latency_stats of an all-errors
    # point is {} — the row still stamps its tally and samples.
    out = {
        "offered_qps": offered_qps,
        "achieved_qps": round(len(done) / wall, 1),
        "n": len(done),
        "errors": total - len(done),
        # buckets=True: the shared histogram grid (tools/artifact.py) so
        # the artifact carries the tail SHAPE, not just p50/p99 points.
        **latency_stats(done, buckets=True),
    }
    if errors:
        out["error_samples"] = errors[:5]
    return out


def run_bench(
    qps_points: List[float],
    duration_s: float = 4.0,
    max_batch: int = 32,
    max_delay_ms: float = 5.0,
    n_clients: int = 8,
    buckets: int = 512,
    embedding_dim: int = 4,
    cache_rows: int = 1 << 20,
    reload_mid_run: bool = True,
    artifact_path: Optional[str] = None,
    artifact_name: str = "SERVE_r10.json",
) -> Dict:
    """The full bench: PS shard + seeded checkpoint + serving server, one
    point per offered QPS, hot reload during the MIDDLE point."""
    import tempfile

    import jax

    from elasticdl_tpu.common.checkpoint import CheckpointManager
    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer
    from elasticdl_tpu.ps.service import PSServer
    from elasticdl_tpu.serving.client import ServingClient
    from elasticdl_tpu.serving.server import ServingServer
    from tools.artifact import code_rev

    say = lambda m: print(m, file=sys.stderr, flush=True)
    spec = load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        buckets_per_feature=buckets, embedding_dim=embedding_dim,
        hidden=(32,), host_tier=True,
    )
    ps = PSServer(spec.host_io, shard=0, num_shards=1).start()
    tmp = tempfile.mkdtemp(prefix="serving_bench_")
    ckpt_dir = os.path.join(tmp, "ckpt")

    # Seed checkpoint: the "training side" publishing step 0.
    trainer = Trainer(
        spec,
        JobConfig(
            distribution_strategy=DistributionStrategy.ALLREDUCE,
            ps_addresses=ps.address,
        ),
        create_mesh([jax.devices()[0]]),
    )
    state0 = trainer.init_state(jax.random.key(0))
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(0, jax.device_get(state0), wait=True)
    mgr.publish(0, code_rev=code_rev())

    server = ServingServer(
        spec,
        checkpoint_dir=ckpt_dir,
        ps_addresses=ps.address,
        max_batch=max_batch,
        max_delay_ms=max_delay_ms,
        cache_rows=cache_rows,
        poll_interval_s=0.2,
        # graftgauge (r14): serve /metrics on an ephemeral port; the
        # bench scrapes it mid-point (under live load, around the hot
        # reload) and stamps the snapshot — the endpoint must answer
        # while the replica is busy, not just at rest.
        gauge_port=0,
    ).start()
    warmup_s = server.warmup()
    say(f"serving up on {server.address} (compile {warmup_s:.2f}s)")

    feed = _RequestFeed(n=4096, buckets=buckets)
    points = []
    reload_info: Dict = {"performed": False}
    live_metrics: Dict = {"endpoint": server.metrics_address}
    probe = ServingClient(server.address)
    try:
        probe.wait_ready(10.0)
        mid = len(qps_points) // 2
        for idx, qps in enumerate(qps_points):
            reloader = None
            if reload_mid_run and idx == mid:
                # Publish step 1 halfway through this point's window: the
                # swap lands under live load, and every request must still
                # succeed (the acceptance criterion).
                def do_reload():
                    time.sleep(duration_s / 2)
                    params = jax.device_get(state0.params)
                    params["dense_linear"]["b"] = params["dense_linear"]["b"] + 0.5
                    state1 = state0.replace(params=params)
                    mgr.save(1, jax.device_get(state1), wait=True)
                    t_pub = time.perf_counter()
                    mgr.publish(1, code_rev=code_rev())
                    deadline = t_pub + 20.0
                    while (probe.model_info()["step"] != 1
                           and time.perf_counter() < deadline):
                        time.sleep(0.02)
                    reload_info["publish_to_live_s"] = round(
                        time.perf_counter() - t_pub, 3
                    )

                reloader = threading.Thread(target=do_reload, daemon=True)
                reloader.start()
            scraper = None
            if idx == mid and server.metrics_address:
                # Mid-point live scrape: lands while this point's load
                # (and the reload, when enabled) is in flight.
                def do_scrape():
                    time.sleep(duration_s / 3)
                    try:
                        from tools.watch_job import fetch

                        fams = fetch(server.metrics_address, timeout_s=5.0)
                        live_metrics["snapshot"] = {
                            name: [
                                {"labels": s["labels"], "value": s["value"]}
                                for s in fam["samples"]
                            ]
                            for name, fam in sorted(fams.items())
                            if name.startswith("edl_serving")
                            and fam.get("type") != "histogram"
                        }
                        live_metrics["during_offered_qps"] = qps
                    except Exception as e:  # noqa: BLE001 — stamped, not fatal
                        live_metrics["error"] = f"{type(e).__name__}: {e}"

                scraper = threading.Thread(target=do_scrape, daemon=True)
                scraper.start()
            point = _drive_point(
                server.address, feed, qps, duration_s, n_clients
            )
            if scraper is not None:
                scraper.join(duration_s + 10.0)
            if reloader is not None:
                reloader.join(30.0)
                point["reload_during_point"] = True
                reload_info["performed"] = True
                reload_info["during_offered_qps"] = qps
                reload_info["failed_requests"] = point["errors"]
            points.append(point)
            say(f"  {qps:>6} QPS offered: p50 {point.get('p50_ms', '—')} ms, "
                f"p99 {point.get('p99_ms', '—')} ms, achieved "
                f"{point['achieved_qps']} ({point['errors']} errors)")
        info = probe.model_info()
        if reload_info.get("performed"):
            reload_info["live_swap_ms"] = info["last_swap_ms"]
            reload_info["restore_load_s"] = info["last_load_s"]
            reload_info["reloads"] = info["reloads"]
        result = {
            "metric": "serving_latency_vs_qps",
            "model": "deepfm(host_tier, buckets=%d, dim=%d)" % (buckets, embedding_dim),
            "transport": "grpc-loopback-json",
            "max_batch": max_batch,
            "max_delay_ms": max_delay_ms,
            "clients": n_clients,
            "duration_s_per_point": duration_s,
            "warmup_compile_s": round(warmup_s, 2),
            "points": points,
            "reload": reload_info,
            "live_metrics": live_metrics,
            "batcher": info["batcher"],
            "embedding_cache": info["cache"],
            "serving_step": info["step"],
            "code_rev": code_rev(),
        }
    finally:
        probe.close()
        server.stop()
        mgr.close()
        ps.stop()

    from tools.artifact import write_artifact

    write_artifact(result, artifact_name, env_var="SERVE_OUT",
                   path=artifact_path, log=say)
    total_errors = sum(p["errors"] for p in points)
    if total_errors:
        say(f"FAIL: {total_errors} failed request(s) across the run")
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--qps", default="50,100,200",
                    help="comma list of offered-QPS points (>= 3 for the "
                         "artifact contract)")
    ap.add_argument("--duration", type=float, default=4.0,
                    help="seconds per QPS point")
    ap.add_argument("--max_batch", type=int, default=32)
    ap.add_argument("--max_delay_ms", type=float, default=5.0)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--buckets", type=int, default=512,
                    help="hash buckets per categorical feature (id space = "
                         "26 * buckets)")
    ap.add_argument("--dim", type=int, default=4)
    ap.add_argument("--cache_rows", type=int, default=1 << 20)
    ap.add_argument("--no_reload", action="store_true",
                    help="skip the mid-run hot reload")
    ap.add_argument("--artifact", default=None)
    args = ap.parse_args()
    result = run_bench(
        [float(q) for q in args.qps.split(",") if q],
        duration_s=args.duration,
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        n_clients=args.clients,
        buckets=args.buckets,
        embedding_dim=args.dim,
        cache_rows=args.cache_rows,
        reload_mid_run=not args.no_reload,
        artifact_path=args.artifact,
    )
    print(json.dumps({"points": result["points"], "reload": result["reload"]}))
    return 1 if sum(p["errors"] for p in result["points"]) else 0


if __name__ == "__main__":
    sys.exit(main())
