"""Serving-tier latency/QPS bench — the r10 perf surface, plus the r19
fleet ramp.

Single-replica mode (the r10 surface) drives the online prediction service
(serving/server.ServingServer) the way production traffic would: a tiny
host-tier DeepFM whose sparse rows live in a real in-process PS shard
(ps/service.PSServer), real gRPC on loopback, open-loop arrivals at
several offered-QPS points, and — mid-run — a hot checkpoint reload that
must complete with ZERO failed requests.

Fleet mode (``--fleet``, the r19 surface) stands the whole scale tier up
for real: SUBPROCESS replicas (``python -m elasticdl_tpu.serving.main``
via ProcessPodBackend, warm-standby spares parked) behind a
ServingFleetController, traffic through the p2c FleetServingClient, a
constant bulk-lane flood riding under the online ramp, and the closed
autoscaling loop polling live per-replica /metrics.  The ramp goes UP past
one replica's knee and back DOWN, and the artifact records whether the
loop converged (monotone up-leg then down-leg, no flapping), what
aggregate QPS the fleet held inside the online-lane SLO, and the measured
single-replica knee on the same substrate — against the r10 record (knee
~145 QPS at max_batch=32, where 94% of forwarded rows were padding;
bucketed compiles are what moved it).

Latency is measured per request against its SCHEDULED arrival (open-loop):
a backlogged server shows up as queueing delay in the percentiles instead
of silently throttling the offered load — the honest way to read "can this
replica hold N QPS at a p99".

Stamps p50/p99 per offered-QPS point (plus the reload's live-path downtime
in single mode, the autoscale audit trail in fleet mode) into
``artifacts/SERVE_r10.json`` / ``artifacts/SERVE_r19.json`` (env override
SERVE_OUT) — the second first-class perf surface alongside examples/sec
(docs/perf.md).

Usage:
  python tools/serving_bench.py [--qps 50,100,200] [--duration 4]
      [--max_batch 32] [--max_delay_ms 5] [--clients 8] [--no_reload]
  python tools/serving_bench.py --fleet [--ramp 350:8,2200:10,600:12,...]
      [--single_qps 300,600,900,1200] [--replicas_max 3] [--bulk_qps 25]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_DENSE = 13
NUM_CAT = 26




class _RequestFeed:
    """Zipf-ish single-example feature generator: most categorical values
    draw from a small hot pool (the cache's reason to exist), the tail from
    the full bucket range — pre-generated so the load loop costs nothing."""

    def __init__(self, n: int, buckets: int, hot_pool: int = 200,
                 hot_frac: float = 0.8, seed: int = 0):
        rng = np.random.RandomState(seed)
        hot = rng.randint(0, buckets, size=(hot_pool, NUM_CAT))
        self.features: List[Dict[str, list]] = []
        for i in range(n):
            if rng.rand() < hot_frac:
                cat = hot[rng.randint(hot_pool)]
            else:
                cat = rng.randint(0, buckets, size=(NUM_CAT,))
            dense = rng.rand(NUM_DENSE) * 100.0
            self.features.append({
                "dense": [dense.round(3).tolist()],
                "cat": [cat.tolist()],
            })

    def __getitem__(self, i: int) -> Dict[str, list]:
        return self.features[i % len(self.features)]


def _drive_point(
    address: str,
    feed: _RequestFeed,
    offered_qps: float,
    duration_s: float,
    n_clients: int,
    timeout_s: float = 30.0,
) -> Dict:
    """Open-loop load: ``offered_qps * duration_s`` requests on a fixed
    schedule, striped over ``n_clients`` threads (each with its own channel
    — one client serializing everything would close the loop)."""
    from elasticdl_tpu.serving.client import ServingClient

    total = max(int(offered_qps * duration_s), 1)
    interval = 1.0 / offered_qps
    lat_ms: List[Optional[float]] = [None] * total
    errors: List[str] = []
    err_lock = threading.Lock()

    def run_client(cid: int) -> None:
        client = ServingClient(address)
        try:
            client.wait_ready(10.0)
            for i in range(cid, total, n_clients):
                target = t0 + i * interval
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    client.predict(feed[i], timeout_s=timeout_s)
                    lat_ms[i] = (time.perf_counter() - target) * 1e3
                except Exception as e:  # noqa: BLE001 — tallied, not fatal
                    with err_lock:
                        errors.append(f"req {i}: {type(e).__name__}: {e}")
        except Exception as e:  # noqa: BLE001 — a client thread dying
            # pre-loop (wait_ready timeout) must not vanish its whole
            # request stripe: the accounting below turns every UNISSUED
            # request into an error, or 'zero failed requests' could
            # false-pass with 1/n_clients of the load never sent.
            with err_lock:
                errors.append(f"client {cid} died: {type(e).__name__}: {e}")
        finally:
            client.close()

    threads = [
        threading.Thread(target=run_client, args=(c,), daemon=True)
        for c in range(n_clients)
    ]
    t0 = time.perf_counter() + 0.05  # shared schedule epoch
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    done = [l for l in lat_ms if l is not None]
    from tools.artifact import latency_stats

    # Every scheduled request is accounted: completed, individually
    # errored, or unissued (a dead client thread's stripe) — the error
    # count is total minus completed, so "0 errors" really means every
    # request was sent AND answered.  latency_stats of an all-errors
    # point is {} — the row still stamps its tally and samples.
    out = {
        "offered_qps": offered_qps,
        "achieved_qps": round(len(done) / wall, 1),
        "n": len(done),
        "errors": total - len(done),
        # buckets=True: the shared histogram grid (tools/artifact.py) so
        # the artifact carries the tail SHAPE, not just p50/p99 points.
        **latency_stats(done, buckets=True),
    }
    if errors:
        out["error_samples"] = errors[:5]
    return out


def run_bench(
    qps_points: List[float],
    duration_s: float = 4.0,
    max_batch: int = 32,
    max_delay_ms: float = 5.0,
    n_clients: int = 8,
    buckets: int = 512,
    embedding_dim: int = 4,
    cache_rows: int = 1 << 20,
    reload_mid_run: bool = True,
    artifact_path: Optional[str] = None,
    artifact_name: str = "SERVE_r10.json",
) -> Dict:
    """The full bench: PS shard + seeded checkpoint + serving server, one
    point per offered QPS, hot reload during the MIDDLE point."""
    import tempfile

    import jax

    from elasticdl_tpu.common.checkpoint import CheckpointManager
    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer
    from elasticdl_tpu.ps.service import PSServer
    from elasticdl_tpu.serving.client import ServingClient
    from elasticdl_tpu.serving.server import ServingServer
    from tools.artifact import code_rev

    say = lambda m: print(m, file=sys.stderr, flush=True)
    spec = load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        buckets_per_feature=buckets, embedding_dim=embedding_dim,
        hidden=(32,), host_tier=True,
    )
    ps = PSServer(spec.host_io, shard=0, num_shards=1).start()
    tmp = tempfile.mkdtemp(prefix="serving_bench_")
    ckpt_dir = os.path.join(tmp, "ckpt")

    # Seed checkpoint: the "training side" publishing step 0.
    trainer = Trainer(
        spec,
        JobConfig(
            distribution_strategy=DistributionStrategy.ALLREDUCE,
            ps_addresses=ps.address,
        ),
        create_mesh([jax.devices()[0]]),
    )
    state0 = trainer.init_state(jax.random.key(0))
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(0, jax.device_get(state0), wait=True)
    mgr.publish(0, code_rev=code_rev())

    server = ServingServer(
        spec,
        checkpoint_dir=ckpt_dir,
        ps_addresses=ps.address,
        max_batch=max_batch,
        max_delay_ms=max_delay_ms,
        cache_rows=cache_rows,
        poll_interval_s=0.2,
        # graftgauge (r14): serve /metrics on an ephemeral port; the
        # bench scrapes it mid-point (under live load, around the hot
        # reload) and stamps the snapshot — the endpoint must answer
        # while the replica is busy, not just at rest.
        gauge_port=0,
    ).start()
    warmup_s = server.warmup()
    say(f"serving up on {server.address} (compile {warmup_s:.2f}s)")

    feed = _RequestFeed(n=4096, buckets=buckets)
    points = []
    reload_info: Dict = {"performed": False}
    live_metrics: Dict = {"endpoint": server.metrics_address}
    probe = ServingClient(server.address)
    try:
        probe.wait_ready(10.0)
        mid = len(qps_points) // 2
        for idx, qps in enumerate(qps_points):
            reloader = None
            if reload_mid_run and idx == mid:
                # Publish step 1 halfway through this point's window: the
                # swap lands under live load, and every request must still
                # succeed (the acceptance criterion).
                def do_reload():
                    time.sleep(duration_s / 2)
                    params = jax.device_get(state0.params)
                    params["dense_linear"]["b"] = params["dense_linear"]["b"] + 0.5
                    state1 = state0.replace(params=params)
                    mgr.save(1, jax.device_get(state1), wait=True)
                    t_pub = time.perf_counter()
                    mgr.publish(1, code_rev=code_rev())
                    deadline = t_pub + 20.0
                    while (probe.model_info()["step"] != 1
                           and time.perf_counter() < deadline):
                        time.sleep(0.02)
                    reload_info["publish_to_live_s"] = round(
                        time.perf_counter() - t_pub, 3
                    )

                reloader = threading.Thread(target=do_reload, daemon=True)
                reloader.start()
            scraper = None
            if idx == mid and server.metrics_address:
                # Mid-point live scrape: lands while this point's load
                # (and the reload, when enabled) is in flight.
                def do_scrape():
                    time.sleep(duration_s / 3)
                    try:
                        from tools.watch_job import fetch

                        fams = fetch(server.metrics_address, timeout_s=5.0)
                        live_metrics["snapshot"] = {
                            name: [
                                {"labels": s["labels"], "value": s["value"]}
                                for s in fam["samples"]
                            ]
                            for name, fam in sorted(fams.items())
                            if name.startswith("edl_serving")
                            and fam.get("type") != "histogram"
                        }
                        live_metrics["during_offered_qps"] = qps
                    except Exception as e:  # noqa: BLE001 — stamped, not fatal
                        live_metrics["error"] = f"{type(e).__name__}: {e}"

                scraper = threading.Thread(target=do_scrape, daemon=True)
                scraper.start()
            point = _drive_point(
                server.address, feed, qps, duration_s, n_clients
            )
            if scraper is not None:
                scraper.join(duration_s + 10.0)
            if reloader is not None:
                reloader.join(30.0)
                point["reload_during_point"] = True
                reload_info["performed"] = True
                reload_info["during_offered_qps"] = qps
                reload_info["failed_requests"] = point["errors"]
            points.append(point)
            say(f"  {qps:>6} QPS offered: p50 {point.get('p50_ms', '—')} ms, "
                f"p99 {point.get('p99_ms', '—')} ms, achieved "
                f"{point['achieved_qps']} ({point['errors']} errors)")
        info = probe.model_info()
        if reload_info.get("performed"):
            reload_info["live_swap_ms"] = info["last_swap_ms"]
            reload_info["restore_load_s"] = info["last_load_s"]
            reload_info["reloads"] = info["reloads"]
        result = {
            "metric": "serving_latency_vs_qps",
            "model": "deepfm(host_tier, buckets=%d, dim=%d)" % (buckets, embedding_dim),
            "transport": "grpc-loopback-json",
            "max_batch": max_batch,
            "max_delay_ms": max_delay_ms,
            "clients": n_clients,
            "duration_s_per_point": duration_s,
            "warmup_compile_s": round(warmup_s, 2),
            "points": points,
            "reload": reload_info,
            "live_metrics": live_metrics,
            "batcher": info["batcher"],
            "embedding_cache": info["cache"],
            "serving_step": info["step"],
            "code_rev": code_rev(),
        }
    finally:
        probe.close()
        server.stop()
        mgr.close()
        ps.stop()

    from tools.artifact import write_artifact

    write_artifact(result, artifact_name, env_var="SERVE_OUT",
                   path=artifact_path, log=say)
    total_errors = sum(p["errors"] for p in points)
    if total_errors:
        say(f"FAIL: {total_errors} failed request(s) across the run")
    return result


def _fleet_clients_for(offered_qps: float, n_clients: int) -> int:
    """Client threads sized to the leg: an overload leg needs the full
    pool to keep the server's queue decisively past its bound, but an
    in-SLO leg driven by 128 mostly-idle threads measures GIL scheduling
    jitter in its own p99 — one spurious 100 ms wakeup stall on a single
    thread is a tail observation the server never saw."""
    return max(16, min(n_clients, int(offered_qps / 15.0)))


def _drive_fleet_point(
    fc,
    feed: _RequestFeed,
    offered_qps: float,
    duration_s: float,
    n_clients: int,
    timeout_s: float = 30.0,
) -> Dict:
    """Open-loop load through ONE SHARED FleetServingClient (p2c inflight
    counts are only meaningful when a single instance sees every thread's
    traffic — sharing it is the design, not a shortcut)."""
    n_clients = _fleet_clients_for(offered_qps, n_clients)
    total = max(int(offered_qps * duration_s), 1)
    interval = 1.0 / offered_qps
    lat_ms: List[Optional[float]] = [None] * total
    errors: List[str] = []
    err_lock = threading.Lock()

    def run_client(cid: int) -> None:
        for i in range(cid, total, n_clients):
            target = t0 + i * interval
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                fc.predict(feed[i], timeout_s=timeout_s, lane="online")
                lat_ms[i] = (time.perf_counter() - target) * 1e3
            except Exception as e:  # noqa: BLE001 — tallied, not fatal
                with err_lock:
                    errors.append(f"req {i}: {type(e).__name__}: {e}")

    threads = [
        threading.Thread(target=run_client, args=(c,), daemon=True)
        for c in range(n_clients)
    ]
    t0 = time.perf_counter() + 0.05
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    done = [l for l in lat_ms if l is not None]
    from tools.artifact import latency_stats

    out = {
        "offered_qps": offered_qps,
        "achieved_qps": round(len(done) / wall, 1),
        "n": len(done),
        "clients": n_clients,
        "errors": total - len(done),
        **latency_stats(done, buckets=True),
    }
    if errors:
        out["error_samples"] = errors[:5]
    return out


class _BulkFlood:
    """Constant bulk-lane pressure under the online ramp: fixed-rate
    multi-row Predicts on lane="bulk", shed losses tallied (a shed bulk
    request is the priority design WORKING, not an error).  Client-side
    counting survives replica retirement — server-side lane counters die
    with the replica that held them."""

    def __init__(self, fc, feed: _RequestFeed, qps: float, rows: int = 8):
        self._fc = fc
        self._qps = qps
        payload_n = 64
        self._payloads = []
        for i in range(payload_n):
            rows_f = [feed[i * rows + j] for j in range(rows)]
            self._payloads.append({
                "dense": [r["dense"][0] for r in rows_f],
                "cat": [r["cat"][0] for r in rows_f],
            })
        self.ok = 0
        self.shed = 0
        self.failed = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="bench-bulk-flood", daemon=True
        )

    def _loop(self) -> None:
        import grpc

        i = 0
        interval = 1.0 / self._qps
        next_t = time.perf_counter()
        while not self._stop.is_set():
            delay = next_t - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            next_t += interval
            try:
                self._fc.predict(
                    self._payloads[i % len(self._payloads)],
                    timeout_s=30.0, lane="bulk",
                )
                self.ok += 1
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    self.shed += 1  # BatcherOverloaded: shed-bulk-first
                else:
                    self.failed += 1
            except Exception:  # noqa: BLE001 — tallied, not fatal
                self.failed += 1
            i += 1

    def start(self) -> "_BulkFlood":
        self._thread.start()
        return self

    def stop(self) -> Dict:
        self._stop.set()
        self._thread.join(10.0)
        return {
            "offered_qps": self._qps,
            "rows_per_request": len(self._payloads[0]["cat"]),
            "ok": self.ok, "shed": self.shed, "failed": self.failed,
        }


def run_fleet_bench(
    ramp: List[tuple],
    single_qps: List[float],
    duration_single_s: float = 4.0,
    replicas_max: int = 3,
    max_batch: int = 32,
    max_delay_ms: float = 5.0,
    batch_buckets: tuple = (2, 8, 32),
    n_clients: int = 128,
    max_workers: int = 160,
    # Queue bound well UNDER the client concurrency: a decisive overload
    # must overflow into online-lane sheds — the autoscaler's crisp,
    # immediate up signal — rather than sit at a queue depth whose p99
    # oscillates around the SLO threshold and never earns up_consecutive.
    max_queue_rows: int = 48,
    buckets: int = 512,
    embedding_dim: int = 4,
    cache_rows: int = 1 << 20,
    target_p99_ms: float = 100.0,
    bulk_qps: float = 25.0,
    base_port: int = 8700,
    metrics_base_port: int = 8800,
    standby_pool: int = 1,
    artifact_path: Optional[str] = None,
    artifact_name: str = "SERVE_r19.json",
) -> Dict:
    """The r19 fleet ramp: subprocess replicas + warm standby + p2c client
    + the closed autoscaling loop, measured end to end.

    Phase 1 pins the fleet at ONE replica and sweeps ``single_qps`` to
    find this substrate's knee (highest offered point holding the online
    SLO with zero errors).  Phase 2 runs the ``ramp`` — (offered_qps,
    duration_s) legs that climb past that knee and come back down — with
    the autoscale control loop live, a constant bulk-lane flood underneath,
    and membership refresh feeding the p2c client the controller's
    readiness view.  The artifact stamps the scale-event audit trail and a
    convergence verdict: the loop must act monotonically (ups, then downs,
    ending at min replicas) — any direction reversal is flapping."""
    import tempfile

    import jax

    from elasticdl_tpu.common.checkpoint import CheckpointManager
    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer
    from elasticdl_tpu.ps.service import PSServer
    from elasticdl_tpu.serving.client import FleetServingClient
    from elasticdl_tpu.serving.fleet import (
        AutoscaleConfig, ServingFleetController,
    )
    from elasticdl_tpu.master.pod_manager import ProcessPodBackend
    from tools.artifact import code_rev, write_artifact

    say = lambda m: print(m, file=sys.stderr, flush=True)
    spec = load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        buckets_per_feature=buckets, embedding_dim=embedding_dim,
        hidden=(32,), host_tier=True,
    )
    ps = PSServer(spec.host_io, shard=0, num_shards=1).start()
    tmp = tempfile.mkdtemp(prefix="serving_fleet_bench_")
    ckpt_dir = os.path.join(tmp, "ckpt")

    trainer = Trainer(
        spec,
        JobConfig(
            distribution_strategy=DistributionStrategy.ALLREDUCE,
            ps_addresses=ps.address,
        ),
        create_mesh([jax.devices()[0]]),
    )
    state0 = trainer.init_state(jax.random.key(0))
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(0, jax.device_get(state0), wait=True)
    mgr.publish(0, code_rev=code_rev())

    serving_cfg = {
        "model_zoo": "elasticdl_tpu.models",
        "model_def": "deepfm.model_spec",
        "model_params": {
            "buckets_per_feature": buckets, "embedding_dim": embedding_dim,
            "hidden": [32], "host_tier": True,
        },
        "checkpoint_dir": ckpt_dir,
        "ps_addresses": ps.address,
        "max_batch": max_batch,
        "max_delay_ms": max_delay_ms,
        "cache_rows": cache_rows,
        "batch_buckets": list(batch_buckets),
        "target_p99_ms": target_p99_ms,
        "base_port": base_port,
        "metrics_base_port": metrics_base_port,
        # Handler pool above the queue bound: overload must land in the
        # batcher's measured, shedding queue (the autoscaler's signals),
        # never invisibly in the gRPC executor.
        "max_workers": max_workers,
        "max_queue_rows": max_queue_rows,
    }
    auto = AutoscaleConfig(
        min_replicas=1,
        max_replicas=replicas_max,
        poll_s=1.0,
        target_p99_ms=target_p99_ms,
        up_consecutive=2,
        down_consecutive=4,
        cooldown_polls=2,
        # 3x the client's 0.5s membership-refresh cadence: the victim is
        # guaranteed out of every client's pick set before its pod dies,
        # and cooldown (2 polls x 1s) still covers the drain window.
        drain_s=1.5,
    )
    backend = ProcessPodBackend(
        argv=[sys.executable, "-m", "elasticdl_tpu.serving.main"],
        warm_standby=True,
        standby_pool=standby_pool,
        log_dir=os.path.join(tmp, "logs"),
    )
    ctl = ServingFleetController(
        backend,
        JobConfig(job_name="serve-bench", ps_addresses=ps.address),
        base_port=base_port,
        metrics_base_port=metrics_base_port,
        # GRAFT_JITSAN=1 arms the compile-budget sanitizer IN EVERY
        # REPLICA: an over-budget predict_step retrace raises in the
        # flush path, failing requests — so zero errors at the in-SLO
        # points plus zero relaunches IS the "no over-budget retraces"
        # evidence this artifact stamps.
        worker_env={
            "ELASTICDL_SERVING_CONFIG": json.dumps(serving_cfg),
            "GRAFT_JITSAN": "1",
            "JAX_PLATFORMS": "cpu",
        },
        autoscale_enabled=False,  # the bench drives poll_once itself
        autoscale=auto,
        state_path=os.path.join(tmp, "fleet_state.json"),
    )

    feed = _RequestFeed(n=4096, buckets=buckets)
    result: Dict = {}
    try:
        say("booting replica 0 (cold: subprocess pays the full jax import)")
        t_boot = time.perf_counter()
        ctl.start(1)
        ctl.wait_ready(1, timeout_s=180.0)
        say(f"replica 0 ready in {time.perf_counter() - t_boot:.1f}s")

        fc = FleetServingClient(ctl.ready_addresses())

        # ---- phase 1: single-replica knee on THIS substrate ----
        single_points = []
        for qps in single_qps:
            pt = _drive_fleet_point(fc, feed, qps, duration_single_s,
                                    n_clients)
            single_points.append(pt)
            say(f"  single {qps:>6} QPS: p50 {pt.get('p50_ms', '—')} ms, "
                f"p99 {pt.get('p99_ms', '—')} ms ({pt['errors']} errors)")
        # Knee = highest clean point BELOW the first failure: the sweep
        # ascends, so a later point passing after an earlier one failed is
        # box noise, not recovered capacity — a non-monotone "knee" would
        # overstate what the replica sustains.
        knee = None
        for pt in single_points:
            if (pt["errors"] == 0
                    and pt.get("p99_ms") is not None
                    and pt["p99_ms"] <= target_p99_ms
                    and pt["achieved_qps"] >= 0.9 * pt["offered_qps"]):
                knee = pt["offered_qps"]
            else:
                break

        # Settle, then absorb the sweep's history into the scrape baseline
        # (first scrape of a replica has no prev: its p99 would read the
        # WHOLE sweep, and the ramp's first decision would act on stale
        # pressure).  The second poll sees only the quiet settle window.
        time.sleep(2.0)
        ctl.poll_once()
        time.sleep(1.5)
        ctl.poll_once()

        # ---- phase 2: the ramp, control loop live ----
        decisions: List[dict] = []
        ready_samples: List[tuple] = []
        stop_aux = threading.Event()
        t_ramp0 = time.monotonic()

        def poll_loop() -> None:
            while not stop_aux.wait(auto.poll_s):
                try:
                    d = ctl.poll_once()
                    d["t"] = round(time.monotonic() - t_ramp0, 2)
                    decisions.append(d)
                except Exception as e:  # noqa: BLE001 — logged, loop lives
                    decisions.append(
                        {"t": round(time.monotonic() - t_ramp0, 2),
                         "error": f"{type(e).__name__}: {e}"}
                    )

        def refresh_loop() -> None:
            last_n = -1
            while not stop_aux.wait(0.5):
                try:
                    addrs = ctl.ready_addresses()
                except Exception:  # noqa: BLE001 — next tick retries
                    continue
                if addrs:
                    fc.set_replicas(addrs)
                if len(addrs) != last_n:
                    last_n = len(addrs)
                    ready_samples.append(
                        (round(time.monotonic() - t_ramp0, 2), last_n)
                    )

        aux = [
            threading.Thread(target=poll_loop, daemon=True,
                             name="bench-autoscale"),
            threading.Thread(target=refresh_loop, daemon=True,
                             name="bench-membership"),
        ]
        for t in aux:
            t.start()
        flood = _BulkFlood(fc, feed, qps=bulk_qps).start()

        ramp_points = []
        for qps, dur in ramp:
            pt = _drive_fleet_point(fc, feed, qps, dur, n_clients)
            counts = ctl.pods.counts()
            pt["replicas_live"] = counts["live"]
            pt["replicas_desired"] = counts["desired"]
            ramp_points.append(pt)
            say(f"  ramp {qps:>6} QPS x{dur}s: p50 {pt.get('p50_ms', '—')} "
                f"ms, p99 {pt.get('p99_ms', '—')} ms, achieved "
                f"{pt['achieved_qps']} ({pt['errors']} errors, "
                f"{counts['live']} replicas)")
        # Let the loop finish converging down after the last leg's load.
        tail_deadline = time.monotonic() + 20.0
        while (time.monotonic() < tail_deadline
               and ctl.pods.desired() > auto.min_replicas):
            time.sleep(0.5)

        stop_aux.set()
        for t in aux:
            t.join(5.0)
        bulk = flood.stop()

        # ---- audits ----
        events = ctl.events()
        directions = [1 if e["to"] > e["from"] else -1 for e in events]
        reversals = sum(
            1 for a, b in zip(directions, directions[1:]) if a != b
        )
        final_counts = ctl.pods.counts()
        convergence = {
            # One reversal is the ramp's own shape (up-leg then down-leg);
            # any more means the loop oscillated against a steady signal.
            "flaps": max(0, reversals - 1),
            "direction_trace": directions,
            "final_replicas": final_counts["live"],
            "final_desired": final_counts["desired"],
            "relaunches": final_counts["relaunches"],
            "converged": (
                max(0, reversals - 1) == 0
                and final_counts["desired"] == auto.min_replicas
            ),
        }
        # Warm-standby payoff: time from each scale-up decision to the
        # new replica answering its readiness probe.
        scale_up_ready_s = []
        for e, d in zip(events, directions):
            if d != 1:
                continue
            t_evt = e["t"] - t_ramp0
            t_ready = next(
                (ts for ts, n in ready_samples
                 if ts >= t_evt and n >= e["to"]), None
            )
            if t_ready is not None:
                scale_up_ready_s.append(round(t_ready - t_evt, 2))

        # Best aggregate the fleet held INSIDE the online SLO: the number
        # the ISSUE's ">= 3x the r10 knee" criterion reads.
        sla_points = [
            p for p in ramp_points
            if p["errors"] == 0 and p.get("p99_ms") is not None
            and p["p99_ms"] <= target_p99_ms
        ]
        best_sla = max(sla_points, key=lambda p: p["achieved_qps"],
                       default=None)
        window_sheds = {
            "online": sum(d.get("shed_online", 0.0) for d in decisions),
            "bulk": sum(
                d.get("shed_total", 0.0) - d.get("shed_online", 0.0)
                for d in decisions
            ),
        }

        r10_knee = 145.0  # artifacts/SERVE_r10.json: p99 crossed the SLO
        result = {
            "metric": "serving_fleet_ramp",
            "model": "deepfm(host_tier, buckets=%d, dim=%d)"
                     % (buckets, embedding_dim),
            "transport": "grpc-loopback-json",
            "replica_substrate": "subprocess (ProcessPodBackend, "
                                 "warm_standby pool=%d)" % standby_pool,
            "max_batch": max_batch,
            "max_delay_ms": max_delay_ms,
            "batch_buckets": list(batch_buckets),
            "clients": n_clients,
            "replica_max_workers": max_workers,
            "replica_max_queue_rows": max_queue_rows,
            "sla_target_p99_ms": target_p99_ms,
            "autoscale": {
                "min_replicas": auto.min_replicas,
                "max_replicas": auto.max_replicas,
                "poll_s": auto.poll_s,
                "up_slo": auto.up_slo,
                "down_slo": auto.down_slo,
                "up_consecutive": auto.up_consecutive,
                "down_consecutive": auto.down_consecutive,
                "cooldown_polls": auto.cooldown_polls,
            },
            "single_replica": {
                "points": single_points,
                "knee_qps": knee,
                "r10_knee_qps": r10_knee,
                "knee_over_r10": (
                    round(knee / r10_knee, 2) if knee else None
                ),
            },
            "ramp": {
                "points": ramp_points,
                "bulk_flood": bulk,
            },
            "aggregate": {
                "best_sla_qps": (
                    best_sla["achieved_qps"] if best_sla else None
                ),
                "p99_at_best_sla_ms": (
                    best_sla.get("p99_ms") if best_sla else None
                ),
                "replicas_at_best_sla": (
                    best_sla.get("replicas_live") if best_sla else None
                ),
                "over_r10_knee": (
                    round(best_sla["achieved_qps"] / r10_knee, 2)
                    if best_sla else None
                ),
            },
            "scale_events": [
                {**{k: e[k] for k in ("from", "to", "slo", "shed_online")},
                 "t": round(e["t"] - t_ramp0, 2)}
                for e in events
            ],
            "scale_up_ready_s": scale_up_ready_s,
            "convergence": convergence,
            "ready_transitions": ready_samples,
            "decisions": decisions,
            "sheds_by_lane_windowed": window_sheds,
            "jitsan": {
                "armed_in_replicas": True,
                "predict_step_budget_per_replica": len(
                    sorted(set(list(batch_buckets) + [max_batch]))
                ),
                # With the sanitizer armed, an over-budget retrace raises
                # inside the flush path (failed requests) — so the proof
                # of zero over-budget retraces is zero errors at the
                # in-SLO points plus zero replica relaunches.
                "replica_relaunches": final_counts["relaunches"],
            },
            "code_rev": code_rev(),
        }
        fc.close()
    finally:
        ctl.stop()
        backend.close()
        mgr.close()
        ps.stop()

    write_artifact(result, artifact_name, env_var="SERVE_OUT",
                   path=artifact_path, log=say)
    return result


def _parse_ramp(spec: str) -> List[tuple]:
    """``"350:8,1100:12"`` -> [(350.0, 8.0), (1100.0, 12.0)]."""
    out = []
    for leg in spec.split(","):
        if not leg:
            continue
        qps, _, dur = leg.partition(":")
        out.append((float(qps), float(dur) if dur else 10.0))
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--qps", default="50,100,200",
                    help="comma list of offered-QPS points (>= 3 for the "
                         "artifact contract)")
    ap.add_argument("--duration", type=float, default=4.0,
                    help="seconds per QPS point")
    ap.add_argument("--max_batch", type=int, default=32)
    ap.add_argument("--max_delay_ms", type=float, default=5.0)
    ap.add_argument("--clients", type=int, default=None,
                    help="client threads (default 8 single-replica, 128 "
                         "fleet — fleet overload must out-run one replica)")
    ap.add_argument("--buckets", type=int, default=512,
                    help="hash buckets per categorical feature (id space = "
                         "26 * buckets)")
    ap.add_argument("--dim", type=int, default=4)
    ap.add_argument("--cache_rows", type=int, default=1 << 20)
    ap.add_argument("--no_reload", action="store_true",
                    help="skip the mid-run hot reload")
    ap.add_argument("--artifact", default=None)
    ap.add_argument("--fleet", action="store_true",
                    help="r19 fleet ramp: subprocess replicas + autoscaler "
                         "(stamps SERVE_r19.json instead)")
    ap.add_argument("--ramp",
                    default="350:8,2200:10,600:12,450:12,450:12,"
                            "250:12,250:10",
                    help="fleet ramp legs as offered_qps:duration_s — a "
                         "blowout leg past the single-replica knee forces "
                         "scale-up, then an SLA plateau the scaled fleet "
                         "serves clean (600 is the stretch point, 450 the "
                         "3x-r10 margin point on a contended box), then "
                         "quiet legs for the downs")
    ap.add_argument("--single_qps", default="300,600,900,1200",
                    help="fleet phase-1 single-replica knee sweep")
    ap.add_argument("--replicas_max", type=int, default=3)
    ap.add_argument("--bulk_qps", type=float, default=25.0,
                    help="constant bulk-lane flood rate under the ramp")
    ap.add_argument("--slo_ms", type=float, default=100.0,
                    help="online-lane p99 SLO target (fleet mode)")
    ap.add_argument("--base_port", type=int, default=8700)
    ap.add_argument("--metrics_base_port", type=int, default=8800)
    ap.add_argument("--standby_pool", type=int, default=1)
    args = ap.parse_args()
    if args.fleet:
        result = run_fleet_bench(
            _parse_ramp(args.ramp),
            [float(q) for q in args.single_qps.split(",") if q],
            replicas_max=args.replicas_max,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            n_clients=args.clients or 128,
            buckets=args.buckets,
            embedding_dim=args.dim,
            cache_rows=args.cache_rows,
            target_p99_ms=args.slo_ms,
            bulk_qps=args.bulk_qps,
            base_port=args.base_port,
            metrics_base_port=args.metrics_base_port,
            standby_pool=args.standby_pool,
            artifact_path=args.artifact,
        )
        print(json.dumps({
            "single_replica": result["single_replica"],
            "aggregate": result["aggregate"],
            "scale_events": result["scale_events"],
            "convergence": result["convergence"],
        }))
        return 0 if result["convergence"]["converged"] else 1
    result = run_bench(
        [float(q) for q in args.qps.split(",") if q],
        duration_s=args.duration,
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        n_clients=args.clients or 8,
        buckets=args.buckets,
        embedding_dim=args.dim,
        cache_rows=args.cache_rows,
        reload_mid_run=not args.no_reload,
        artifact_path=args.artifact,
    )
    print(json.dumps({"points": result["points"], "reload": result["reload"]}))
    return 1 if sum(p["errors"] for p in result["points"]) else 0


if __name__ == "__main__":
    sys.exit(main())
