#!/bin/bash
# One-shot on-chip measurement battery (round 4; probe-hardened round 5).
# Run from the repo root with the real TPU reachable; each stage appends its
# JSON to the log.  Stages are ordered headline-first so a mid-battery chip
# flake still leaves the most important artifacts.  NEVER run two stages
# concurrently.
#
# The twice-recorded chip failure mode is a HANG in jax.devices(), which a
# stage timeout only converts into a 600 s burn per stage.  So: a killable
# subprocess probe (elasticdl_tpu.common.platform.probe_devices) gates the
# battery — generous attempts at preflight (chip flaky at minute 0, fine at
# minute 5 should still yield a full battery), quick re-probe before each
# later stage so a mid-battery outage skips cleanly instead of eating every
# remaining stage's timeout.
set -u
LOG=${1:-/tmp/chip_battery.log}
echo "== chip battery $(date -u +%H:%M:%S)" | tee -a "$LOG"

probe() {  # $1 = attempts (x90s each)
  python -c "from elasticdl_tpu.common.platform import probe_devices as p; p(attempts=$1, timeout_s=90)" >>"$LOG" 2>&1
}

run() {
  local name=$1; shift
  if ! probe "${PROBE_ATTEMPTS:-3}"; then
    echo "-- $name SKIPPED: chip unreachable at probe" | tee -a "$LOG"
    return
  fi
  echo "-- $name" | tee -a "$LOG"
  # The battery's probe above just passed; the tools' internal probes would
  # pay a redundant backend init each — skip them (platform.probe_devices).
  EDL_SKIP_PROBE=1 timeout 600 "$@" 2>>"$LOG" | tee -a "$LOG"
  # rc of the benchmarked command, not tee's (124 = timeout kill)
  echo "-- rc=${PIPESTATUS[0]}" | tee -a "$LOG"
}

# Preflight: be patient once (up to ~12 min of probing) before the first
# stage; later stages use the quick 3-attempt probe.
if ! probe 8; then
  echo "== chip unreachable at preflight; battery aborted" | tee -a "$LOG"
  exit 3
fi

run "bench.py (headline: e2e DeepFM)"      python bench.py
run "bench_all (configs 1-3 + MFU)"        python tools/bench_all.py
run "train_job (full stack artifact)"      python tools/train_job_tpu.py
run "async depth sweep (host tier)"        python tools/async_depth_bench.py --steps 20
echo "== battery done $(date -u +%H:%M:%S)" | tee -a "$LOG"
