#!/bin/bash
# One-shot on-chip measurement battery (round 4).  Run from the repo root
# with the real TPU reachable; each stage appends its JSON to the log.
# Stages are ordered headline-first so a mid-battery chip flake still
# leaves the most important artifacts.  NEVER run two stages concurrently.
set -u
LOG=${1:-/tmp/chip_battery.log}
echo "== chip battery $(date -u +%H:%M:%S)" | tee -a "$LOG"

run() {
  echo "-- $1" | tee -a "$LOG"
  shift
  timeout 600 "$@" 2>>"$LOG" | tee -a "$LOG"
  # rc of the benchmarked command, not tee's (124 = timeout kill)
  echo "-- rc=${PIPESTATUS[0]}" | tee -a "$LOG"
}

run "bench.py (headline: e2e DeepFM)"      python bench.py
run "bench_all (configs 1-3 + MFU)"        python tools/bench_all.py
run "train_job (full stack artifact)"      python tools/train_job_tpu.py
run "async depth sweep (host tier)"        python tools/async_depth_bench.py --steps 20
echo "== battery done $(date -u +%H:%M:%S)" | tee -a "$LOG"
