"""Smoke-test the REAL ``lax.ragged_all_to_all`` HLO on live TPU hardware.

CI tests run on XLA:CPU, which lacks this HLO, so they exercise the
identical routing code through the ``ragged_emulated`` collective; the bench
takes the dense short-circuit at n=1.  This script is the hardware proof:
an n=1 TPU mesh with an EXPLICIT ``impl="ragged"`` (honored for exactly this
purpose) runs the op forward AND backward (custom_vjp) and checks numerics
against a plain gather.

Last verified: 2026-07-30 on v5e ("REAL ragged_all_to_all HLO: fwd+bwd
(custom_vjp) executed on TPU, numerics match").

Usage: python tools/ragged_smoke.py   (needs the TPU; do not run concurrently
with other chip users)
"""

def main() -> None:
    # Heavy imports deferred to here: importing this module (lint/CLI
    # paths) must never touch — or hang on — the chip; apply_platform_env
    # still runs before the first framework jax use.
    from elasticdl_tpu.common.platform import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.common.jax_compat import jit_compiled, shard_map
    from elasticdl_tpu.ops.embedding import (
        ParallelContext,
        embedding_lookup,
        pack_table,
    )
    from elasticdl_tpu.parallel.mesh import create_mesh

    devices = jax.devices()
    assert devices[0].platform == "tpu", f"needs TPU, got {devices}"
    mesh = create_mesh(devices)
    axis = mesh.axis_names[0]
    table = jax.random.normal(jax.random.key(0), (256, 16), jnp.float32)
    packed = pack_table(table, 16)
    ids = jax.random.randint(jax.random.key(1), (64,), 0, 256)
    cot = jax.random.normal(jax.random.key(2), (64, 16))
    ctx = ParallelContext(
        axis_name=axis, sharded_embeddings=True, embedding_impl="ragged"
    )

    def fwd_bwd(t, i, c):
        def loss(tt):
            return jnp.sum(embedding_lookup(tt, i, ctx, dim=16) * c)

        return jax.value_and_grad(loss)(t)

    mapped = shard_map(
        fwd_bwd,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(), P(axis)),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))  # noqa: E731
    # graftlint: allow[jit-stability] one-shot smoke: main runs once per process, and its single compile is the HLO under test
    step = jit_compiled(mapped, name="ragged_smoke.fwd_bwd")
    val, grad = step(sh(packed), sh(ids), sh(cot))

    exp_val = float(jnp.sum(jnp.take(table, ids, axis=0) * cot))
    exp_grad = jax.grad(lambda t: jnp.sum(jnp.take(t, ids, axis=0) * cot))(table)
    np.testing.assert_allclose(float(val), exp_val, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grad).reshape(-1, 16)[:256], np.asarray(exp_grad), rtol=1e-5
    )
    print(
        "REAL ragged_all_to_all HLO: fwd+bwd (custom_vjp) executed on TPU, "
        "numerics match"
    )


if __name__ == "__main__":
    main()
