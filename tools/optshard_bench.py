"""Sharded-optimizer bench: per-replica optimizer-state bytes and step time,
replicated vs ZeRO-sharded (--optimizer_sharding), at 1/2/4-way data
parallelism — the r11 number of record (artifacts/OPTSHARD_r11.json).

Three measurement families, each in its OWN subprocess so the XLA fake
device count (fixed at backend init) and peak RSS (monotonic per process)
are honest per point:

- sweep: for dp in {1, 2, 4} x mode in {replicated, sharded}: max
  per-device resident optimizer bytes (Trainer.opt_state_bytes_per_device)
  and steady-state step time on a synthetic Criteo-shaped batch.  The
  sharded claim is bytes <= replicated/dp + padding at equal-or-better
  step time.
- donation A/B: the same config with --donate_train_state on/off; the
  delta in peak RSS is the second resident state copy donation removes
  (ROADMAP item 1's cheap half — measurable on CPU).
- parity: one process builds BOTH modes at dp=4, trains N identical
  steps, and reports the max abs param divergence (float32 reduction-
  order noise between psum and psum_scatter — docs/architecture.md) plus
  a bit-exactness check that a 2->4->2 resize preserves the moments.

The model is DeepFM in AllReduce strategy: tables are then REPLICATED
dense params, so the Adam moments are the classic fully-replicated state
the sharding exists to cut (in ParameterServer strategy the table slots
already co-shard with the rows and only the MLP state is at stake).

Usage:
    python tools/optshard_bench.py [--buckets 4096] [--batch 1024]
        [--steps 10] [--out artifacts/OPTSHARD_r11.json]
Env override for the artifact path: OPTSHARD_OUT.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DP_SWEEP = (1, 2, 4)
WARMUP = 3


def _child_env(dp: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={dp}"
    )
    return env


def _load(args):
    """Child-side model/trainer build (jax already initialized)."""
    import jax

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    spec = load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        compute_dtype="float32",
        buckets_per_feature=args.buckets,
        embedding_dim=8,
        hidden=(64, 64),
    )

    def trainer(mode: str, num_devices: int, donate: bool = True) -> Trainer:
        cfg = JobConfig(
            optimizer_sharding=mode, donate_train_state=donate
        )
        return Trainer(
            spec, cfg, create_mesh(jax.devices(), num_devices=num_devices)
        )

    return spec, trainer


def _batch(n: int):
    import numpy as np

    rng = np.random.default_rng(7)
    return {
        "dense": rng.uniform(0, 1000, (n, 13)).astype(np.float32),
        "cat": rng.integers(0, 1 << 30, (n, 26)).astype(np.int64),
        "labels": (rng.uniform(size=(n,)) < 0.25).astype(np.int32),
    }


def child_measure(args) -> dict:
    import jax

    spec, make = _load(args)
    dp = args.dp
    n = max(args.batch // dp * dp, dp)
    t = make(args.mode, dp, donate=bool(args.donate))
    state = t.init_state(jax.random.key(0))
    opt_bytes = t.opt_state_bytes_per_device(state)
    batch = t.shard_batch(_batch(n))
    state, m = t.train_step(state, batch)  # compile
    jax.block_until_ready(m)
    for _ in range(WARMUP):
        state, m = t.train_step(state, batch)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = t.train_step(state, batch)
    jax.block_until_ready(m)
    dt = (time.perf_counter() - t0) / args.steps
    # ru_maxrss is KB on linux; the peak includes compile scratch, so the
    # donation A/B compares two identically-compiled runs.
    peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return {
        "dp": dp,
        "mode": args.mode,
        "donate": bool(args.donate),
        "opt_bytes_per_device_max": max(opt_bytes.values()),
        "step_ms": round(dt * 1e3, 3),
        "examples_per_sec": round(n / dt, 1),
        "global_batch": n,
        "peak_rss_mb": round(peak_kb / 1024.0, 1),
        "loss": round(float(m["loss"]), 6),
    }


def child_parity(args) -> dict:
    import jax
    import numpy as np

    spec, make = _load(args)
    dp = args.dp
    n = max(args.batch // dp * dp, dp)
    tr = make("replicated", dp)
    ts = make("sharded", dp)
    sr = tr.init_state(jax.random.key(0))
    ss = ts.init_state(jax.random.key(0))
    host = _batch(n)
    for _ in range(args.steps):
        sr, _ = tr.train_step(sr, tr.shard_batch(host))
        ss, _ = ts.train_step(ss, ts.shard_batch(host))
    diffs = [
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        if a.size
        else 0.0
        for a, b in zip(
            jax.tree.leaves(jax.device_get(sr.params)),
            jax.tree.leaves(jax.device_get(ss.params)),
        )
    ]
    # Elastic 2->4->2 moment preservation, bit-exact: the canonical host
    # layout bridges every resize, so the redistributed flat shards must
    # reassemble to the identical moments.
    h0 = ts.host_state(ss)
    from elasticdl_tpu.parallel.mesh import create_mesh

    preserved = True
    for size in (2, dp, 2):
        ts.set_mesh(create_mesh(jax.devices(), num_devices=size))
        ss = ts.shard_state(h0)
        h1 = ts.host_state(ss)
        preserved = preserved and all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(h0), jax.tree.leaves(h1))
        )
    return {
        "dp": dp,
        "steps": args.steps,
        "max_abs_param_diff": max(diffs),
        "moments_preserved_2_4_2": preserved,
    }


def _spawn(extra, dp: int, log) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__), "--child"] + extra
    log(f"run {' '.join(extra)}")
    out = subprocess.run(
        cmd,
        env=_child_env(dp),
        capture_output=True,
        text=True,
        timeout=600,
        cwd=_REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"child {extra} failed rc={out.returncode}: {out.stderr[-800:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_bench(args, log=None) -> dict:
    log = log or (lambda m: print(f"[optshard] {m}", file=sys.stderr, flush=True))
    base = [
        "--buckets", str(args.buckets),
        "--batch", str(args.batch),
        "--steps", str(args.steps),
    ]
    sweep = []
    for dp in DP_SWEEP:
        for mode in ("replicated", "sharded"):
            row = _spawn(
                base + ["--task", "measure", "--mode", mode, "--dp", str(dp)],
                dp, log,
            )
            sweep.append(row)
            log(
                f"dp={dp} {mode}: {row['opt_bytes_per_device_max']:,} "
                f"opt B/device, {row['step_ms']} ms/step"
            )
    by = {(r["dp"], r["mode"]): r for r in sweep}
    checks = {}
    for dp in DP_SWEEP:
        if dp == 1:
            continue
        rep, sh = by[(dp, "replicated")], by[(dp, "sharded")]
        # "<= replicated/dp + padding": padding is bounded by one flat
        # shard row per param-shaped leaf; 5% covers it at bench sizes.
        checks[f"bytes_ok_dp{dp}"] = (
            sh["opt_bytes_per_device_max"]
            <= rep["opt_bytes_per_device_max"] / dp * 1.05
        )
        checks[f"step_ratio_dp{dp}"] = round(
            sh["step_ms"] / rep["step_ms"], 3
        )
    donation = {}
    for donate in (1, 0):
        row = _spawn(
            base + [
                "--task", "measure", "--mode", "replicated",
                "--dp", "1", "--donate", str(donate),
            ],
            1, log,
        )
        donation["on" if donate else "off"] = row
    donation["delta_mb"] = round(
        donation["off"]["peak_rss_mb"] - donation["on"]["peak_rss_mb"], 1
    )
    log(f"donation peak-RSS delta: {donation['delta_mb']} MB")
    parity = _spawn(
        base + ["--task", "parity", "--mode", "sharded", "--dp", "4"], 4, log
    )
    log(f"parity: {parity}")
    return {
        "metric": "optimizer_sharding_bytes_and_step",
        "model": f"deepfm AllReduce buckets={args.buckets} dim=8 hidden=(64,64)",
        "sweep": sweep,
        "checks": checks,
        "donation": donation,
        "parity": parity,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python tools/optshard_bench.py")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--task", default="measure", choices=("measure", "parity"))
    ap.add_argument(
        "--mode", default="replicated", choices=("replicated", "sharded")
    )
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--donate", type=int, default=1)
    ap.add_argument("--buckets", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    if args.child:
        result = (
            child_parity(args) if args.task == "parity" else child_measure(args)
        )
        print(json.dumps(result), flush=True)
        return 0
    result = run_bench(args)
    from tools.artifact import code_rev, write_artifact

    result["code_rev"] = code_rev()
    write_artifact(
        result, "OPTSHARD_r11.json", env_var="OPTSHARD_OUT",
        path=args.out or None,
    )
    print(json.dumps(result["checks"]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
