"""Long-context single-chip capability bench — trains the GPT-2-small-shape
transformer at increasing sequence lengths on ONE chip and records the
longest that fits plus its throughput.

What makes the long lengths possible: per-block rematerialization plus the
Pallas flash-attention kernel (ops/flash_attention.py).  Measured split of
credit at L=8192 (2026-07-31): remat alone lets the XLA attention path
squeeze b=2 through — its O(L^2) score tensors ([b,12,8192,8192] f32 =
6.4 GB at b=2) become per-block transients — but b=4 OOMs there, while the
flash path (attention memory O(L*D)) runs it; at L=1024 the same kernel is
what made global batch 32 fit at all (19 GB of saved probability tensors
gone).  Beyond one chip's HBM, ring-attention sequence parallelism
(ops/ring_attention.py) shards L over the mesh; that path is
CPU-mesh-tested (tests/test_ring_attention.py) since this environment has
one physical chip.

Throughput caveat: wall-clock per step on the tunneled chip includes a
large, shape-dependent execute-turnaround overhead (the L=2048 row's wall
exceeds its ~57 ms/step device self-time several-fold; block_until_ready
returns before execution completes on this backend, so steps settle via
the loss fetch).  Treat tokens_per_s as a lower bound.  Each length row
therefore ALSO records trace-derived device self-time
(``device_step_ms`` / ``device_tokens_per_s``, same xplane instrument as
tools/profile_step.py) — the repo's measurement rule says per-op trace
time, not wall, is the number of record on this link, and the committed
r5 walls (L=2048 at 929 ms vs L=4096 at 376 ms) are exactly the kind of
bimodal-wire nonsense the rule exists to keep out of artifacts.

Usage: python tools/longcontext_bench.py [--lengths 2048,4096,8192]
One JSON line per length; artifact: artifacts/longcontext_r05.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elasticdl_tpu.common.platform import apply_platform_env, enable_compile_cache

apply_platform_env()


def _trace_device_step_ms(out_dir: str, steps: int):
    """Per-step device self-time (ms) from the xplane trace; None when the
    trace toolchain is unavailable (the wall numbers still emit — device
    time is the better instrument, not a new hard dependency)."""
    try:
        from tools.gather_experiments import trace_total_device_us

        return trace_total_device_us(out_dir)["total_us"] / steps / 1000.0
    except Exception as e:  # noqa: BLE001 — best-effort instrumentation
        print(f"[longcontext] trace parse unavailable: {str(e)[:200]}",
              file=sys.stderr)
        return None


def bench_length(seq: int, batch: int, steps: int = 5) -> dict:
    import jax
    import numpy as np

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    spec = load_model_spec(
        "elasticdl_tpu.models", "transformer_lm.model_spec",
        vocab=32768, dim=768, n_heads=12, n_layers=12,
        seq_len=seq, max_seq=seq, remat=True,
    )
    trainer = Trainer(
        spec, JobConfig(distribution_strategy="AllReduce"),
        create_mesh(jax.devices()),
    )
    try:
        state = trainer.init_state(jax.random.key(0))
        seqs = jax.random.randint(jax.random.key(1), (batch, seq + 1), 0, 32768)
        b = trainer.shard_batch({"tokens": seqs[:, :-1], "labels": seqs[:, 1:]})
        state, m = trainer.train_step(state, b)
        # Settle the warmup via a fetch — block_until_ready returns before
        # execution completes on this backend (see module docstring).
        np.asarray(jax.device_get(m["loss"]))
        # Wall timing runs UNTRACED — live xplane collection inflates wall
        # time, and these fields must stay comparable to the untraced r5
        # walls the artifact series quotes.
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = trainer.train_step(state, b)
        # settles all steps
        loss = float(np.asarray(jax.device_get(m["loss"])))
        dt = (time.perf_counter() - t0) / steps
        # Device self-time from a SEPARATE traced set of steps (trace
        # overhead lands on wall, not on device self-time, so the traced
        # steps measure the same thing).
        # Fresh dir per run: trace_total_device_us parses the newest
        # xplane under it, and a stale file from a previous invocation
        # would silently stamp the OLD run's device time into this row
        # if this run's trace fails to flush.
        import shutil

        trace_dir = f"/tmp/longcontext_trace_L{seq}"
        shutil.rmtree(trace_dir, ignore_errors=True)
        os.makedirs(trace_dir)
        tracing = True
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception:  # a live outer trace or missing profiler support
            tracing = False
        traced_ok = True
        try:
            if tracing:
                for _ in range(steps):
                    state, m = trainer.train_step(state, b)
                np.asarray(jax.device_get(m["loss"]))  # settle before stop
        except Exception as e:  # noqa: BLE001 — degrade, don't discard
            # The traced re-run can fail where the untraced steps passed
            # (xplane collection adds device-memory/overhead pressure, and
            # near-OOM lengths are exactly where this runs): the wall row
            # already measured above must not be thrown to the outer OOM
            # handler — degrade to wall-only for this length.
            print(f"[longcontext] traced re-run failed, wall-only row: "
                  f"{str(e)[:200]}", file=sys.stderr)
            traced_ok = False
        finally:
            # Stop on the failure path too (an OOM row is expected data):
            # a trace left live would poison the NEXT length's wall numbers
            # with collection overhead and make its start_trace fail,
            # silently dropping every later device_step_ms.
            if tracing:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
        dev_ms = (
            _trace_device_step_ms(trace_dir, steps)
            if tracing and traced_ok else None
        )
        row = {
            "seq_len": seq, "batch": batch, "ok": True,
            "step_ms": round(dt * 1e3, 1),
            "tokens_per_s": round(batch * seq / dt),
            "loss": round(loss, 3),
        }
        # Device self-time rides beside the wall numbers (measurement rule:
        # trace time is the number of record on the tunneled link).
        if dev_ms is not None:
            row["device_step_ms"] = round(dev_ms, 1)
            if dev_ms > 0:
                row["device_tokens_per_s"] = round(
                    batch * seq / (dev_ms / 1e3)
                )
        return row
    except Exception as e:  # noqa: BLE001 — OOM is a data point here
        msg = str(e)
        oom = "memory" in msg.lower() or "hbm" in msg.lower()
        return {
            "seq_len": seq, "batch": batch, "ok": False,
            "error": "OOM" if oom else msg[:200],
        }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lengths", default="2048,4096,8192")
    # b=4 is the committed artifact's configuration AND the credit-split
    # claim (XLA+remat fits b=2 but OOMs b=4; flash runs b=4).
    ap.add_argument("--batch", type=int, default=4)
    args = ap.parse_args()
    from elasticdl_tpu.common.platform import probe_devices

    probe_devices(attempts=3, timeout_s=90)
    enable_compile_cache()
    results = []
    try:
        for seq in (int(s) for s in args.lengths.split(",")):
            r = bench_length(seq, args.batch)
            results.append(r)
            print(json.dumps(r), flush=True)
    finally:
        if results:
            from tools.artifact import write_artifact

            write_artifact(
                {
                    "metric": "longcontext_single_chip",
                    "model": "transformer_lm 12L/768d/12h vocab 32768, "
                             "remat + pallas flash attention",
                    "lengths": results,
                },
                "longcontext_r05.json", env_var="LONGCONTEXT_OUT",
            )


if __name__ == "__main__":
    main()
