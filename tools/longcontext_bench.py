"""Long-context single-chip capability bench — trains the GPT-2-small-shape
transformer at increasing sequence lengths on ONE chip and records the
longest that fits plus its throughput.

What makes the long lengths possible: per-block rematerialization plus the
Pallas flash-attention kernel (ops/flash_attention.py).  Measured split of
credit at L=8192 (2026-07-31): remat alone lets the XLA attention path
squeeze b=2 through — its O(L^2) score tensors ([b,12,8192,8192] f32 =
6.4 GB at b=2) become per-block transients — but b=4 OOMs there, while the
flash path (attention memory O(L*D)) runs it; at L=1024 the same kernel is
what made global batch 32 fit at all (19 GB of saved probability tensors
gone).  Beyond one chip's HBM, ring-attention sequence parallelism
(ops/ring_attention.py) shards L over the mesh; that path is
CPU-mesh-tested (tests/test_ring_attention.py) since this environment has
one physical chip.

Throughput caveat: wall-clock per step on the tunneled chip includes a
large, shape-dependent execute-turnaround overhead (the L=2048 row's wall
exceeds its ~57 ms/step device self-time several-fold; block_until_ready
returns before execution completes on this backend, so steps settle via
the loss fetch).  Treat tokens_per_s as a lower bound; per-op device time
(tools/profile_step.py --config transformer_lm) is the honest instrument.

Usage: python tools/longcontext_bench.py [--lengths 2048,4096,8192]
One JSON line per length; artifact: artifacts/longcontext_r05.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elasticdl_tpu.common.platform import apply_platform_env, enable_compile_cache

apply_platform_env()


def bench_length(seq: int, batch: int, steps: int = 5) -> dict:
    import jax
    import numpy as np

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    spec = load_model_spec(
        "elasticdl_tpu.models", "transformer_lm.model_spec",
        vocab=32768, dim=768, n_heads=12, n_layers=12,
        seq_len=seq, max_seq=seq, remat=True,
    )
    trainer = Trainer(
        spec, JobConfig(distribution_strategy="AllReduce"),
        create_mesh(jax.devices()),
    )
    try:
        state = trainer.init_state(jax.random.key(0))
        seqs = jax.random.randint(jax.random.key(1), (batch, seq + 1), 0, 32768)
        b = trainer.shard_batch({"tokens": seqs[:, :-1], "labels": seqs[:, 1:]})
        state, m = trainer.train_step(state, b)
        # Settle the warmup via a fetch — block_until_ready returns before
        # execution completes on this backend (see module docstring).
        np.asarray(jax.device_get(m["loss"]))
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = trainer.train_step(state, b)
        loss = float(np.asarray(jax.device_get(m["loss"])))  # settles all steps
        dt = (time.perf_counter() - t0) / steps
        return {
            "seq_len": seq, "batch": batch, "ok": True,
            "step_ms": round(dt * 1e3, 1),
            "tokens_per_s": round(batch * seq / dt),
            "loss": round(loss, 3),
        }
    except Exception as e:  # noqa: BLE001 — OOM is a data point here
        msg = str(e)
        oom = "memory" in msg.lower() or "hbm" in msg.lower()
        return {
            "seq_len": seq, "batch": batch, "ok": False,
            "error": "OOM" if oom else msg[:200],
        }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lengths", default="2048,4096,8192")
    # b=4 is the committed artifact's configuration AND the credit-split
    # claim (XLA+remat fits b=2 but OOMs b=4; flash runs b=4).
    ap.add_argument("--batch", type=int, default=4)
    args = ap.parse_args()
    from elasticdl_tpu.common.platform import probe_devices

    probe_devices(attempts=3, timeout_s=90)
    enable_compile_cache()
    results = []
    try:
        for seq in (int(s) for s in args.lengths.split(",")):
            r = bench_length(seq, args.batch)
            results.append(r)
            print(json.dumps(r), flush=True)
    finally:
        if results:
            from tools.artifact import write_artifact

            write_artifact(
                {
                    "metric": "longcontext_single_chip",
                    "model": "transformer_lm 12L/768d/12h vocab 32768, "
                             "remat + pallas flash attention",
                    "lengths": results,
                },
                "longcontext_r05.json", env_var="LONGCONTEXT_OUT",
            )


if __name__ == "__main__":
    main()
