#!/bin/sh
# graftlint pre-commit hook: lint changed files (plus their module-level
# dependents — project-wide passes judge whole-graph properties) before
# every commit.  Pure stdlib, no jax import: costs milliseconds.
#
# The v6 passes ride --changed like the rest: jit-shim and jit-stability
# are per-file (scoped to the changed set), and transfer-discipline is a
# project pass over the v2/v5 call graph, so its findings follow the SAME
# dependent-module scoping as import-hygiene — edit a '# jit-boundary'
# helper and every hot-path module that calls it re-lints.
#
# So do the v7 durability passes (r21): both are project passes, so they
# always SEE the whole file set (every '# durable-file' constant resolves
# even when its declaring module didn't change) while reporting stays
# scoped to the changed files plus their dependents.
#
# And the v8 wire passes (r22): both are project passes too — the
# MessageSchema index in common/rpc.py resolves from the full file set
# even when only a sender or handler module changed, and a schema edit
# re-judges wire-evolution against artifacts/wire_schema.lock.json
# (regenerate with tools/graftlint.py --update-wire-lock in the SAME
# diff as any schema change).
#
# Install (from the repo root):
#     ln -sf ../../tools/precommit.sh .git/hooks/pre-commit
# or, to keep an existing hook, call this script from it.
#
# Bypass for a work-in-progress commit (the tier-1 gate still runs the
# full lint): git commit --no-verify
set -u
repo_root="$(git rev-parse --show-toplevel)" || exit 2
cd "$repo_root" || exit 2
exec python tools/graftlint.py --changed
