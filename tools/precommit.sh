#!/bin/sh
# graftlint pre-commit hook: lint changed files (plus their module-level
# dependents — project-wide passes judge whole-graph properties) before
# every commit.  Pure stdlib, no jax import: costs milliseconds.
#
# Install (from the repo root):
#     ln -sf ../../tools/precommit.sh .git/hooks/pre-commit
# or, to keep an existing hook, call this script from it.
#
# Bypass for a work-in-progress commit (the tier-1 gate still runs the
# full lint): git commit --no-verify
set -u
repo_root="$(git rev-parse --show-toplevel)" || exit 2
cd "$repo_root" || exit 2
exec python tools/graftlint.py --changed
