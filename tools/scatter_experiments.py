"""Backward-path (table-gradient) formulation experiments on the live chip.

The round-3 profile (docs/perf.md) puts the embedding scatter-add at 2.85 ms
— 42% of the DeepFM step — at ~13 ns per touched row, op-rate-bound.  This
tool measures candidate reformulations of JUST the backward table-grad
computation, trace-derived like tools/gather_experiments.py:

- ``baseline``      — what ships: unsorted scatter-add of [N,128] rows.
- ``sorted_flags``  — sort ids, permute grad rows (a gather — measured 5x
  cheaper per row than scatter), segment-sum duplicate runs, then
  scatter-add with ``indices_are_sorted=True`` +  ``unique_indices=True`` so
  XLA can use a monotonic lowering.
- ``sort_only``     — just the argsort + permute + segment-sum, no scatter:
  isolates the pipeline overhead from the sorted-scatter win.
- ``scatter_sorted_presorted`` — the sorted+unique scatter-add alone on
  ALREADY sorted unique indices: the upper bound of the sorted lowering.

Each variant is profiled in its own trace dir; per-op device times printed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elasticdl_tpu.common.platform import (  # noqa: E402
    apply_platform_env,
    enable_compile_cache,
)
from tools.gather_experiments import trace_total_device_us  # noqa: E402

# jax globals populated by _init_jax() (same lazy pattern as
# gather_experiments): module import stays cheap and chip-free for
# --help/lint paths; function bodies resolve the names at call time.
jax = None
jnp = None
lax = None


def _init_jax() -> None:
    global jax, jnp, lax
    if jax is not None:
        return
    apply_platform_env()
    import jax as _jax
    import jax.numpy as _jnp
    from jax import lax as _lax

    jax, jnp, lax = _jax, _jnp, _lax

B, F = 8192, 26
N = B * F                 # 212,992 touched rows per step
BUCKETS = 65536
V = F * BUCKETS
DIM = 8
PACK = 128 // DIM
P = V // PACK             # 106,496 physical rows
W = 128


def _scatter_rows(table, rows_idx, updates, sorted_unique: bool):
    """scatter-add ``updates`` [N, W] into ``table`` [P, W] at rows_idx."""
    dnums = lax.ScatterDimensionNumbers(
        update_window_dims=(1,),
        inserted_window_dims=(0,),
        scatter_dims_to_operand_dims=(0,),
    )
    return lax.scatter_add(
        table,
        rows_idx[:, None].astype(jnp.int32),
        updates,
        dnums,
        indices_are_sorted=sorted_unique,
        unique_indices=sorted_unique,
        mode=lax.GatherScatterMode.FILL_OR_DROP,
    )


def baseline(ids, grads):
    zeros = jnp.zeros((P, W), jnp.float32)
    return _scatter_rows(zeros, ids, grads, sorted_unique=False)


def _sorted_segments(ids, grads):
    """argsort ids, permute grad rows, segment-sum equal-id runs.

    Returns (unique-ish row ids [N], summed rows [N, W]) where duplicate
    positions hold zeros and a sentinel row id P (dropped by FILL_OR_DROP) —
    static shapes, no host round-trip.
    """
    order = jnp.argsort(ids)
    sids = ids[order]
    srows = grads[order]                       # the 0.5ms-class gather
    # Run boundaries: position i starts a new run when sids[i] != sids[i-1].
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sids[1:] != sids[:-1]]
    )
    # Segment-sum via inclusive cumsum differencing: css[i] = sum rows[0..i];
    # for a run ending at j (last position before next run or N-1), the run
    # sum = css[j] - css[start-1].  Take per-run sums at run STARTS.
    css = jnp.cumsum(srows, axis=0)
    # Run ends via the "next run's start - 1" trick.  Padding slots fill
    # with N (NOT N-1): a fill of N-1 would masquerade as a real start at
    # the last position and clip the LAST run's end to N-2, silently
    # dropping the final sorted row from its segment sum.
    start_pos = jnp.nonzero(first, size=N, fill_value=N)[0]       # [N] padded
    n_runs = jnp.sum(first.astype(jnp.int32))
    next_start = jnp.concatenate([start_pos[1:], jnp.array([N])])
    end_pos = jnp.clip(next_start - 1, 0, N - 1)
    safe_start = jnp.minimum(start_pos, N - 1)
    run_sums = css[end_pos] - jnp.where(
        (safe_start == 0)[:, None], 0.0, css[jnp.maximum(safe_start - 1, 0)]
    )
    run_rows = sids[safe_start]
    # Mask padded run slots (beyond n_runs) to sentinel P -> dropped.
    valid = jnp.arange(N) < n_runs
    run_rows = jnp.where(valid, run_rows, P)
    run_sums = jnp.where(valid[:, None], run_sums, 0.0)
    return run_rows, run_sums


def sorted_flags(ids, grads):
    rows, sums = _sorted_segments(ids, grads)
    zeros = jnp.zeros((P, W), jnp.float32)
    return _scatter_rows(zeros, rows, sums, sorted_unique=True)


def sort_only(ids, grads):
    rows, sums = _sorted_segments(ids, grads)
    return rows.astype(jnp.float32).sum() + sums.sum()


def scatter_sorted_presorted(ids, grads):
    # ids pre-sorted & unique by construction at call site.
    zeros = jnp.zeros((P, W), jnp.float32)
    return _scatter_rows(zeros, ids, grads, sorted_unique=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--variants",
        default="baseline,sorted_flags,sort_only,scatter_sorted_presorted",
    )
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--outbase", default="/tmp/sexp")
    args = ap.parse_args()
    _init_jax()
    enable_compile_cache()
    from elasticdl_tpu.common.jax_compat import jit_compiled

    print(f"devices: {jax.devices()}", file=sys.stderr)

    kids = jax.random.randint(jax.random.key(1), (N,), 0, V) // PACK
    kids = kids.astype(jnp.int32)
    grads = jax.random.normal(jax.random.key(2), (N, W))
    # presorted unique indices for the upper-bound variant
    presorted = (jnp.arange(N, dtype=jnp.int32) * P) // N

    fns = {
        "baseline": (baseline, kids),
        "sorted_flags": (sorted_flags, kids),
        "sort_only": (sort_only, kids),
        "scatter_sorted_presorted": (scatter_sorted_presorted, presorted),
    }
    results = {}
    for name in args.variants.split(","):
        fn, ids = fns[name]
        # graftlint: allow[jit-stability] bench main runs once per process; one fresh compile per measured scatter variant IS the experiment
        step = jit_compiled(fn, name=f"scatter_experiments.{name}")
        try:
            t0 = time.perf_counter()
            out = step(ids, grads)
            jax.block_until_ready(out)
            compile_s = time.perf_counter() - t0
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}: {str(e)[:300]}",
                  file=sys.stderr)
            continue
        for _ in range(2):
            out = step(ids, grads)
        jax.block_until_ready(out)
        out_dir = f"{args.outbase}_{name}"
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
        for _ in range(args.steps):
            out = step(ids, grads)
        jax.block_until_ready(out)
        jax.profiler.stop_trace()
        stats = trace_total_device_us(out_dir)
        dev_ms = stats["total_us"] / args.steps / 1000
        results[name] = dev_ms
        print(f"== {name}: device {dev_ms:.2f} ms/step (compile {compile_s:.1f}s)",
              file=sys.stderr)
        top = sorted(stats["per_op"].items(), key=lambda kv: -kv[1][1])[:6]
        for opname, (occ, us) in top:
            print(f"     {us/args.steps/1000:9.3f} ms  x{int(occ/args.steps):>7} "
                  f" {opname[:90]}", file=sys.stderr)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
