"""Compare embedding gather/scatter formulations on the live chip via
trace-derived per-op device times (wall-clock micros on this tunneled chip
are bimodal — VERDICT r2 Weak #2; per-op times from the xplane trace are the
honest instrument).

Each variant computes forward lookup + backward table-grad for the DeepFM
shape: ids [8192, 26] into a 1.7M-row table, dim 8.  We profile each variant
in its own trace dir and report total device time per step.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elasticdl_tpu.common.platform import (  # noqa: E402
    apply_platform_env,
    enable_compile_cache,
)

B, F = 8192, 26
BUCKETS = 65536
V = F * BUCKETS          # 1,703,936
DIM = 8
PACK = 128 // DIM        # 16 logical rows per 128-lane physical row

# jax globals are populated by _init_jax(): importing this module must stay
# cheap and chip-free — scatter_experiments imports it just for
# trace_total_device_us, and --help/lint paths must never pay (or hang on)
# a backend init.  Function bodies resolve these names at CALL time, so
# everything below works unchanged once main() has run _init_jax().
jax = None
jnp = None
lax = None
_GATHER_DNUMS = None


def _init_jax() -> None:
    global jax, jnp, lax, _GATHER_DNUMS
    if jax is not None:
        return
    apply_platform_env()
    import jax as _jax
    import jax.numpy as _jnp
    from jax import lax as _lax

    jax, jnp, lax = _jax, _jnp, _lax
    _GATHER_DNUMS = lax.GatherDimensionNumbers(
        offset_dims=(1,), collapsed_slice_dims=(), start_index_map=(0,)
    )


def flat_lookup(flat, ids):
    """Current design: 1-D flat table, per-row slice gather (FILL_OR_DROP)."""
    starts = (ids.reshape(-1, 1) * DIM).astype(jnp.int32)
    out = lax.gather(flat, starts, _GATHER_DNUMS, slice_sizes=(DIM,),
                     mode=lax.GatherScatterMode.FILL_OR_DROP,
                     fill_value=jnp.nan)
    return out.reshape(B, F, DIM)


def take2d_clip(table2d, ids):
    """2-D [V, 8] take, clip mode."""
    return jnp.take(table2d, ids, axis=0, mode="clip")


def take2d_fill(table2d, ids):
    """2-D [V, 8] take, fill (FILL_OR_DROP) mode."""
    return jnp.take(table2d, ids, axis=0, mode="fill", fill_value=jnp.nan)


def onehot_matmul(table3d, ids):
    """Per-feature one-hot matmul: [B, BUCKETS] @ [BUCKETS, DIM] on the MXU.

    table3d: [F, BUCKETS, DIM].  ids are global (feature-offset) ids.
    """
    local = ids - jnp.arange(F)[None, :] * BUCKETS          # [B, F]
    oh = jax.nn.one_hot(local, BUCKETS, dtype=jnp.bfloat16)  # [B, F, BUCKETS]
    out = jnp.einsum("bfv,fvd->bfd", oh, table3d.astype(jnp.bfloat16))
    return out.astype(jnp.float32)


def packed_lookup_width(packed, ids, width):
    """Packed rows of an arbitrary element width (dtype from the table):
    gather full physical rows, lane-select.  width=128 f32 is the shipped
    layout; bf16 at width 128 halves bytes/row (256B), bf16 at width 256
    keeps 512B rows with double pack."""
    pack = width // DIM
    hi = ids // pack
    lo = ids % pack
    rows = jnp.take(packed, hi.reshape(-1), axis=0)        # [B*F, width]
    rows = rows.reshape(B * F, pack, DIM)
    sel = jax.nn.one_hot(lo.reshape(-1), pack, dtype=rows.dtype)
    out = jnp.einsum("npd,np->nd", rows, sel)
    return out.reshape(B, F, DIM)


def _packed_table(key, width, dtype=None):
    # dtype default resolved at call time (module import is jax-free).
    dtype = jnp.float32 if dtype is None else dtype
    rows = V // (width // DIM)
    return jax.random.normal(key, (rows, width)).astype(dtype)


VARIANTS = {
    "flat": (lambda key: jax.random.normal(key, (V * DIM,)), flat_lookup),
    "take2d_clip": (lambda key: jax.random.normal(key, (V, DIM)), take2d_clip),
    "take2d_fill": (lambda key: jax.random.normal(key, (V, DIM)), take2d_fill),
    "packed": (
        lambda key: _packed_table(key, 128),
        lambda t, ids: packed_lookup_width(t, ids, 128),
    ),
    "packed_bf16_w128": (
        lambda key: _packed_table(key, 128, jnp.bfloat16),
        lambda t, ids: packed_lookup_width(t, ids, 128),
    ),
    "packed_bf16_w256": (
        lambda key: _packed_table(key, 256, jnp.bfloat16),
        lambda t, ids: packed_lookup_width(t, ids, 256),
    ),
    "onehot": (lambda key: jax.random.normal(key, (F, BUCKETS, DIM)), onehot_matmul),
}


def trace_total_device_us(out_dir: str) -> dict:
    paths = sorted(glob.glob(os.path.join(out_dir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    from xprof.convert import raw_to_tool_data as rtd
    data, _ = rtd.xspace_to_tool_data([paths[-1]], "framework_op_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    tbl = json.loads(data)[0]
    cols = [c['label'] for c in tbl['cols']]
    i_name, i_tot = cols.index('Operation Name'), cols.index('Total self-time (us)')
    i_occ = cols.index('#Occurrences')
    per_op = {}
    total = 0.0
    for r in tbl['rows']:
        vals = [c.get('v') for c in r['c']]
        name = vals[i_name]
        if name == 'IDLE':
            continue
        per_op[name] = (vals[i_occ], vals[i_tot])
        total += vals[i_tot]
    return {"total_us": total, "per_op": per_op}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--variants", default=",".join(VARIANTS))
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--outbase", default="/tmp/gexp")
    args = ap.parse_args()
    _init_jax()
    enable_compile_cache()
    from elasticdl_tpu.common.jax_compat import jit_compiled

    print(f"devices: {jax.devices()}", file=sys.stderr)

    key = jax.random.key(0)
    ids = jax.random.randint(jax.random.key(1), (B, F), 0, BUCKETS) \
        + jnp.arange(F)[None, :] * BUCKETS
    ids = ids.astype(jnp.int32)

    results = {}
    for name in args.variants.split(","):
        init, fn = VARIANTS[name]
        table = init(key)

        def loss(t):
            out = fn(t, ids)
            return jnp.sum(out * out)

        # graftlint: allow[jit-stability] bench main runs once per process; one fresh compile per measured lookup variant IS the experiment
        step = jit_compiled(
            jax.grad(loss), name=f"gather_experiments.{name}"
        )
        try:
            t0 = time.perf_counter()
            g = step(table)
            jax.block_until_ready(g)
            compile_s = time.perf_counter() - t0
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}: {str(e)[:200]}",
                  file=sys.stderr)
            continue
        for _ in range(2):
            g = step(table)
        jax.block_until_ready(g)
        out_dir = f"{args.outbase}_{name}"
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            g = step(table)
        jax.block_until_ready(g)
        wall = (time.perf_counter() - t0) / args.steps
        jax.profiler.stop_trace()
        stats = trace_total_device_us(out_dir)
        dev_ms = stats["total_us"] / args.steps / 1000
        results[name] = dev_ms
        print(f"== {name}: device {dev_ms:.2f} ms/step  (wall {wall*1e3:.2f} "
              f"ms, compile {compile_s:.1f}s)", file=sys.stderr)
        top = sorted(stats["per_op"].items(), key=lambda kv: -kv[1][1])[:6]
        for opname, (occ, us) in top:
            print(f"     {us/args.steps/1000:9.3f} ms  x{int(occ/args.steps):>7} "
                  f" {opname[:90]}", file=sys.stderr)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
