"""mesh2d_bench — the 2D (data x model) mesh's numbers of record
(artifacts/MESH2D_r20.json).

Three measurement families, each in its OWN subprocess so the XLA fake
device count (fixed at backend init) is honest per point:

- parity: tensor-mode transformer_lm trained 1D (dp=2) and 2D (dp=2,
  tp=2) on IDENTICAL batches in one process; the column/row-split
  projections plus the tp psum must reproduce the dense math, so the
  max abs loss divergence over the run is float32 reduction-order noise
  (the row-split matmul sums 1/tp partials through ``tp_all_reduce``).
  The ISSUE 17 acceptance bar is <= 1e-6 after 10 steps.
- sweep: step time + the analytic inter-host bytes model
  (Trainer.collective_bytes_per_step) across (dp, tp) factorizations of
  8 devices.  The grad reduce runs over dp ONLY and each rank reduces
  1/tp of every tp-sharded leaf, so resolved bytes fall monotonically
  as tp rises — the traffic the 2D layout exists to not move.
- chaos: an in-process Worker job (tensor_parallelism=4, sharded
  optimizer, jitsan armed) loses a phantom host mid-job and gets it
  back: tp-major 4x2 -> 4x1 -> 4x2 (dp 2 -> 1 -> 2, tp preserved by
  mesh.resolve_2d_shape).  Every re-partition must carry the Adam
  moments BIT-EXACTLY through the canonical host bridge, the job must
  finish exactly-once, and trainer.train_step must re-lower exactly
  once per topology (3 total) with zero jitsan over-budget retraces.

Usage:
    python tools/mesh2d_bench.py [--steps 10] [--out artifacts/MESH2D_r20.json]
    python tools/mesh2d_bench.py --smoke    # parity (4 steps) + chaos
                                            # (bench_all --mesh2d-smoke)
Env override for the artifact path: MESH2D_OUT.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

#: (dp, tp) factorizations of the 8-device pool, widest tp last.
SWEEP_SHAPES = ((8, 1), (4, 2), (2, 4), (1, 8))
WARMUP = 3


def _child_env(n_devices: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    )
    # The chaos family's compile accounting (and the zero-over-budget
    # claim) only means something with the sanitizer armed.
    env["GRAFT_JITSAN"] = "1"
    return env


def _spec(n_heads: int = 4, dim: int = 32, seq: int = 64):
    """Child-side tensor-mode transformer_lm (import order: trainer
    before models — the ops<->parallel import cycle predates r20)."""
    from elasticdl_tpu.parallel.trainer import Trainer  # noqa: F401

    from elasticdl_tpu.models.spec import load_model_spec

    return load_model_spec(
        "elasticdl_tpu.models", "transformer_lm.model_spec",
        vocab=256, dim=dim, n_heads=n_heads, n_layers=2,
        max_seq=seq, seq_len=seq, compute_dtype="float32",
        parallelism="tensor",
    )


def _batch(rng, b: int, seq: int, vocab: int = 256):
    import numpy as np

    toks = rng.integers(0, vocab, size=(b, seq + 1)).astype(np.int32)
    return {"tokens": toks[:, :-1], "labels": toks[:, 1:]}


def child_parity(args) -> dict:
    import jax
    import numpy as np

    from elasticdl_tpu.parallel.trainer import Trainer
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.parallel.mesh import create_mesh

    seq = 32
    spec2d, spec1d = _spec(seq=seq), _spec(seq=seq)
    cfg = JobConfig(distribution_strategy="AllReduce")
    t2 = Trainer(spec2d, cfg, create_mesh(num_devices=4, tensor_parallelism=2))
    t1 = Trainer(spec1d, cfg, create_mesh(num_devices=2))
    s2 = t2.init_state(jax.random.key(0))
    s1 = t1.init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    diffs = []
    for _ in range(args.steps):
        host = _batch(rng, 8, seq)
        s2, m2 = t2.train_step(s2, t2.shard_batch(host))
        s1, m1 = t1.train_step(s1, t1.shard_batch(host))
        diffs.append(abs(float(m2["loss"]) - float(m1["loss"])))
    p2 = jax.tree.leaves(jax.device_get(s2.params))
    p1 = jax.tree.leaves(jax.device_get(s1.params))
    param_diff = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b)))) if a.size else 0.0
        for a, b in zip(p2, p1)
    )
    return {
        "shapes": {"flat": {"dp": 2, "tp": 1}, "two_d": {"dp": 2, "tp": 2}},
        "steps": args.steps,
        "loss_diffs": [round(d, 9) for d in diffs],
        "max_abs_loss_diff": max(diffs),
        "max_abs_param_diff": param_diff,
    }


def child_point(args) -> dict:
    import jax
    import numpy as np

    from elasticdl_tpu.parallel.trainer import Trainer
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.parallel.mesh import create_mesh, mesh_shape

    dp, tp = args.dp, args.tp
    seq = 64
    spec = _spec(n_heads=8, dim=64, seq=seq)
    mesh = (
        create_mesh(num_devices=dp * tp, tensor_parallelism=tp)
        if tp > 1 else create_mesh(num_devices=dp)
    )
    t = Trainer(spec, JobConfig(distribution_strategy="AllReduce"), mesh)
    state = t.init_state(jax.random.key(0))
    b = max(16 // dp * dp, dp)
    batch = t.shard_batch(_batch(np.random.default_rng(7), b, seq))
    state, m = t.train_step(state, batch)  # compile
    jax.block_until_ready(m)
    for _ in range(WARMUP):
        state, m = t.train_step(state, batch)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = t.train_step(state, batch)
    jax.block_until_ready(m)
    dt = (time.perf_counter() - t0) / args.steps
    bytes_model = t.collective_bytes_per_step(state)
    return {
        "dp": mesh_shape(mesh)[0],
        "tp": mesh_shape(mesh)[1],
        "global_batch": b,
        "step_ms": round(dt * 1e3, 3),
        "examples_per_sec": round(b / dt, 1),
        "interhost_bytes_flat": bytes_model["flat"],
        "interhost_bytes_resolved": bytes_model["resolved"],
        "loss": round(float(m["loss"]), 6),
    }


def child_chaos(args) -> dict:
    import tempfile

    import jax
    import numpy as np

    from elasticdl_tpu.parallel.trainer import Trainer  # noqa: F401
    from elasticdl_tpu.common import jitsan
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import mesh_shape
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    seq, vocab, n_tasks = 64, 128, 6
    records_per_task, mb = 8, 4
    tmp = tempfile.mkdtemp(prefix="mesh2d_chaos_")
    path = os.path.join(tmp, "lm.rio")
    generate("lm", path, records_per_task * n_tasks, seq_len=seq, vocab=vocab)
    spec = load_model_spec(
        "elasticdl_tpu.models", "transformer_lm.model_spec",
        vocab=vocab, dim=32, n_heads=4, n_layers=2, max_seq=seq,
        seq_len=seq, compute_dtype="float32", parallelism="tensor",
    )
    config = JobConfig(
        model_def="transformer_lm.model_spec",
        distribution_strategy="AllReduce",
        training_data=path,
        minibatch_size=mb,
        tensor_parallelism=4,
        optimizer_sharding="sharded",
        # Per-step dispatch (no fused scan): trainer.train_step is then
        # THE compile site, so "re-lowers exactly once per topology" is
        # one crisp counter.  lease_batch=1 keeps the GetTask counter a
        # per-task schedule for the membership injections below.
        fused_task_scan=False,
        lease_batch=1,
    )
    reader = create_data_reader(path)
    servicer = MasterServicer(
        TaskDispatcher(reader.create_shards(records_per_task))
    )
    audit = {"transitions": [], "moments_bit_exact": True, "initial": None}

    class AuditWorker(Worker):
        """Bit-exactness probe on the reform seam: host_state before the
        canonical re-placement must equal host_state after it."""

        def _replace_state(self):
            before = jax.device_get(self.trainer.host_state(self.state))
            super()._replace_state()
            after = jax.device_get(self.trainer.host_state(self.state))
            ok = all(
                np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after))
            )
            dp, tp = mesh_shape(self.trainer.mesh)
            audit["transitions"].append(
                {"dp": dp, "tp": tp, "moments_bit_exact": bool(ok)}
            )
            audit["moments_bit_exact"] &= ok

        def _apply_membership(self, membership, initial=False):
            super()._apply_membership(membership, initial=initial)
            if audit["initial"] is None and self.trainer is not None:
                dp, tp = mesh_shape(self.trainer.mesh)
                audit["initial"] = {"dp": dp, "tp": tp}

    # Phantom pre-registered: the job STARTS at world 2 (8 devices ->
    # dp2 x tp4); its mid-job leave + rejoin drives 4x2 -> 4x1 -> 4x2
    # (tp-major, tp preserved — mesh.resolve_2d_shape shrinks dp first).
    servicer.rendezvous.register("phantom")
    worker = AuditWorker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=jax.devices(),
        devices_per_worker=4,
    )
    orig_get_task = servicer.GetTask
    counter = {"n": 0}

    def get_task_with_events(req):
        counter["n"] += 1
        if counter["n"] == 3:
            servicer.rendezvous.remove("phantom")
        elif counter["n"] == 5:
            servicer.rendezvous.register("phantom")
        return orig_get_task(req)

    servicer.GetTask = get_task_with_events
    c0 = jitsan.compiles("trainer.train_step")
    result = worker.run()
    status = servicer.JobStatus({})
    shapes = [audit["initial"]] + [
        {"dp": t["dp"], "tp": t["tp"]} for t in audit["transitions"]
    ]
    path_str = " -> ".join(
        f"{s['tp']}x{s['dp']}" for s in shapes if s
    )  # tp-major, the ISSUE's notation
    train_compiles = jitsan.compiles("trainer.train_step") - c0
    out = {
        "shapes": shapes,
        "path_tp_major": path_str,
        "reforms": int(result["reforms"]),
        "steps": int(result["step"]),
        "tasks_done": int(status["done"]),
        "tasks_expected": n_tasks,
        "finished": bool(servicer.dispatcher.finished()),
        "moments_bit_exact": bool(audit["moments_bit_exact"]),
        "transitions": audit["transitions"],
        "jitsan_armed": jitsan.enabled(),
        "train_step_compiles": train_compiles,
        "jitsan_stats": jitsan.stats(),
    }
    problems = []
    if result["reforms"] != 2:
        problems.append(f"expected 2 reforms, saw {result['reforms']}")
    if not out["finished"] or status["done"] != n_tasks:
        problems.append(
            f"exactly-once violated: done={status['done']}/{n_tasks}"
        )
    if result["step"] != records_per_task * n_tasks // mb:
        problems.append(f"step count {result['step']}: work lost or repeated")
    if not audit["moments_bit_exact"]:
        problems.append("a re-partition did not carry the moments bit-exactly")
    if path_str != "4x2 -> 4x1 -> 4x2":
        problems.append(f"unexpected shape path {path_str!r}")
    if jitsan.enabled() and train_compiles != 3:
        problems.append(
            f"train_step lowered {train_compiles}x, expected 3 "
            "(once per topology)"
        )
    out["problems"] = problems
    return out


def _spawn(extra, n_devices: int, log) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__), "--child"] + extra
    log(f"run {' '.join(extra)}")
    out = subprocess.run(
        cmd,
        env=_child_env(n_devices),
        capture_output=True,
        text=True,
        timeout=600,
        cwd=_REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"child {extra} failed rc={out.returncode}: {out.stderr[-800:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_bench(args, log=None) -> dict:
    log = log or (lambda m: print(f"[mesh2d] {m}", file=sys.stderr, flush=True))
    parity = _spawn(
        ["--task", "parity", "--steps", str(args.steps)], 4, log
    )
    log(
        f"parity: max loss diff {parity['max_abs_loss_diff']:.2e} over "
        f"{parity['steps']} steps"
    )
    sweep = []
    for dp, tp in SWEEP_SHAPES:
        row = _spawn(
            [
                "--task", "point", "--dp", str(dp), "--tp", str(tp),
                "--steps", str(args.steps),
            ],
            dp * tp, log,
        )
        sweep.append(row)
        log(
            f"dp={dp} tp={tp}: {row['step_ms']} ms/step, "
            f"{row['interhost_bytes_resolved']:,} B/step resolved"
        )
    chaos = _spawn(["--task", "chaos"], 8, log)
    log(f"chaos: {chaos['path_tp_major']}, problems={chaos['problems']}")
    by_tp = {r["tp"]: r for r in sweep}
    checks = {
        "parity_ok": parity["max_abs_loss_diff"] <= 1e-6,
        # Resolved bytes fall monotonically as tp rises: 1/tp of every
        # tp-sharded leaf over (dp-1)/dp replicas.
        "bytes_monotonic_in_tp": all(
            by_tp[a]["interhost_bytes_resolved"]
            > by_tp[b]["interhost_bytes_resolved"]
            for a, b in zip((1, 2, 4), (2, 4, 8))
        ),
        "chaos_ok": not chaos["problems"],
    }
    return {
        "metric": "mesh2d_parity_step_and_bytes",
        "model": "transformer_lm tensor-parallel (wqkv/w1 column, wo/w2 row)",
        "harness": (
            f"cpu ({os.cpu_count()} core host), fake devices per point; "
            "bytes are the analytic model of docs/perf.md (no DCN on the "
            "harness), labeled as such"
        ),
        "parity": parity,
        "sweep": sweep,
        "chaos": chaos,
        "checks": checks,
    }


def run_smoke(log) -> dict:
    """Quick CI face (bench_all --mesh2d-smoke): the parity probe at 4
    steps plus the full chaos reform — the two correctness families; the
    step-time sweep stays in the artifact run."""
    parity = _spawn(["--task", "parity", "--steps", "4"], 4, log)
    chaos = _spawn(["--task", "chaos"], 8, log)
    problems = list(chaos["problems"])
    if parity["max_abs_loss_diff"] > 1e-6:
        problems.append(
            f"1D-vs-2D parity {parity['max_abs_loss_diff']:.2e} > 1e-6"
        )
    return {"parity": parity, "chaos": chaos, "problems": problems}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python tools/mesh2d_bench.py")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--task", default="parity", choices=("parity", "point", "chaos"))
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    if args.child:
        result = {
            "parity": child_parity,
            "point": child_point,
            "chaos": child_chaos,
        }[args.task](args)
        print(json.dumps(result), flush=True)
        return 0
    log = lambda m: print(f"[mesh2d] {m}", file=sys.stderr, flush=True)
    if args.smoke:
        result = run_smoke(log)
        print(json.dumps(result), flush=True)
        if result["problems"]:
            for p in result["problems"]:
                log(f"FAIL: {p}")
            return 1
        log(
            "PASS: parity "
            f"{result['parity']['max_abs_loss_diff']:.2e}, chaos "
            f"{result['chaos']['path_tp_major']} bit-exact, zero "
            "over-budget retraces"
        )
        return 0
    result = run_bench(args, log)
    from tools.artifact import code_rev, write_artifact

    result["code_rev"] = code_rev()
    write_artifact(
        result, "MESH2D_r20.json", env_var="MESH2D_OUT",
        path=args.out or None,
    )
    print(json.dumps(result["checks"]))
    return 0 if all(result["checks"].values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
