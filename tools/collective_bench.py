"""collective_bench — graftreduce's numbers of record (artifacts/COLLECT_r15.json).

Three measurement families:

- **parity** (subprocess per device count, honest XLA fake-device
  counts): flat vs hierarchical train-step param divergence after K
  identical steps (float32 reduction-order only), the subgroup
  renormalization probe — a dp-way step excluding one shard vs a
  1-device step over the surviving shards' examples — and the
  recompile-free assertion (every exclusion mask runs in ONE compiled
  program).
- **sweep** (subprocess per point): steady-state step time at 2/4/8-way
  dp, flat vs hierarchical (``--collective_local_size 2`` emulates the
  host grouping on fake CPU devices), plus the analytic per-replica
  inter-host bytes under each route
  (collectives.interhost_bytes_per_step's model — this harness has no
  real DCN to meter, and the artifact labels the bytes as modeled).
  CPU caveat, stamped into the artifact: fake-device collectives share
  one host's cores, so step-time deltas here measure the route's
  LAUNCH overhead, not the inter-host bandwidth the hierarchy exists
  to save — the bytes column is the claim, the time column is the
  non-regression guard.
- **chaos fleet** (real worker subprocess + real gRPC master +
  PodManager, the chaos_bench harness): a mid-collective stall —
  ``stall:point=collective,shard=1`` wedges one dp shard's contribution
  at the r15 in-step gate — driven through three shapes: a fault-free
  baseline, the stall with the gate OFF (``collective_deadline_ms=0``:
  the dispatch blocks for the full stall, the pre-r15 behavior), and
  the stall with the gate ON (the step completes on the subgroup at the
  deadline).  The degradation comparison is stamped against both the
  blocking path and the r13 sever-and-solo-drain number
  (CHAOS_r13.json's 25.8 s skip->trained), with the worker's
  ``edl_collective_skip_total`` observed in the MASTER's live /metrics
  scrape mid-stall (the fleet-aggregated envelope view).

Usage:
    python tools/collective_bench.py [--steps 10] [--tasks 6]
        [--stall-ms 2000] [--deadline-ms 250]
        [--families parity,sweep,chaos] [--out artifacts/COLLECT_r15.json]
    python tools/collective_bench.py --smoke   # tiny subgroup fleet
                                               # (bench_all --collective-smoke)
Env override for the artifact path: COLLECT_OUT.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

ARTIFACT_NAME = "COLLECT_r15.json"

#: The r13 number the in-collective path is measured against: the stall
#: fleet's sever-and-solo-drain skip->trained wall (CHAOS_r13.json).
R13_SKIP_TO_TRAINED_MS = 25800.0

FLEET_TIMEOUT_S = 600.0

DP_SWEEP = (2, 4, 8)
WARMUP = 3


def _child_env(dp: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={dp}"
    )
    return env


def _spawn(extra, dp: int, log) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__), "--child"] + extra
    log(f"run {' '.join(extra)}")
    out = subprocess.run(
        cmd, env=_child_env(dp), capture_output=True, text=True,
        timeout=600, cwd=_REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"child {extra} failed rc={out.returncode}: {out.stderr[-800:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


# ---------------------------------------------------------------------------
# child tasks (jax initializes inside the subprocess)
# ---------------------------------------------------------------------------


def _make_trainer(dp: int, mode: str, min_elems: int = 4096):
    import jax

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    cfg = JobConfig(
        collective=mode,
        collective_local_size=(2 if mode == "hierarchical" else 0),
        collective_min_elems=min_elems,
    )
    return spec, Trainer(
        spec, cfg, create_mesh(jax.devices(), num_devices=dp)
    )


def _batch(n: int, seed: int = 7):
    import numpy as np

    rng = np.random.default_rng(seed)
    return {
        "images": rng.uniform(size=(n, 28, 28, 1)).astype(np.float32),
        "labels": rng.integers(0, 10, (n,)).astype(np.int32),
    }


def child_measure(args) -> dict:
    import jax

    dp = args.dp
    _, t = _make_trainer(dp, args.mode)
    state = t.init_state(jax.random.key(0))
    n = max(args.batch // dp * dp, dp)
    batch = t.shard_batch(_batch(n))
    bytes_model = t.collective_bytes_per_step(state)
    state, m = t.train_step(state, batch)  # compile
    jax.block_until_ready(m)
    for _ in range(WARMUP):
        state, m = t.train_step(state, batch)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = t.train_step(state, batch)
    jax.block_until_ready(m)
    dt = (time.perf_counter() - t0) / args.steps
    return {
        "dp": dp,
        "mode": args.mode,
        "topology": t.collective.describe() if t.collective else "flat",
        "step_ms": round(dt * 1e3, 3),
        "examples_per_sec": round(n / dt, 1),
        "global_batch": n,
        "interhost_bytes_per_step_model": bytes_model,
        "loss": round(float(m["loss"]), 6),
    }


def child_parity(args) -> dict:
    import jax
    import numpy as np

    dp = args.dp

    def diff(a, b):
        return max(
            float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
            if x.size else 0.0
            for x, y in zip(
                jax.tree.leaves(jax.device_get(a.params)),
                jax.tree.leaves(jax.device_get(b.params)),
            )
        )

    n = max(args.batch // dp * dp, dp)
    host = _batch(n)
    # flat vs hierarchical, identical steps
    _, tf_ = _make_trainer(dp, "flat")
    _, th = _make_trainer(dp, "hierarchical")
    sf = tf_.init_state(jax.random.key(0))
    sh = th.init_state(jax.random.key(0))
    for _ in range(args.steps):
        sf, _ = tf_.train_step(sf, tf_.shard_batch(host))
        sh, _ = th.train_step(sh, th.shard_batch(host))
    flat_vs_hier = diff(sf, sh)
    # renormalization: exclude the last shard vs a 1-device run over the
    # surviving shards' examples
    _, tx = _make_trainer(dp, "flat")
    sx = tx.init_state(jax.random.key(0))
    mask = [1] * (dp - 1) + [0]
    tx.set_active_contributors(mask)
    sx, mx = tx.train_step(sx, tx.shard_batch(host))
    _, t1 = _make_trainer(1, "flat")
    s1 = t1.init_state(jax.random.key(0))
    keep = n // dp * (dp - 1)
    s1, m1 = t1.train_step(s1, t1.shard_batch({k: v[:keep] for k, v in host.items()}))
    renorm = diff(sx, s1)
    # recompile-free: every mask variant through ONE compiled program
    fn = tx._train_step
    compiles_ok = True
    for m in ([0] + [1] * (dp - 1), None, [1] * (dp - 1) + [0]):
        tx.set_active_contributors(m)
        sx, _ = tx.train_step(sx, tx.shard_batch(host))
        compiles_ok = compiles_ok and tx._train_step is fn
    cache = getattr(fn, "_cache_size", lambda: None)()
    if cache is not None:
        compiles_ok = compiles_ok and cache == 1
    return {
        "dp": dp,
        "steps": args.steps,
        "hier_local_size": 2,
        "max_abs_param_diff_flat_vs_hier": flat_vs_hier,
        "max_abs_param_diff_excluded_vs_smaller_world": renorm,
        "excluded_loss": round(float(mx["loss"]), 6),
        "smaller_world_loss": round(float(m1["loss"]), 6),
        "mask_flip_recompile_free": bool(compiles_ok),
        "jit_cache_size_after_mask_flips": cache,
    }


# ---------------------------------------------------------------------------
# chaos fleet (real gRPC master + worker subprocess, 2 fake devices)
# ---------------------------------------------------------------------------


def _scrape_collectives(address: str, stop, box: dict) -> None:
    """Poll the master's /metrics, tracking the MAX observed
    edl_collective_* values — the mid-stall observability claim."""
    from tools.watch_job import fetch

    while not stop.is_set():
        try:
            families = fetch(address, timeout_s=2.0)
        except Exception as e:  # noqa: BLE001 — tallied; the job goes on
            box["scrapes_failed"] = box.get("scrapes_failed", 0) + 1
            box["last_error"] = f"{type(e).__name__}: {e}"
        else:
            box["scrapes_ok"] = box.get("scrapes_ok", 0) + 1
            for name in (
                "edl_collective_skip_total",
                "edl_collective_subgroup_size",
                "edl_collective_interhost_bytes_total",
            ):
                fam = families.get(name)
                if not fam:
                    continue
                for s in fam["samples"]:
                    key = f"{name}:max_seen"
                    box[key] = max(box.get(key, 0.0), s["value"])
                    if name == "edl_collective_subgroup_size" and s["value"]:
                        key_min = f"{name}:min_seen"
                        box[key_min] = min(
                            box.get(key_min, float("inf")), s["value"]
                        )
        stop.wait(0.2)


def _ensure_fleet_env() -> None:
    """The fleet's worker subprocesses inherit this process's env: a
    2-fake-device dp mesh per worker (without it the worker boots 1
    device and the gate disables itself — the in-step deadline needs
    two contributors), CPU only (the chaos_bench stance — never aim a
    fault run at a possibly-hung tunneled chip).  Called by every fleet
    entry point: ``main`` AND ``run_smoke`` (bench_all imports the
    latter directly)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def run_fleet(
    n_tasks: int,
    tmp: str,
    log,
    label: str,
    chaos: str = "",
    deadline_ms: float = 0.0,
    stall_ms: float = 0.0,
    timeout_s: float = FLEET_TIMEOUT_S,
) -> dict:
    """One 1-worker job (the worker holds a 2-fake-device dp mesh)
    through the full master stack; returns wall, accounting, and the
    mid-run collective-gauge scrape."""
    _ensure_fleet_env()
    from elasticdl_tpu.common import trace
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.main import Master

    mb, mb_per_task = 16, 2
    # Keyed by task count: fleets of different sizes (the warmup fleet is
    # deliberately short) must never share a dataset sized for the first
    # caller — a 2-task file silently turns every 6-task fleet into a
    # 2-task one.
    path = os.path.join(tmp, f"collective_mnist_{n_tasks}.rio")
    if not os.path.exists(path):
        generate("mnist", path, mb * mb_per_task * n_tasks)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = os.path.join(tmp, "jax_cache")
    config = JobConfig(
        job_name=f"coll-{label}",
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=path,
        minibatch_size=mb,
        num_minibatches_per_task=mb_per_task,
        num_epochs=1,
        num_workers=1,
        trace=True,
        chaos=chaos,
        collective_deadline_ms=deadline_ms,
        gang_skip_budget=8,
        checkpoint_steps=0,
        pod_log_dir=os.path.join(tmp, f"pods-{label}"),
        gauge_port=0,
    )
    trace.configure(enabled=True)
    trace.default().clear()
    master = Master(config)
    result_box: dict = {}

    def _run():
        try:
            result_box["status"] = master.run()
        except Exception as e:
            result_box["error"] = e

    t0 = time.perf_counter()
    runner = threading.Thread(target=_run, name=f"coll-{label}", daemon=True)
    runner.start()
    scrape_box: dict = {}
    scrape_stop = threading.Event()
    scraper = None
    if master.metrics_server is not None:
        scraper = threading.Thread(
            target=_scrape_collectives,
            args=(master.metrics_server.address, scrape_stop, scrape_box),
            name=f"coll-scrape-{label}", daemon=True,
        )
        scraper.start()
    runner.join(timeout=timeout_s)
    scrape_stop.set()
    if scraper is not None:
        scraper.join(timeout=5.0)
    wall = time.perf_counter() - t0
    if runner.is_alive():
        master.shutdown()
        runner.join(timeout=30)
        raise RuntimeError(
            f"collective fleet {label!r} still running after {timeout_s:.0f}s"
        )
    if "error" in result_box:
        raise RuntimeError(
            f"collective fleet {label!r} failed: {result_box['error']}"
        ) from result_box["error"]
    status = result_box["status"]
    done = int(status.get("done", 0))
    # The in-step wait, on phase clocks: every gate crossing — a bounded
    # deadline wait (gate armed) or a blocking inline stall (gate off) —
    # lands in the worker's ``collective_gate`` phase, so this number is
    # immune to the ±2-3 s process-spawn/scrape noise whole-fleet walls
    # carry on this box.
    gate_s = sum(
        float(p.get("collective_gate", 0.0))
        for p in (status.get("phase_times") or {}).values()
    )
    out = {
        "label": label,
        "chaos": chaos,
        "collective_deadline_ms": deadline_ms,
        "stall_ms": stall_ms,
        "wall_s": round(wall, 2),
        "gate_phase_s": round(gate_s, 3),
        "tasks_done": done,
        "tasks_expected": n_tasks,
        "abandoned": int(status.get("abandoned", 0)),
        "duplicate_done": int(status.get("duplicate_done", 0)),
        "collective_skips": status.get("collective_skips") or {},
        "live_metrics": {
            "endpoint": (
                master.metrics_server.address
                if master.metrics_server is not None else None
            ),
            **scrape_box,
        },
        "zero_double_train": (
            done == n_tasks
            and int(status.get("duplicate_done", 0)) == 0
            and int(status.get("abandoned", 0)) == 0
        ),
    }
    log(f"fleet {label}: {json.dumps(out)}")
    return out


def run_chaos_family(args, tmp: str, log) -> dict:
    """baseline / stall-with-gate-off / stall-with-gate-on, one stamped
    comparison (see module docstring)."""
    stall = (
        f"stall:point=collective,shard=1,step=3,"
        f"ms={int(args.stall_ms)},count=1"
    )
    # All three fleets share one compile cache (same model, same shapes);
    # an UNSTAMPED warmup fleet pays the XLA compiles first, so the
    # baseline — the degradation DENOMINATOR — measures steady-state
    # wall, not compilation (the chaos_bench cache stance, one step
    # further: here even the baseline must be warm).
    run_fleet(2, tmp, log, "warmup")
    fleets = {
        "baseline": run_fleet(args.tasks, tmp, log, "baseline"),
        "stall_blocking": run_fleet(
            args.tasks, tmp, log, "stall-blocking", chaos=stall,
            deadline_ms=0.0, stall_ms=args.stall_ms,
        ),
        "stall_subgroup": run_fleet(
            args.tasks, tmp, log, "stall-subgroup", chaos=stall,
            deadline_ms=args.deadline_ms, stall_ms=args.stall_ms,
        ),
    }
    base = fleets["baseline"]["wall_s"]
    blocking_gate_ms = round(fleets["stall_blocking"]["gate_phase_s"] * 1e3, 1)
    subgroup_gate_ms = round(fleets["stall_subgroup"]["gate_phase_s"] * 1e3, 1)
    skips = sum(fleets["stall_subgroup"]["collective_skips"].values())
    live = fleets["stall_subgroup"]["live_metrics"]
    return {
        "fleets": fleets,
        "stall_ms": args.stall_ms,
        "deadline_ms": args.deadline_ms,
        # The three-way degradation story, on PHASE clocks (the
        # noise-immune number — every gate crossing, blocking or
        # deadline-bounded, is accounted under the worker's
        # ``collective_gate`` phase): the blocking path pays ~the stall
        # inside the step, the subgroup path pays ~the deadline, and the
        # r13 evict-and-reform path paid 25.8 s.
        "in_step_wait_ms": {
            "blocking": blocking_gate_ms,
            "subgroup": subgroup_gate_ms,
            "r13_sever_and_solo_drain": R13_SKIP_TO_TRAINED_MS,
        },
        # Whole-fleet wall excess over the fault-free baseline — stamped
        # for context, NOT gated: a difference of ~15-20 s fleet walls
        # on a 2-core box carries ±2-3 s process-spawn/scrape noise
        # (the r12 wall-A/B stance; the phase numbers above are the
        # comparison of record).
        "wall_excess_ms_noisy": {
            "blocking": round(
                (fleets["stall_blocking"]["wall_s"] - base) * 1e3, 1
            ),
            "subgroup": round(
                (fleets["stall_subgroup"]["wall_s"] - base) * 1e3, 1
            ),
        },
        "subgroup_completed_with_skips": skips,
        "skip_observed_in_live_scrape": (
            live.get("edl_collective_skip_total:max_seen", 0) >= 1
        ),
        "checks": {
            "all_fleets_exactly_once": all(
                f["zero_double_train"] for f in fleets.values()
            ),
            "subgroup_skipped": skips >= 1,
            # The blocking fleet's in-step wait must show (most of) the
            # stall — proof the fault actually wedged a dispatch.
            "blocking_paid_the_stall": blocking_gate_ms >= args.stall_ms * 0.9,
            "subgroup_beats_blocking": subgroup_gate_ms < blocking_gate_ms,
            # Bounded by the deadline per gate pass (one pass per task,
            # +1 for the warm-in crossing), not by the stall.
            "subgroup_bounded_by_deadline": (
                subgroup_gate_ms
                <= args.deadline_ms * (args.tasks + 1)
            ),
            "subgroup_well_under_r13": (
                subgroup_gate_ms < R13_SKIP_TO_TRAINED_MS / 10
            ),
        },
    }


def run_smoke(log, tmp: Optional[str] = None) -> dict:
    """Tiny subgroup-completion check (bench_all --collective-smoke):
    one worker, one mid-collective stall, gate on — asserts the job
    completed on the subgroup (skips > 0), nothing trained twice, and
    the skip was visible in the live master scrape."""
    import tempfile

    tmp = tmp or tempfile.mkdtemp(prefix="collective_smoke_")
    result = run_fleet(
        4, tmp, log, "smoke",
        chaos="stall:point=collective,shard=1,step=2,ms=1500,count=1",
        deadline_ms=150.0, stall_ms=1500.0,
    )
    problems = []
    if not result["zero_double_train"]:
        problems.append(
            f"exactly-once violated: done={result['tasks_done']}/"
            f"{result['tasks_expected']}, duplicate_done="
            f"{result['duplicate_done']}, abandoned={result['abandoned']}"
        )
    if not sum(result["collective_skips"].values()):
        problems.append(
            "no collective_skips in JobStatus — the gate never excluded?"
        )
    if not result["live_metrics"].get("edl_collective_skip_total:max_seen"):
        problems.append(
            "edl_collective_skip_total never observed in the live scrape"
        )
    result["problems"] = problems
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="collective_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--task", default="measure", choices=("measure", "parity"))
    ap.add_argument("--mode", default="flat", choices=("flat", "hierarchical"))
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--tasks", type=int, default=6)
    ap.add_argument("--stall-ms", type=float, default=2000.0)
    ap.add_argument("--deadline-ms", type=float, default=250.0)
    ap.add_argument(
        "--families", default="parity,sweep,chaos",
        help="comma-separated subset of parity,sweep,chaos",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="tiny subgroup fleet; exit 1 on any failed check")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    if args.child:
        result = (
            child_parity(args) if args.task == "parity" else child_measure(args)
        )
        print(json.dumps(result), flush=True)
        return 0

    _ensure_fleet_env()
    log = lambda m: print(f"[collective] {m}", file=sys.stderr, flush=True)
    from tools.artifact import ArtifactRun

    run = ArtifactRun()

    if args.smoke:
        result = run_smoke(log)
        print(json.dumps(result), flush=True)
        if result["problems"]:
            for p in result["problems"]:
                log(f"FAIL: {p}")
            return 1
        log(
            "PASS: subgroup completion with "
            f"{sum(result['collective_skips'].values())} skip(s), "
            "zero double-train"
        )
        return 0

    import tempfile

    wanted = {f.strip() for f in args.families.split(",") if f.strip()}
    artifact: Dict = {
        "metric": "collective_step_time_and_straggler_degradation",
        "harness": (
            f"cpu ({os.cpu_count()} core host), XLA fake devices; "
            "hierarchical grouping emulated via --collective_local_size 2 "
            "(fake-device collectives share one host's cores, so step-time "
            "deltas measure launch overhead, not DCN bandwidth — the "
            "inter-host bytes column is the analytic model)"
        ),
        "model": "mnist dense f32",
    }
    if "parity" in wanted:
        parity = _spawn(
            ["--task", "parity", "--dp", "4",
             "--batch", str(args.batch), "--steps", str(args.steps)],
            4, log,
        )
        log(f"parity: {parity}")
        artifact["parity"] = parity
    if "sweep" in wanted:
        sweep = []
        for dp in DP_SWEEP:
            # local_size=2 cannot factor a 2-wide axis into multiple
            # hosts (n_host would be 1 → resolve_topology demotes to
            # flat); stamping that point as "hierarchical" would gate a
            # mislabeled flat-vs-flat series in bench_regress.
            modes = ("flat",) if dp <= 2 else ("flat", "hierarchical")
            for mode in modes:
                row = _spawn(
                    ["--task", "measure", "--mode", mode, "--dp", str(dp),
                     "--batch", str(args.batch), "--steps", str(args.steps)],
                    dp, log,
                )
                sweep.append(row)
                log(
                    f"dp={dp} {mode}: {row['step_ms']} ms/step, "
                    f"interhost(model) {row['interhost_bytes_per_step_model']}"
                )
        artifact["sweep"] = sweep
        by = {(r["dp"], r["mode"]): r for r in sweep}
        artifact["sweep_checks"] = {
            f"interhost_cut_dp{dp}": round(
                by[(dp, "flat")]["interhost_bytes_per_step_model"]["flat"]
                / max(
                    by[(dp, "hierarchical")]["interhost_bytes_per_step_model"][
                        "resolved"
                    ],
                    1,
                ),
                2,
            )
            for dp in DP_SWEEP
            if (dp, "hierarchical") in by
        }
    if "chaos" in wanted:
        tmp = tempfile.mkdtemp(prefix="collective_bench_")
        artifact["chaos"] = run_chaos_family(args, tmp, log)

    run.write(
        artifact, ARTIFACT_NAME, env_var="COLLECT_OUT",
        path=args.out or None, log=log,
    )
    print(json.dumps({
        k: v for k, v in artifact.items() if k in ("sweep_checks",)
    } | ({"chaos_checks": artifact["chaos"]["checks"]}
         if "chaos" in artifact else {})))
    ok = all(artifact.get("chaos", {}).get("checks", {"ok": True}).values())
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
