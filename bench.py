"""Headline benchmark: DeepFM/Criteo training throughput, examples/sec/chip
(BASELINE.json metric).

Two phases, ONE JSON line:
1. device-step: the full hybrid train step (mesh-sharded embedding tables +
   psum'd dense grads) on all available devices, synthetic pre-sharded batch,
   steady-state steps/sec — the device ceiling.
2. end-to-end (tools/bench_e2e.py): the WHOLE worker path on a real recordio
   file — master task dispatch, bulk C++ reads, C++ criteo decode, prefetch,
   pipelined device steps.  This is the headline ``value``: it is what a
   user's job sustains (VERDICT r3 Missing #1 demanded the end-to-end number
   be the one of record); the device-step figure rides along as
   ``device_step_examples_per_sec_per_chip``.

Robustness (the round-1 bench produced *nothing* when the chip was flaky):
- every phase (init / build / compile / warmup / measure) logs a timestamped
  line to stderr, so a hang is forensically attributable;
- device init and the first compile retry with backoff on transient
  ``UNAVAILABLE`` TPU errors;
- the JSON line is emitted even on partial measurement (``"partial": true``
  with whatever phase was reached), so the driver always gets a parseable
  artifact;
- the persistent compilation cache is enabled so repeat benches skip the
  ~20-40 s XLA compile.

``vs_baseline``: no published reference number exists (BASELINE.json
``"published": {}``; see BASELINE.md).  The denominator below is a documented
ESTIMATE of per-V100 ElasticDL DeepFM throughput implied by the north-star
target ("match 8xV100 Horovod throughput"): ~120k examples/sec/GPU for a
small DeepFM with PS-hosted embeddings.  Treat vs_baseline as relative to
that stand-in until a real number is obtainable.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from elasticdl_tpu.common.platform import (
    apply_platform_env,
    enable_compile_cache,
    probe_devices,
)

apply_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

# Hard per-run watchdog: a hang inside the TPU runtime (observed: a bare
# jax.devices() blocking >9 min when the tunneled chip is unhealthy) is not
# catchable as an exception, so a daemon thread force-exits with a partial
# JSON artifact once the deadline passes.  The driver then still gets a
# parseable line naming the phase that hung.
WATCHDOG_DEADLINE_S = float(os.environ.get("BENCH_WATCHDOG_S", "480"))

# Stand-ins for the unpublished reference number (see module docstring).
# Kept SEPARATE per metric: r1-r3 compared the *device-step* figure against
# the ~120k/GPU estimate; r4 switched the headline to *end-to-end*, which in
# the reference's own story is also what a V100 job sustains (the estimate
# already includes its input pipeline), so the same stand-in applies — but a
# consumer of the old metric name must not silently read the new one
# (ADVICE r4 #4), hence the explicit ``renamed_from`` field in the output.
REFERENCE_E2E_EXAMPLES_PER_SEC_PER_CHIP = 120_000.0
REFERENCE_DEVICE_STEP_EXAMPLES_PER_SEC_PER_CHIP = 120_000.0

GLOBAL_BATCH = 8192
WARMUP_STEPS = 5
MEASURE_STEPS = 30
RETRIES = 4
BACKOFF_S = 15.0
# Killable-subprocess device probes before the first in-process backend
# touch (worst case 4x90s + backoffs = ~390s, safely inside the watchdog).
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "4"))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "90"))

_state = {
    "phase": "start",
    "t0": time.time(),
    "emitted": False,
    "deadline": time.time() + WATCHDOG_DEADLINE_S,
}


def _log(phase: str, msg: str = "") -> None:
    _state["phase"] = phase
    dt = time.time() - _state["t0"]
    print(f"[bench +{dt:7.1f}s] {phase}: {msg}", file=sys.stderr, flush=True)


def _watchdog() -> None:
    # The deadline is re-armed once the device probe succeeds (a probe can
    # legitimately consume most of the first window when the chip is flaky
    # at minute 0 and fine at minute 4 — the budget must then still cover
    # init + compile + measure, or the probe's rescue was pointless).
    while True:
        remaining = _state["deadline"] - time.time()
        if remaining <= 0:
            break
        time.sleep(min(remaining, 5.0))
    hung_phase = _state["phase"]  # capture BEFORE logging mutates it
    _log("watchdog", f"phase {hung_phase!r} still running after "
                     f"{WATCHDOG_DEADLINE_S:.0f}s; force-exiting")
    _state["phase"] = hung_phase
    if not _state["emitted"]:
        _emit(None, partial=True, error=f"watchdog: hung in phase {hung_phase!r}")
    os._exit(2)


def _code_rev() -> str:
    """Commit hash stamped into every bench artifact (tools/artifact.py
    ``code_rev``: shared with graftlint's LINT artifact so bench and lint
    trajectories key to the same revision ids).  The best-run-wins record
    guard needs it to tell "a worse run of the same code" (keep the
    record) from "the first run of NEW code" (the record must follow the
    code) — see the guard in ``_emit`` for the dirty-rev rules.
    """
    try:
        from tools.artifact import code_rev

        return code_rev(os.path.dirname(os.path.abspath(__file__)))
    except Exception:
        return ""


def _emit(
    value: float | None,
    *,
    partial: bool = False,
    error: str = "",
    extras: dict | None = None,
) -> None:
    _state["emitted"] = True
    line = {
        "metric": "deepfm_criteo_e2e_examples_per_sec_per_chip",
        # r4 renamed the headline from the device-step metric; trend lines
        # across rounds 1-3 compare against device_step_* in extras instead.
        "renamed_from": "deepfm_criteo_examples_per_sec_per_chip",
        "value": round(value, 1) if value is not None else None,
        "unit": "examples/sec/chip",
        "vs_baseline": (
            round(value / REFERENCE_E2E_EXAMPLES_PER_SEC_PER_CHIP, 3)
            if value is not None
            else None
        ),
    }
    line["code_rev"] = _code_rev()
    if extras:
        line.update(extras)
    if partial:
        line["partial"] = True
        line["phase_reached"] = _state["phase"]
    if error:
        line["error"] = error[:400]
    print(json.dumps(line), flush=True)
    # Belt: deposit the same line under artifacts/ so a battery or driver
    # run leaves a committed number-of-record file even if stdout capture
    # is lost (best-effort: the printed line is the primary channel).
    # Partials go to their OWN file — a later outage rerun must never
    # clobber a committed real number with value:null.
    try:
        from tools.artifact import write_artifact

        if partial:
            # Partials go to their OWN file and NEVER honor the env
            # override: with BENCH_OUT pointed at the committed headline,
            # an outage rerun would clobber the real number with
            # value:null — the exact hazard the name split prevents.
            write_artifact(
                line, "bench_r05_partial.json", env_var="",
                log=lambda m: None,
            )
        else:
            # Every full run is recorded (bench_r05_latest.json), but the
            # number-of-record file keeps the BEST run: the tunnel's wire
            # is bimodal across runs (docs/perf.md run table), and a
            # stall-window rerun must not replace a healthy-link number —
            # the record file's link fields say what its wire was doing.
            write_artifact(
                line, "bench_r05_latest.json", env_var="",
                log=lambda m: None,
            )
            # Compare against the SAME file the guarded write resolves to
            # (BENCH_OUT-aware) — reading the default while writing the
            # override would skip explicit-override writes entirely.
            best = os.environ.get("BENCH_OUT") or os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "artifacts", "bench_r05.json",
            )
            prev = prev_rev = None
            try:
                with open(best) as f:
                    rec = json.load(f)
                prev = rec.get("value")
                prev_rev = rec.get("code_rev")
            except Exception:
                pass
            # Best-run-wins is a SAME-REVISION, SAME-PIPELINE-CONFIG guard:
            # across runs of the same code AND the same ingest/prep/lease
            # shape it keeps the healthy-link number (the tunnel's wire is
            # bimodal), but once either changes the record must follow the
            # fresh run — throughput at ingest_threads=4 and at 1 are
            # different experiments, and a genuine regression must be able
            # to lower the number of record.  Unknown/missing revs or
            # pipeline stamps (old artifacts, no git) count as "different":
            # the fresh run wins.
            same_rev = (
                prev_rev is not None
                and prev_rev != ""
                # Dirty revs never match — even each other: two runs of
                # the same dirty HEAD can be running different code.
                and not prev_rev.endswith("-dirty")
                and prev_rev == line["code_rev"]
                and rec.get("pipeline") is not None
                and rec.get("pipeline") == line.get("pipeline")
            )
            if prev is None or (
                value is not None and (not same_rev or value >= prev)
            ):
                write_artifact(
                    line, "bench_r05.json", env_var="BENCH_OUT",
                    log=lambda m: None,
                )
    except Exception:
        pass


def _retry(phase: str, fn):
    """Run fn(), retrying with backoff on transient TPU UNAVAILABLE errors."""
    for attempt in range(RETRIES):
        try:
            return fn()
        except Exception as e:  # jaxlib surfaces these as generic RuntimeError
            msg = str(e)
            transient = "UNAVAILABLE" in msg or "ABORTED" in msg
            if not transient or attempt == RETRIES - 1:
                raise
            _log(phase, f"transient error (attempt {attempt + 1}/{RETRIES}), "
                        f"retrying in {BACKOFF_S:.0f}s: {msg[:200]}")
            time.sleep(BACKOFF_S)


def _batch(n: int):
    # Synthetic Criteo-shaped batch; ids spread across the full hashed space.
    k = jax.random.key(7)
    k1, k2, k3 = jax.random.split(k, 3)
    return {
        "dense": jax.random.uniform(k1, (n, 13), jnp.float32, 0.0, 1000.0),
        "cat": jax.random.randint(k2, (n, 26), 0, 1 << 30),
        "labels": jax.random.bernoulli(k3, 0.25, (n,)).astype(jnp.int32),
    }


def main() -> None:
    profile_dir = os.environ.get("BENCH_PROFILE_DIR", "")
    threading.Thread(target=_watchdog, name="bench-watchdog", daemon=True).start()
    enable_compile_cache()

    # A hang in jax.devices() (the twice-recorded chip failure, BENCH_r02/
    # r04) is not an exception, so _retry can't save it and the watchdog
    # only records the corpse.  Probe the backend in killable subprocesses
    # first; enter the un-killable in-process init only once a probe has
    # answered, and fail fast (partial artifact) when none does.
    _log("init", "probing device backend in subprocess")
    probe_devices(
        attempts=PROBE_ATTEMPTS,
        timeout_s=PROBE_TIMEOUT_S,
        log=lambda m: _log("init", m),
    )
    # Re-arm: a late-succeeding probe must not have eaten the budget the
    # remaining phases (init/compile/measure/e2e) still need.
    _state["deadline"] = time.time() + WATCHDOG_DEADLINE_S
    _log("init", "querying devices")
    devices = _retry("init", jax.devices)
    n = len(devices)
    _log("init", f"{n} device(s): {devices[0].platform}")
    batch_size = max(GLOBAL_BATCH // n * n, n)

    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    _log("build", "constructing DeepFM trainer")
    spec = load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        buckets_per_feature=65536,
        embedding_dim=8,
        hidden=(400, 400),
    )
    mesh = create_mesh(devices)
    trainer = Trainer(
        spec,
        JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER),
        mesh,
    )
    # "auto" is mesh-size-aware: 1-device meshes resolve to dense (local
    # gather), n>1 TPU meshes to the ragged all-to-all route.  Logged so the
    # recorded artifact names the code path it measured (VERDICT r2 Weak #1).
    _log("build", f"embedding_lookup_impl resolved to "
                  f"{trainer.ctx.embedding_impl!r} on {n} device(s)")

    _log("compile", "init_state + first train_step (XLA compile)")
    state = _retry("compile", lambda: trainer.init_state(jax.random.key(0)))
    batch = trainer.shard_batch(_batch(batch_size))

    def _first_step():
        s, m = trainer.train_step(state, batch)
        jax.block_until_ready(m)
        return s, m

    state, metrics = _retry("compile", _first_step)
    _log("compile", "done")

    try:
        _log("warmup", f"{WARMUP_STEPS} steps")
        for _ in range(WARMUP_STEPS):
            state, metrics = trainer.train_step(state, batch)
        jax.block_until_ready(metrics)

        _log("measure", f"{MEASURE_STEPS} steps @ global batch {batch_size}")
        if profile_dir:
            jax.profiler.start_trace(profile_dir)
        t0 = time.perf_counter()
        for _ in range(MEASURE_STEPS):
            state, metrics = trainer.train_step(state, batch)
        jax.block_until_ready(metrics)
        elapsed = time.perf_counter() - t0
        if profile_dir:
            jax.profiler.stop_trace()
            _log("measure", f"profile trace written to {profile_dir}")
    except Exception as e:
        # Partial result: we compiled and ran at least one step; report that.
        failed_phase = _state["phase"]
        _log("error", str(e)[:300])
        _state["phase"] = failed_phase  # keep phase_reached forensic
        _emit(None, partial=True, error=str(e))
        raise

    eps_per_chip = batch_size * MEASURE_STEPS / elapsed / n
    # MFU context: DeepFM's dense FLOPs are ~20 GFLOP/step at this batch
    # (MLP 608->400->400->1 fwd+bwd), so even a perfect step is ~1% MFU on a
    # v5e — the model is embedding-bound BY DESIGN.  The honest utilization
    # lens is the embedding traffic: per step the fused table moves ~109 MB
    # of random 128-lane rows each way (gather + scatter-add); per-op trace
    # times (tools/profile_step.py) put those at ~1.9/2.9 ms = ~50 GB/s
    # effective random-row bandwidth, i.e. the step sits at the HBM
    # random-access floor, not a compute ceiling.
    step_ms = elapsed / MEASURE_STEPS * 1e3
    # 20 GFLOP is the GLOBAL batch's dense work; per-chip MFU divides by n.
    mfu = 20e9 / n / (elapsed / MEASURE_STEPS) / 197e12
    _log("device-step", f"{eps_per_chip:,.0f} examples/sec/chip "
                        f"({step_ms:.2f} ms/step, ~{mfu * 100:.1f}% MFU of "
                        f"v5e bf16 peak — embedding-bound, see comment)")
    extras = {
        "device_step_examples_per_sec_per_chip": round(eps_per_chip, 1),
        "device_step_ms": round(step_ms, 3),
        # Cross-round trend line vs r1-r3, which benched this metric.
        "device_step_vs_baseline": round(
            eps_per_chip / REFERENCE_DEVICE_STEP_EXAMPLES_PER_SEC_PER_CHIP, 3
        ),
    }

    # Phase 2: end-to-end through the real worker loop (the headline).
    _log("e2e", "running the full job stack on a recordio file")
    try:
        from tools.bench_e2e import run_e2e

        e2e = run_e2e(log=lambda m: _log("e2e", m))
    except Exception as e:
        # The device-step figure is still a valid partial artifact.
        _log("e2e-error", str(e)[:300])
        _emit(None, partial=True, error=f"e2e failed: {e}", extras=extras)
        raise
    e2e_eps = e2e["e2e_examples_per_sec_per_chip"]
    extras["e2e_detail"] = {
        k: (round(v, 3) if isinstance(v, float) else v)
        for k, v in e2e.items()
        if k != "e2e_examples_per_sec_per_chip"
    }
    # Pipeline shape of record (r9, extended r11): like the link fields,
    # throughput is only comparable at equal ingest/prep/lease config AND
    # equal step shape (optimizer sharding / donation) — the record guard
    # in _emit treats a different shape as a different experiment, so a
    # sharded-optimizer run and a replicated run never compete for the one
    # record slot.
    extras["pipeline"] = {
        k: e2e[k]
        for k in (
            "ingest_threads", "prep_depth", "lease_batch",
            "optimizer_sharding", "donate_train_state",
        )
        if k in e2e
    }
    _log("done", f"end-to-end {e2e_eps:,.0f} examples/sec/chip "
                 f"(device-step ceiling {eps_per_chip:,.0f})")
    _emit(e2e_eps, extras=extras)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # always leave a parseable artifact — exactly one
        if not _state["emitted"]:
            _emit(None, partial=True, error=f"{type(e).__name__}: {e}")
        raise
