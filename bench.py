"""Headline benchmark: DeepFM/Criteo training throughput, examples/sec/chip
(BASELINE.json metric).

Runs the full hybrid train step (mesh-sharded embedding tables + psum'd dense
grads) on all available devices with synthetic Criteo-shaped data, measures
steady-state steps/sec, prints ONE JSON line.

``vs_baseline``: no published reference number exists (BASELINE.json
``"published": {}``; see BASELINE.md).  The denominator below is a documented
ESTIMATE of per-V100 ElasticDL DeepFM throughput implied by the north-star
target ("match 8xV100 Horovod throughput"): ~120k examples/sec/GPU for a
small DeepFM with PS-hosted embeddings.  Treat vs_baseline as relative to
that stand-in until a real number is obtainable.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.trainer import Trainer

# Stand-in for the unpublished reference number (see module docstring).
REFERENCE_EXAMPLES_PER_SEC_PER_CHIP = 120_000.0

GLOBAL_BATCH = 8192
WARMUP_STEPS = 5
MEASURE_STEPS = 30


def _batch(n: int):
    # Synthetic Criteo-shaped batch; ids spread across the full hashed space.
    k = jax.random.key(7)
    k1, k2, k3 = jax.random.split(k, 3)
    return {
        "dense": jax.random.uniform(k1, (n, 13), jnp.float32, 0.0, 1000.0),
        "cat": jax.random.randint(k2, (n, 26), 0, 1 << 30),
        "labels": jax.random.bernoulli(k3, 0.25, (n,)).astype(jnp.int32),
    }


def main() -> None:
    devices = jax.devices()
    n = len(devices)
    batch_size = max(GLOBAL_BATCH // n * n, n)

    spec = load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        buckets_per_feature=65536,
        embedding_dim=8,
        hidden=(400, 400),
    )
    mesh = create_mesh(devices)
    trainer = Trainer(
        spec,
        JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER),
        mesh,
    )
    state = trainer.init_state(jax.random.key(0))
    batch = trainer.shard_batch(_batch(batch_size))

    for _ in range(WARMUP_STEPS):
        state, metrics = trainer.train_step(state, batch)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = trainer.train_step(state, batch)
    jax.block_until_ready(metrics)
    elapsed = time.perf_counter() - t0

    eps_per_chip = batch_size * MEASURE_STEPS / elapsed / n
    print(
        json.dumps(
            {
                "metric": "deepfm_criteo_examples_per_sec_per_chip",
                "value": round(eps_per_chip, 1),
                "unit": "examples/sec/chip",
                "vs_baseline": round(
                    eps_per_chip / REFERENCE_EXAMPLES_PER_SEC_PER_CHIP, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
