"""Ring attention — sequence/context parallelism over the device mesh.

The reference framework predates long-context models (its models are
tabular/CNN — SURVEY.md §2 parallelism table: SP/CP absent), but this
rebuild treats long-context as first-class: sequences too long for one
device's HBM shard along a ``sp`` mesh axis, and attention runs blockwise
while key/value blocks rotate around the ring via ``lax.ppermute`` —
compute on the current block overlaps the ICI transfer of the next, so the
ring costs ~one extra block of latency, not a full all-gather of K/V.

Math: classic streaming-softmax (flash-style) accumulation.  Each step
processes one K/V block against the local Q block, carrying a running
row-max ``m``, normalizer ``l``, and unnormalized output ``o``; exact to
fp error regardless of block order.  Causal masking uses global positions
(device rank × block length + offset), so the sharded result equals the
unsharded lower-triangular mask.

All collectives are XLA ``ppermute`` on the mesh axis (ICI), differentiable
(transpose is the reverse rotation), so the same code path trains.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from elasticdl_tpu.common.jax_compat import axis_size


def _rotate(x: jax.Array, axis_name: str) -> jax.Array:
    n = axis_size(axis_name)
    return lax.ppermute(x, axis_name, [(j, (j + 1) % n) for j in range(n)])


def attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Plain full attention ([B, L, H, D] layout) — the numerics oracle."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool), lk - lq)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _local_attention(q, k, v, causal: bool) -> jax.Array:
    """Exact single-shard attention: the Pallas flash kernel on TPU (no
    O(L^2) HBM tensors — at the MFU-bench shape the XLA path's saved
    probability tensors alone are ~19 GB at b=32, the difference between
    OOM and 2x the batch), the XLA oracle elsewhere (CPU tests/dryruns;
    the kernel itself is oracle-tested in interpret mode in
    tests/test_flash_attention.py)."""
    from elasticdl_tpu.ops.flash_attention import flash_attention, supports

    if jax.default_backend() == "tpu" and supports(q, k, v):
        return flash_attention(q, k, v, causal)
    return attention_reference(q, k, v, causal=causal)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: Optional[str] = None,
    causal: bool = False,
) -> jax.Array:
    """Blockwise attention with K/V ring rotation over ``axis_name``.

    Inputs are the LOCAL sequence shards ``[B, L_local, H, D]`` (inside
    shard_map over the ``sp`` axis); the output is the local shard of the
    full-attention result.  With ``axis_name=None`` (or outside shard_map)
    it degrades to exact single-device attention.
    """
    if axis_name is None:
        return _local_attention(q, k, v, causal)

    n = axis_size(axis_name)
    if n == 1:
        # Degenerate ring (1-device mesh under shard_map): exact local
        # attention, flash-kernelled on TPU.
        return _local_attention(q, k, v, causal)
    my = lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = d**-0.5
    q_pos = my * lq + jnp.arange(lq)  # global positions of local queries

    def accumulate(acc, src, k_blk, v_blk):
        o, m, l = acc
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
        if causal:
            kv_pos = src * lk + jnp.arange(lk)
            mask = q_pos[:, None] >= kv_pos[None, :]  # [lq, lk]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # Fully-masked rows keep m=-inf; guard the exp against inf-inf.
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(scores - safe_m[..., None])
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
        return o_new, m_new, l_new

    # Block 0 is the locally-held K/V; the scan then performs exactly n-1
    # rotations (rotate-then-accumulate), so no transferred block is wasted.
    o0 = jnp.zeros((b, h, lq, d), jnp.float32)
    m0 = jnp.full((b, h, lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    acc = accumulate(
        (o0, m0, l0), my, k.astype(jnp.float32), v.astype(jnp.float32)
    )

    def step(carry, i):
        acc, k_blk, v_blk = carry
        k_blk = _rotate(k_blk, axis_name)
        v_blk = _rotate(v_blk, axis_name)
        acc = accumulate(acc, (my - i) % n, k_blk, v_blk)
        return (acc, k_blk, v_blk), None

    if n > 1:
        (acc, _, _), _ = lax.scan(
            step,
            (acc, k.astype(jnp.float32), v.astype(jnp.float32)),
            jnp.arange(1, n),
        )
    o, m, l = acc
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Lq, H, D]
