"""Mesh-sharded embedding lookup — the TPU-native replacement for the
reference's gRPC parameter-server embedding path (``elasticdl.layers.
Embedding`` pulling vectors / pushing IndexedSlices grads over gRPC
[D: BASELINE.json north_star]; reference sources unverifiable, mount empty at
survey time).

Design (static shapes, XLA/ICI-friendly — see SURVEY.md §7 item 5):

- **Lane-packed storage.**  A table of ``V'`` logical rows × ``dim`` is
  stored as a 2-D array ``[V'/pack, pack*stride]`` where ``stride`` is the
  next power of two ≥ ``dim`` (dead lanes zero-filled) and ``pack =
  128 // stride``: ``pack`` logical rows share one exactly-128-lane physical
  row, so every gather/scatter touches whole lane-aligned vregs.  The
  power-of-two stride matters: a dim-9 table packed at its natural width 126
  measured a 3x slower gather than the same data at width 128 on v5e.  This
  formulation is
  what XLA:TPU vectorizes: per-op device times from a ``jax.profiler`` trace
  of the real DeepFM step (8192×26 ids into a 1.7M-row dim-8 table, v5e)
  measure the packed row gather at 0.53 ms and its transpose scatter-add at
  2.75 ms — versus **370 ms / 728 ms** for the same shapes stored flat 1-D
  and gathered as ``dim``-element slices, which XLA lowers to a *serial
  per-row while loop* (212,992 iterations/step at ~2-3 µs each; this was
  round 2's entire ~200x throughput gap).  An unpacked 2-D ``[V, 8]`` table
  vectorizes too but wastes 15/16 of each vreg on the scatter (18.2 ms); a
  one-hot-matmul lookup costs 20 ms of MXU time.  bf16 rows do NOT help:
  the scatter-add is op-rate-bound (~13 ns/row whether the physical row is
  256 B or 512 B — measured 2.97 ms bf16 vs 2.75 ms f32), so tables stay
  f32 (see docs/perf.md).  Trace-derived numbers, not
  wall-clock micros (the tunneled chip's dispatch wall-clock is bimodal and
  untrustworthy — VERDICT r2 Weak #2); reproduce with
  ``tools/gather_experiments.py``.
- Lookup of logical row ``i`` reads physical row ``i // pack`` (one 128-lane
  gather) and selects lane group ``i % pack`` with a tiny one-hot einsum;
  the AD transpose expands cotangents back to 128-lane rows (einsum
  transpose) and scatter-adds whole physical rows.
- The table is **physical-row-sharded** over the mesh axis: ``V'`` is padded
  so the physical row count divides every power-of-two mesh size up to 256,
  and shard ``i`` owns logical rows ``[i*V'/n, (i+1)*V'/n)`` — GSPMD's
  natural div-sharding of dim 0, so the same array is addressable both
  outside shard_map (one logical array, e.g. for Orbax) and inside (the
  local row range).

Two collective lookup implementations, selected at trace time:

- ``ragged`` (default on multi-chip TPU) — the north-star **ragged
  all-to-all** route: sort local ids by owner shard, exchange
  per-destination counts (n² int32), ``lax.ragged_all_to_all`` the ids to
  their owners, lane-packed gather locally, ``lax.ragged_all_to_all`` the
  vectors straight back, unsort.  Each vector crosses ICI exactly once, so
  per-device vector traffic is ~``B_local·dim`` (id-distribution dependent),
  independent of mesh size.  XLA:CPU does not implement the
  ``ragged-all-to-all`` HLO, so tests exercise the identical
  routing/offset/unsort code through a dense all_gather emulation of the
  collective (``ragged_emulated``) that is semantically equivalent by
  construction.
- ``dense`` (CPU fallback; also the n=1 degenerate) — ``all_gather`` every
  device's ids, masked lane-packed gather over the full global id list, then
  ``psum_scatter`` a ``[n·B_local, dim]`` array so each device receives its
  own rows.  Simple and always available, but the psum_scatter moves
  ~``(n-1)·B_local·dim`` per device — ~(n−1)× the ragged route's vector
  volume — so it loses badly at pod scale.

``auto`` resolves per (platform, mesh size): a 1-device axis always takes
the local-gather short-circuit (paying ragged's sort/bincount machinery with
zero peers was a measured 28% tax in round 2 — VERDICT r2 Weak #1); n>1 on
TPU takes ``ragged``; CPU takes ``dense``.

Backward (both impls): the cotangents retrace the forward route back to the
owner shard and scatter-add into its local rows (whole-physical-row
scatter-add — the transpose of the packed gather), with duplicate ids
correctly accumulated — the moral equivalent of the reference's server-side
IndexedSlices apply.  The ragged impl does this through a ``custom_vjp`` (the
ragged collective has no AD rule): the saved routing metadata is replayed,
vectors flow requester→owner, and the owner applies the same masked
scatter-add.

Fail-loud OOV contract (both impls): an id outside the padded global vocab
comes back as a NaN row — never a silently wrong or zero row.  In the ragged
impl this is structural: the junk id routes to a clamped owner whose local
row range it misses, the fill-mode gather fills NaN, and the NaN rides back
to the requester; its cotangent is dropped on the same grounds.

Optimizer state for the table is co-sharded automatically because optax maps
leaf-wise (each shard's Adam moments live next to its rows — like the
reference's per-PS-pod Go optimizer state).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from elasticdl_tpu.common.jax_compat import axis_size
from elasticdl_tpu.parallel import collectives

# TPU vreg lane count: physical rows are packed to (at most) this many lanes.
LANES = 128

# Pad physical row counts to a multiple of this so the padded table
# div-shards over every power-of-two mesh size up to a v5e-256 pod; table
# shapes then stay identical across elastic resizes (4->8->4 never reshapes
# params or optimizer state).
PHYSICAL_ROW_MULTIPLE = 256

# HBM guard for auto host-tier promotion: a table whose padded storage plus
# Adam moments (3x) would crowd a v5e's 16 GiB HBM (shared with activations
# and the dense model) belongs on the host tier (ps/host_store) instead of
# the mesh.  Per-DEVICE cost is bytes/n at mesh size n; the guard is
# conservative for n=1 (the single-chip bench/dev case).
HOST_TIER_GUARD_BYTES = 4 << 30


def table_bytes(vocab_size: int, dim: int, itemsize: int = 4) -> int:
    """Padded lane-packed storage bytes for one table (excl. optimizer)."""
    rows, width = table_shape(vocab_size, dim)
    return rows * width * itemsize


def exceeds_hbm_guard(vocab_size: int, dim: int, num_devices: int = 0) -> bool:
    """True when the PER-DEVICE share of table + 2 Adam moments exceeds
    HOST_TIER_GUARD_BYTES.  The table row-shards over the whole mesh, so a
    table that crowds one chip can be fine on a pod — ``num_devices``
    defaults to the current backend's device count (1 on a lone chip)."""
    if num_devices <= 0:
        num_devices = jax.device_count()
    return 3 * table_bytes(vocab_size, dim) > HOST_TIER_GUARD_BYTES * num_devices


#: Lookup implementations (ParallelContext.embedding_impl / config flag).
IMPL_AUTO = "auto"
IMPL_RAGGED = "ragged"
IMPL_RAGGED_EMULATED = "ragged_emulated"  # tests: same routing, dense collective
IMPL_DENSE = "dense"
LOOKUP_IMPLS = (IMPL_AUTO, IMPL_RAGGED, IMPL_RAGGED_EMULATED, IMPL_DENSE)


@dataclasses.dataclass(frozen=True)
class ParallelContext:
    """Trace-time description of how the current step is parallelized.

    Passed by the trainer into ``ModelSpec.apply`` so embedding ops know
    whether tables are mesh-sharded (ParameterServer strategy) or replicated
    (AllReduce/Local).  ``axis_name`` is the mesh axis the step runs under
    (None when not inside shard_map).  ``embedding_impl`` picks the sharded
    lookup route; ``auto`` resolves per (platform, mesh size) — the trainer
    resolves it before tracing via :func:`resolve_impl`.  ``tp_axis`` names
    the tensor-parallel mesh axis on a 2D ``(dp, tp)`` mesh (r20) — models
    with a ``tensor_sharding`` plan switch their apply to the column/row
    -split path when it is set; None everywhere else.
    """

    axis_name: Optional[str] = None
    sharded_embeddings: bool = False
    embedding_impl: str = IMPL_AUTO
    tp_axis: Optional[str] = None


def row_stride(dim: int) -> int:
    """Lane stride a logical row occupies in packed storage.

    The next power of two >= dim (so the 128-lane physical row divides into
    whole strides) for dim <= 128, else the next multiple of 128.  Keeping
    the physical width exactly lane-aligned matters: a dim-9 table packed at
    its natural width 126 (14 rows x 9) measured a 3x slower gather than the
    same data at width 128 (8 rows x stride 16) on v5e — dead lanes are
    cheaper than misalignment.
    """
    if dim <= 0:
        raise ValueError(f"embedding dim must be positive, got {dim}")
    if dim >= LANES:
        return ((dim + LANES - 1) // LANES) * LANES
    stride = 1
    while stride < dim:
        stride *= 2
    return stride


def row_pack(dim: int) -> int:
    """Logical rows per 128-lane physical row (1 when dim >= 128)."""
    return max(1, LANES // row_stride(dim))


def pad_vocab(vocab_size: int, dim: int = LANES) -> int:
    """Padded logical vocab: the smallest multiple of pack*PHYSICAL_ROW_MULTIPLE
    >= vocab_size, so the packed table's physical rows divide every
    power-of-two mesh size up to 256."""
    multiple = row_pack(dim) * PHYSICAL_ROW_MULTIPLE
    return ((vocab_size + multiple - 1) // multiple) * multiple


def table_shape(vocab_size: int, dim: int) -> Tuple[int, int]:
    """Packed storage shape [physical_rows, pack*stride] for a padded vocab."""
    pack = row_pack(dim)
    return pad_vocab(vocab_size, dim) // pack, pack * row_stride(dim)


def _pack_geometry(width: int, dim: int) -> Tuple[int, int]:
    """(pack, stride) for a table of physical width ``width`` holding
    ``dim``-sized logical rows.  ``width == dim`` is the plain un-packed
    case; otherwise the stride is :func:`row_stride`'s canonical value."""
    if width == dim:
        return 1, dim
    stride = row_stride(dim)
    if width % stride:
        raise ValueError(
            f"table width {width} is not a multiple of the canonical "
            f"stride {stride} for dim {dim}"
        )
    return width // stride, stride


def init_table(rng: jax.Array, vocab_size: int, dim: int, scale: float = 0.01):
    """A freshly initialized lane-packed [P, pack*dim] table."""
    return jax.random.normal(rng, table_shape(vocab_size, dim)) * scale


def pack_table(table: jax.Array, dim: int) -> jax.Array:
    """Convert a plain [V, dim] (or flat [V*dim]) table into the padded
    lane-packed [P, pack*stride] layout.  Rows past V and lanes past dim
    zero-fill."""
    if table.ndim == 1:
        if table.shape[0] % dim:
            raise ValueError(
                f"flat table of {table.shape[0]} elements is not a multiple "
                f"of dim {dim}"
            )
        table = table.reshape(-1, dim)
    if table.ndim != 2 or table.shape[1] != dim:
        raise ValueError(
            f"expected a [V, {dim}] or flat [V*{dim}] table, got {table.shape}"
        )
    rows, width = table_shape(table.shape[0], dim)
    stride = row_stride(dim)
    pack = width // stride
    padded = rows * pack
    if table.shape[0] < padded:
        table = jnp.concatenate(
            [table, jnp.zeros((padded - table.shape[0], dim), table.dtype)]
        )
    if stride > dim:
        table = jnp.concatenate(
            [table, jnp.zeros((padded, stride - dim), table.dtype)], axis=-1
        )
    return table.reshape(rows, width)


def unpack_table(table: jax.Array, dim: int) -> jax.Array:
    """The [V', dim] logical view of a lane-packed table (padding included)."""
    _, stride = _pack_geometry(table.shape[1], dim)
    return table.reshape(-1, stride)[:, :dim]


def logical_rows(table: jax.Array, dim: int) -> int:
    """Number of logical rows a packed [P, pack*stride] table holds."""
    pack, _ = _pack_geometry(table.shape[1], dim)
    return table.shape[0] * pack


def gather_rows(table: jax.Array, ids: jax.Array, dim: Optional[int] = None):
    """Logical rows ``ids`` of a lane-packed table as ``ids.shape + (dim,)``.

    ``table`` is ``[P, pack*dim]`` (``dim`` defaults to the full width, i.e. a
    plain ``[V, dim]`` table is the ``pack == 1`` case).  Whole-physical-row
    gather + one-hot lane select; its AD transpose is a whole-physical-row
    scatter-add.  Out-of-range ids (either sign) fill with NaN (floats) so
    id-generation bugs surface immediately instead of silently training on a
    clamped row; the fill-mode transpose likewise drops OOB cotangents.
    """
    P, W = table.shape
    if dim is None:
        dim = W
    pack, stride = _pack_geometry(W, dim)
    fill = jnp.nan if jnp.issubdtype(table.dtype, jnp.floating) else 0
    flat_ids = ids.reshape(-1)
    # Mark OOB (either sign) explicitly and redirect to physical row P, which
    # take's fill mode NaN-fills — jnp.take wraps NEGATIVE indices NumPy-style
    # before the bounds check, so a bare -1 would silently read the last row.
    # The redirected rows' cotangents are dropped by the fill-mode transpose,
    # and in the packed path the NaN survives the lane-select einsum below
    # (NaN * 0 == NaN).
    oob = (flat_ids < 0) | (flat_ids >= P * pack)
    if pack == 1:
        idx = jnp.where(oob, P, flat_ids)
        out = jnp.take(table, idx, axis=0, mode="fill", fill_value=fill)
        out = out[:, :dim]
    else:
        hi = jnp.where(oob, P, flat_ids // pack)
        lo = jnp.where(oob, 0, flat_ids - (flat_ids // pack) * pack)
        rows = jnp.take(table, hi, axis=0, mode="fill", fill_value=fill)
        rows = rows.reshape(flat_ids.shape[0], pack, stride)
        sel = jax.nn.one_hot(lo, pack, dtype=table.dtype)
        out = jnp.einsum("nps,np->ns", rows, sel)[:, :dim]
    return out.reshape(ids.shape + (dim,))


def embedding_lookup(
    table: jax.Array,
    ids: jax.Array,
    ctx: ParallelContext,
    dim: Optional[int] = None,
) -> jax.Array:
    """Look up ``ids`` in ``table``.

    ``table`` is 2-D lane-packed ``[P, pack*dim]`` (build with
    :func:`init_table` / :func:`pack_table`; a plain ``[V, dim]`` table is
    the ``pack == 1`` case and needs no ``dim``).  In sharded mode (inside
    shard_map) the array is this device's physical-row range of the padded
    global table and the lookup is collective, as described in the module
    docstring.

    ids may have any shape; output has shape ``ids.shape + (dim,)``.
    """
    if table.ndim != 2:
        raise ValueError(
            f"table must be 2-D lane-packed [P, pack*stride] (got shape "
            f"{table.shape}); convert flat tables with pack_table()"
        )
    if dim is None:
        dim = table.shape[1]
    _pack_geometry(table.shape[1], dim)  # raises on inconsistent width/dim

    if not (ctx.sharded_embeddings and ctx.axis_name):
        return gather_rows(table, ids, dim)
    impl = resolve_impl(ctx.embedding_impl)
    # n=1 degenerates to a local gather (dense short-circuits it); an
    # EXPLICIT ragged request is still honored so the real op can be
    # smoke-tested on a single chip.
    if impl == IMPL_DENSE or (
        axis_size(ctx.axis_name) == 1 and impl == IMPL_RAGGED_EMULATED
    ):
        return _dense_lookup(table, ids, ctx.axis_name, dim)
    return _ragged_lookup(
        table, ids, ctx.axis_name, dim, impl == IMPL_RAGGED_EMULATED
    )


def resolve_impl(
    impl: str, platform: Optional[str] = None, axis_size: Optional[int] = None
) -> str:
    """Resolve ``auto`` to a concrete impl for (platform, mesh size).

    A 1-device axis means dense (whose n=1 path is a plain local gather) —
    paying the ragged route's sort/bincount/collective machinery with zero
    peers to shard over was a measured 28% step tax in round 2.  XLA:CPU has
    no ragged-all-to-all HLO, so auto means dense there too; multi-chip TPU
    means the ragged route.  Explicit impls pass through untouched.
    """
    if impl not in LOOKUP_IMPLS:
        raise ValueError(f"unknown embedding lookup impl {impl!r}")
    if impl != IMPL_AUTO:
        return impl
    if axis_size == 1:
        return IMPL_DENSE
    platform = platform or jax.default_backend()
    return IMPL_RAGGED if platform == "tpu" else IMPL_DENSE


# ---------------------------------------------------------------------------
# dense route: all_gather ids -> masked local gather -> psum_scatter vectors
# ---------------------------------------------------------------------------


def _dense_lookup(local_table: jax.Array, ids: jax.Array, axis_name: str, dim: int):
    n = axis_size(axis_name)
    my_shard = lax.axis_index(axis_name)
    rows_local = logical_rows(local_table, dim)

    ids_shape = ids.shape
    flat_ids = ids.reshape(-1)
    bad = (flat_ids < 0) | (flat_ids >= n * rows_local)
    if n == 1:
        out = gather_rows(local_table, flat_ids, dim)  # NaN-fills OOB itself
        return out.reshape(ids_shape + (dim,))

    # [n * local_ids] — every device's flat id list.
    all_ids = lax.all_gather(flat_ids, axis_name).reshape(-1)

    owner = all_ids // rows_local
    local_row = all_ids - owner * rows_local
    mine = owner == my_shard
    safe_row = jnp.where(mine, local_row, 0)
    vectors = jnp.where(mine[:, None], gather_rows(local_table, safe_row, dim), 0)

    # Route each device its own block, summing over shards (one nonzero each).
    vectors = vectors.reshape(n, -1, dim)
    out = collectives.psum_scatter(
        vectors, axis_name, scatter_dimension=0, tiled=False
    )
    # Fail-loud OOV: an id owned by NO shard summed to zeros above; surface
    # it as NaN to match gather_rows' single-device contract.
    out = jnp.where(bad[:, None], jnp.nan, out)
    return out.reshape(ids_shape + (dim,))


# ---------------------------------------------------------------------------
# ragged route: sort by owner -> ragged all-to-all ids -> local gather ->
# ragged all-to-all vectors back -> unsort        (custom_vjp: retrace route)
# ---------------------------------------------------------------------------


def _ragged_collective(operand, output, in_off, send, out_off, recv, axis_name,
                       emulate: bool):
    """``lax.ragged_all_to_all`` or a semantically-identical dense emulation.

    The emulation exists because XLA:CPU lacks the ragged-all-to-all HLO: it
    all_gathers every device's operand and offset metadata, then each device
    assembles its output buffer position-by-position from the senders' chunks
    — exactly the op's documented placement semantics (chunk ``j`` of device
    ``k``'s operand, ``[in_off[j], +send[j])``, lands in device ``j``'s output
    at ``[out_off[j], +send[j])``).  O(n·len(output)) masks — test-only.
    """
    if not emulate:
        return lax.ragged_all_to_all(
            operand, output,
            in_off.astype(jnp.int32), send.astype(jnp.int32),
            out_off.astype(jnp.int32), recv.astype(jnp.int32),
            axis_name=axis_name,
        )
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    ops = lax.all_gather(operand, axis_name)          # [n, L, ...]
    IN = lax.all_gather(in_off, axis_name)            # [n, n] sender-major
    SE = lax.all_gather(send, axis_name)              # [n, n]
    OUT = lax.all_gather(out_off, axis_name)          # [n, n]
    L_out = output.shape[0]
    pos = jnp.arange(L_out)
    # For sender k, its chunk to me sits at my [OUT[k,me], +SE[k,me]).
    start = OUT[:, me][:, None]                       # [n, 1]
    size = SE[:, me][:, None]
    src0 = IN[:, me][:, None]
    inside = (pos[None, :] >= start) & (pos[None, :] < start + size)  # [n, L_out]
    k_of = jnp.argmax(inside, axis=0)                 # sender for each position
    valid = jnp.any(inside, axis=0)
    src = src0[k_of, 0] + pos - start[k_of, 0]
    flat_src = k_of * ops.shape[1] + jnp.clip(src, 0, ops.shape[1] - 1)
    picked = ops.reshape((-1,) + ops.shape[2:])[flat_src]
    mask = valid.reshape((-1,) + (1,) * (output.ndim - 1))
    return jnp.where(mask, picked, output)


def _routing_plan(ids: jax.Array, axis_name: str, rows_local: int):
    """Per-device routing metadata for the ragged route.

    Returns (perm, sorted_ids, send_sizes, in_off, out_off, recv_sizes,
    back_out_off).  ``S[k, j]`` (how many ids device k sends to shard j) is
    shared via one tiny [n, n] int32 all_gather; every offset both directions
    derives from it, so forward and backward use one consistent plan.
    """
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    # Junk ids get a clamped owner; their original value then misses that
    # owner's row range and NaN-fills (fail-loud OOV, see module docstring).
    owner = jnp.clip(ids // rows_local, 0, n - 1)
    perm = jnp.argsort(owner)
    sorted_ids = ids[perm]
    send_sizes = jnp.bincount(owner, length=n).astype(jnp.int32)
    in_off = _exclusive_cumsum(send_sizes)
    S = lax.all_gather(send_sizes, axis_name)          # [n, n]
    recv_sizes = S[:, me]
    # Where my chunk starts in shard j's recv buffer: senders before me.
    before_me = (jnp.arange(n) < me)[:, None]
    out_off = jnp.sum(jnp.where(before_me, S, 0), axis=0).astype(jnp.int32)
    # Where shard j's RETURN chunk starts in my [L] buffer: my ids are sorted
    # by owner, so it's my in_off — but computed on j's side it must be the
    # same value; return routing reuses in_off/out_off with roles swapped.
    return perm, sorted_ids, send_sizes, in_off, out_off, recv_sizes, S


def _exclusive_cumsum(x: jax.Array) -> jax.Array:
    return jnp.concatenate(
        [jnp.zeros((1,), x.dtype), jnp.cumsum(x)[:-1].astype(x.dtype)]
    )


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _ragged_lookup(local_table, ids, axis_name: str, dim: int, emulate: bool):
    out, _ = _ragged_lookup_fwd(local_table, ids, axis_name, dim, emulate)
    return out


def _ragged_lookup_fwd(local_table, ids, axis_name: str, dim: int, emulate: bool):
    n = axis_size(axis_name)
    rows_local = logical_rows(local_table, dim)
    ids_shape = ids.shape
    flat_ids = ids.reshape(-1)
    L = flat_ids.shape[0]

    (perm, sorted_ids, send, in_off, out_off, recv, S) = _routing_plan(
        flat_ids, axis_name, rows_local
    )
    # ids -> owners.  Buffer statically sized n*L (worst-case skew: every
    # shard's batch hits my rows); -1 padding = OOB = NaN row if ever read.
    id_buf = jnp.full((n * L,), -1, dtype=flat_ids.dtype)
    recv_ids = _ragged_collective(
        sorted_ids, id_buf, in_off, send, out_off, recv, axis_name, emulate
    )
    local_rows = recv_ids - lax.axis_index(axis_name) * rows_local
    vecs = gather_rows(local_table, local_rows, dim)   # [n*L, dim], NaN on OOB

    # vectors -> requesters: exactly the reverse plan.  My block offsets are
    # recv's exclusive cumsum (received chunks are sender-ordered); my chunk
    # lands back where requester j's sorted block for me starts — j's in_off
    # for me, which is S[j, :me].sum() row-wise.
    me = lax.axis_index(axis_name)
    back_in_off = _exclusive_cumsum(recv)
    before = (jnp.arange(n) < me)[None, :]
    back_out_off = jnp.sum(jnp.where(before, S, 0), axis=1).astype(jnp.int32)
    vec_buf = jnp.zeros((L, dim), vecs.dtype)
    sorted_out = _ragged_collective(
        vecs, vec_buf, back_in_off, recv, back_out_off, send, axis_name, emulate
    )
    inv = jnp.zeros_like(perm).at[perm].set(jnp.arange(L))
    out = sorted_out[inv].reshape(ids_shape + (dim,))
    residuals = (perm, send, in_off, out_off, recv, back_in_off, back_out_off,
                 local_rows, local_table.shape, ids_shape)
    return out, residuals


def _ragged_lookup_bwd(axis_name: str, dim: int, emulate: bool, residuals, g):
    (perm, send, in_off, out_off, recv, back_in_off, back_out_off,
     local_rows, table_shape_, ids_shape) = residuals
    n = axis_size(axis_name)
    L = perm.shape[0]
    # Cotangents retrace the forward id route (requester -> owner): sort by
    # owner, ragged a2a with the SAME plan, then whole-physical-row
    # scatter-add into the local shard.  Stale buffer slots hold
    # local_rows=-1 (OOB), so the fill-mode transpose drops them — as it
    # drops junk-id cotangents.
    g_sorted = g.reshape(L, dim)[perm]
    g_buf = jnp.zeros((n * L, dim), g_sorted.dtype)
    g_at_owner = _ragged_collective(
        g_sorted, g_buf, in_off, send, out_off, recv, axis_name, emulate
    )
    zeros = jnp.zeros(table_shape_, g_at_owner.dtype)
    _, pull = jax.vjp(lambda t: gather_rows(t, local_rows, dim), zeros)
    (table_bar,) = pull(g_at_owner)
    ids_bar = np.zeros(ids_shape, jax.dtypes.float0)
    return table_bar, ids_bar


_ragged_lookup.defvjp(_ragged_lookup_fwd, _ragged_lookup_bwd)
