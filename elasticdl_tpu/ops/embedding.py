"""Mesh-sharded embedding lookup — the TPU-native replacement for the
reference's gRPC parameter-server embedding path (``elasticdl.layers.
Embedding`` pulling vectors / pushing IndexedSlices grads over gRPC
[D: BASELINE.json north_star]; reference sources unverifiable, mount empty at
survey time).

Design (static shapes, XLA/ICI-friendly — see SURVEY.md §7 item 5):

- The table is **row-sharded** over the mesh axis: with ``n`` shards and a
  padded vocab ``V'`` (multiple of ``n``), shard ``i`` owns contiguous rows
  ``[i*V'/n, (i+1)*V'/n)``.  This is GSPMD's natural div-sharding of a global
  ``[V', D]`` array, so the same array is addressable both outside shard_map
  (as one logical array for checkpointing) and inside (as the local shard).
- Forward, per device: ``all_gather`` every device's ids (tiny int32
  traffic), gather the rows this shard owns (masked, uniform compute — load
  is balanced regardless of id distribution), then ``psum_scatter`` the
  vectors so each device receives exactly its own batch's embeddings, summed
  across shards (exactly one shard contributed each row).  Vector traffic
  crosses ICI once — the same volume a ragged all-to-all would move.
- Backward is pure JAX AD: the transpose of ``psum_scatter`` is
  ``all_gather`` of the cotangents and the transpose of the masked gather is
  a scatter-add into the local shard — the moral equivalent of the
  reference's server-side IndexedSlices apply, with duplicate ids correctly
  accumulated.

Optimizer state for the table is co-sharded automatically because optax maps
leaf-wise (each shard's Adam moments live next to its rows — like the
reference's per-PS-pod Go optimizer state).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# Pad vocabularies to a multiple of this so the padded size divides every
# power-of-two mesh size up to a v5e-256 pod; table shapes then stay identical
# across elastic resizes (4->8->4 never reshapes params or optimizer state).
DEFAULT_VOCAB_MULTIPLE = 256


@dataclasses.dataclass(frozen=True)
class ParallelContext:
    """Trace-time description of how the current step is parallelized.

    Passed by the trainer into ``ModelSpec.apply`` so embedding ops know
    whether tables are mesh-sharded (ParameterServer strategy) or replicated
    (AllReduce/Local).  ``axis_name`` is the mesh axis the step runs under
    (None when not inside shard_map).
    """

    axis_name: Optional[str] = None
    sharded_embeddings: bool = False


def pad_vocab(vocab_size: int, multiple: int = DEFAULT_VOCAB_MULTIPLE) -> int:
    return ((vocab_size + multiple - 1) // multiple) * multiple


def embedding_lookup(
    table: jax.Array, ids: jax.Array, ctx: ParallelContext
) -> jax.Array:
    """Look up ``ids`` in ``table``.

    - Replicated mode: a plain gather (``table[ids]``).
    - Sharded mode (inside shard_map): ``table`` is this device's local row
      shard of the padded global table; collective lookup as described in the
      module docstring.

    ids may have any shape; output has shape ``ids.shape + (dim,)``.
    """
    if not (ctx.sharded_embeddings and ctx.axis_name):
        return jnp.take(table, ids, axis=0)
    return _sharded_lookup(table, ids, ctx.axis_name)


def _sharded_lookup(local_table: jax.Array, ids: jax.Array, axis_name: str):
    n = lax.axis_size(axis_name)
    my_shard = lax.axis_index(axis_name)
    rows_local, dim = local_table.shape

    ids_shape = ids.shape
    # [n, local_ids] — every device's flat id list.
    all_ids = lax.all_gather(ids.reshape(-1), axis_name)
    flat = all_ids.reshape(-1)

    owner = flat // rows_local
    local_row = flat - owner * rows_local
    mine = owner == my_shard
    safe_row = jnp.where(mine, local_row, 0)
    vectors = jnp.where(mine[:, None], local_table[safe_row], 0)

    # Route each device its own block, summing over shards (one nonzero each).
    vectors = vectors.reshape(n, -1, dim)
    out = lax.psum_scatter(vectors, axis_name, scatter_dimension=0, tiled=False)
    return out.reshape(ids_shape + (dim,))
