"""Mesh-sharded embedding lookup — the TPU-native replacement for the
reference's gRPC parameter-server embedding path (``elasticdl.layers.
Embedding`` pulling vectors / pushing IndexedSlices grads over gRPC
[D: BASELINE.json north_star]; reference sources unverifiable, mount empty at
survey time).

Design (static shapes, XLA/ICI-friendly — see SURVEY.md §7 item 5):

- **Flat storage.**  A table of ``V'`` rows × ``dim`` is stored as ONE 1-D
  array ``[V' * dim]`` and rows are fetched as contiguous ``dim``-element
  slices (``lax.gather`` with ``slice_sizes=(dim,)``).  This is the fast
  path on TPU: a 1-D array has the packed ``T(1024)`` tiling, so a row is
  one contiguous 4·dim-byte read and the AD-transpose scatter-add writes the
  same way.  2-D ``[V', dim]`` tables with small ``dim`` hit pathological
  layouts instead — XLA picks a vocab-minor layout ``{0,1}`` to avoid lane
  padding, which turns every row gather/scatter into ``dim`` strided
  accesses (measured 8.9 ms for one scatter-add of 213k rows on a v5e chip
  vs 0.03 ms flat — a ~300x difference; profiled via hlo_stats, fusion.3
  "bound by VMEM Write" at 2.2 GiB/s).
- The flat table is **row-sharded** over the mesh axis: with ``n`` shards
  and padded vocab ``V'`` (multiple of ``n``), shard ``i`` owns flat range
  ``[i*V'*dim/n, (i+1)*V'*dim/n)`` = rows ``[i*V'/n, (i+1)*V'/n)`` — GSPMD's
  natural div-sharding of the 1-D array, so the same array is addressable
  both outside shard_map (one logical array, e.g. for Orbax) and inside (the
  local row range).
- Forward, per device: ``all_gather`` every device's ids (tiny int32
  traffic), slice-gather the rows this shard owns (masked, uniform compute —
  load is balanced regardless of id distribution), then ``psum_scatter`` the
  vectors so each device receives exactly its own batch's embeddings, summed
  across shards (exactly one shard contributed each row).  Vector traffic
  crosses ICI once — the same volume a ragged all-to-all would move.
- Backward is pure JAX AD: the transpose of ``psum_scatter`` is
  ``all_gather`` of the cotangents and the transpose of the slice gather is
  a contiguous scatter-add into the local shard — the moral equivalent of
  the reference's server-side IndexedSlices apply, with duplicate ids
  correctly accumulated.

Optimizer state for the table is co-sharded automatically because optax maps
leaf-wise (each shard's Adam moments live next to its rows — like the
reference's per-PS-pod Go optimizer state).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# Pad vocabularies to a multiple of this so the padded size divides every
# power-of-two mesh size up to a v5e-256 pod; table shapes then stay identical
# across elastic resizes (4->8->4 never reshapes params or optimizer state).
DEFAULT_VOCAB_MULTIPLE = 256

_GATHER_DNUMS = lax.GatherDimensionNumbers(
    offset_dims=(1,), collapsed_slice_dims=(), start_index_map=(0,)
)


@dataclasses.dataclass(frozen=True)
class ParallelContext:
    """Trace-time description of how the current step is parallelized.

    Passed by the trainer into ``ModelSpec.apply`` so embedding ops know
    whether tables are mesh-sharded (ParameterServer strategy) or replicated
    (AllReduce/Local).  ``axis_name`` is the mesh axis the step runs under
    (None when not inside shard_map).
    """

    axis_name: Optional[str] = None
    sharded_embeddings: bool = False


def pad_vocab(vocab_size: int, multiple: int = DEFAULT_VOCAB_MULTIPLE) -> int:
    return ((vocab_size + multiple - 1) // multiple) * multiple


def flat_table_size(vocab_size: int, dim: int) -> int:
    """Storage length of a flat table with a padded vocab.

    Flat offsets are computed as ``id * dim`` in int32 (jax's default —
    x64 is disabled), so the whole table must stay addressable in int32;
    beyond that the old 2-D path would be required (or id-space sharding
    across multiple tables).  Raise loudly instead of wrapping silently.
    """
    size = pad_vocab(vocab_size) * dim
    if size > 2**31 - 1:
        raise ValueError(
            f"flat table of {pad_vocab(vocab_size)} rows x dim {dim} exceeds "
            "int32 addressing; shard the id space over multiple tables"
        )
    return size


def init_flat_table(rng: jax.Array, vocab_size: int, dim: int, scale: float = 0.01):
    """A freshly initialized flat [pad_vocab(V)*dim] table."""
    return jax.random.normal(rng, (flat_table_size(vocab_size, dim),)) * scale


def gather_rows(flat_table: jax.Array, ids: jax.Array, dim: int) -> jax.Array:
    """Rows ``ids`` of a flat table as ``ids.shape + (dim,)``.

    Contiguous-slice gather; its AD transpose is a contiguous scatter-add.
    Out-of-range ids fill with NaN (floats) so id-generation bugs surface
    immediately instead of silently training on a clamped row; the sharded
    path returns zeros for the same bug (no shard owns the row).  The
    FILL_OR_DROP transpose likewise drops OOB cotangents.
    """
    starts = (ids.reshape(-1, 1) * dim).astype(jnp.int32)
    out = lax.gather(
        flat_table,
        starts,
        _GATHER_DNUMS,
        slice_sizes=(dim,),
        mode=lax.GatherScatterMode.FILL_OR_DROP,
        fill_value=jnp.nan if jnp.issubdtype(flat_table.dtype, jnp.floating) else 0,
    )
    return out.reshape(ids.shape + (dim,))


def embedding_lookup(
    table: jax.Array,
    ids: jax.Array,
    ctx: ParallelContext,
    dim: Optional[int] = None,
) -> jax.Array:
    """Look up ``ids`` in ``table``.

    ``table`` is either flat 1-D ``[V'*dim]`` (preferred on TPU — pass
    ``dim``) or 2-D ``[V', dim]``.  In sharded mode (inside shard_map) the
    array is this device's local row range of the padded global table and
    the lookup is collective, as described in the module docstring.

    ids may have any shape; output has shape ``ids.shape + (dim,)``.
    """
    if table.ndim == 2:
        if dim is not None and dim != table.shape[1]:
            raise ValueError(f"dim={dim} but table has dim {table.shape[1]}")
        dim = table.shape[1]
        flat = table.reshape(-1)
    elif table.ndim == 1:
        if dim is None:
            raise ValueError("flat tables need an explicit dim")
        flat = table
    else:
        raise ValueError(f"table must be 1-D or 2-D, got shape {table.shape}")

    if not (ctx.sharded_embeddings and ctx.axis_name):
        return gather_rows(flat, ids, dim)
    return _sharded_lookup(flat, ids, ctx.axis_name, dim)


def _sharded_lookup(local_flat: jax.Array, ids: jax.Array, axis_name: str, dim: int):
    n = lax.axis_size(axis_name)
    my_shard = lax.axis_index(axis_name)
    rows_local = local_flat.shape[0] // dim

    ids_shape = ids.shape
    # [n, local_ids] — every device's flat id list.
    all_ids = lax.all_gather(ids.reshape(-1), axis_name)
    flat_ids = all_ids.reshape(-1)

    owner = flat_ids // rows_local
    local_row = flat_ids - owner * rows_local
    mine = owner == my_shard
    safe_row = jnp.where(mine, local_row, 0)
    vectors = jnp.where(mine[:, None], gather_rows(local_flat, safe_row, dim), 0)

    # Route each device its own block, summing over shards (one nonzero each).
    vectors = vectors.reshape(n, -1, dim)
    out = lax.psum_scatter(vectors, axis_name, scatter_dimension=0, tiled=False)
    return out.reshape(ids_shape + (dim,))
