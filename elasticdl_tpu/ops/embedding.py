"""Mesh-sharded embedding lookup — the TPU-native replacement for the
reference's gRPC parameter-server embedding path (``elasticdl.layers.
Embedding`` pulling vectors / pushing IndexedSlices grads over gRPC
[D: BASELINE.json north_star]; reference sources unverifiable, mount empty at
survey time).

Design (static shapes, XLA/ICI-friendly — see SURVEY.md §7 item 5):

- **Flat storage.**  A table of ``V'`` rows × ``dim`` is stored as ONE 1-D
  array ``[V' * dim]`` and rows are fetched as contiguous ``dim``-element
  slices (``lax.gather`` with ``slice_sizes=(dim,)``).  This is the fast
  path on TPU: a 1-D array has the packed ``T(1024)`` tiling, so a row is
  one contiguous 4·dim-byte read and the AD-transpose scatter-add writes the
  same way.  2-D ``[V', dim]`` tables with small ``dim`` hit pathological
  layouts instead — XLA picks a vocab-minor layout ``{0,1}`` to avoid lane
  padding, which turns every row gather/scatter into ``dim`` strided
  accesses (measured 8.9 ms for one scatter-add of 213k rows on a v5e chip
  vs 0.03 ms flat — a ~300x difference; profiled via hlo_stats, fusion.3
  "bound by VMEM Write" at 2.2 GiB/s).
- The flat table is **row-sharded** over the mesh axis: with ``n`` shards
  and padded vocab ``V'`` (multiple of ``n``), shard ``i`` owns flat range
  ``[i*V'*dim/n, (i+1)*V'*dim/n)`` = rows ``[i*V'/n, (i+1)*V'/n)`` — GSPMD's
  natural div-sharding of the 1-D array, so the same array is addressable
  both outside shard_map (one logical array, e.g. for Orbax) and inside (the
  local row range).

Two collective lookup implementations, selected at trace time:

- ``ragged`` (default on TPU) — the north-star **ragged all-to-all** route:
  sort local ids by owner shard, exchange per-destination counts (n² int32),
  ``lax.ragged_all_to_all`` the ids to their owners, slice-gather locally,
  ``lax.ragged_all_to_all`` the vectors straight back, unsort.  Each vector
  crosses ICI exactly once, so per-device vector traffic is ~``B_local·dim``
  (id-distribution dependent), independent of mesh size.  XLA:CPU does not
  implement the ``ragged-all-to-all`` HLO, so tests exercise the identical
  routing/offset/unsort code through a dense all_gather emulation of the
  collective (``ragged_emulated``) that is semantically equivalent by
  construction.
- ``dense`` (CPU fallback; also the n=1 degenerate) — ``all_gather`` every
  device's ids, masked slice-gather over the full global id list, then
  ``psum_scatter`` a ``[n·B_local, dim]`` array so each device receives its
  own rows.  Simple and always available, but the psum_scatter moves
  ~``(n-1)·B_local·dim`` per device — ~(n−1)× the ragged route's vector
  volume — so it loses badly at pod scale.

Backward (both impls): the cotangents retrace the forward route back to the
owner shard and scatter-add into its local rows (contiguous flat scatter —
the transpose of the slice gather), with duplicate ids correctly accumulated
— the moral equivalent of the reference's server-side IndexedSlices apply.
The ragged impl does this through a ``custom_vjp`` (the ragged collective has
no AD rule): the saved routing metadata is replayed, vectors flow
requester→owner, and the owner applies the same masked scatter-add.

Fail-loud OOV contract (both impls): an id outside the padded global vocab
comes back as a NaN row — never a silently wrong or zero row.  In the ragged
impl this is structural: the junk id routes to a clamped owner whose local
row range it misses, the FILL_OR_DROP gather fills NaN, and the NaN rides
back to the requester; its cotangent is dropped on the same grounds.

Optimizer state for the table is co-sharded automatically because optax maps
leaf-wise (each shard's Adam moments live next to its rows — like the
reference's per-PS-pod Go optimizer state).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Pad vocabularies to a multiple of this so the padded size divides every
# power-of-two mesh size up to a v5e-256 pod; table shapes then stay identical
# across elastic resizes (4->8->4 never reshapes params or optimizer state).
DEFAULT_VOCAB_MULTIPLE = 256

_GATHER_DNUMS = lax.GatherDimensionNumbers(
    offset_dims=(1,), collapsed_slice_dims=(), start_index_map=(0,)
)

#: Lookup implementations (ParallelContext.embedding_impl / config flag).
IMPL_AUTO = "auto"
IMPL_RAGGED = "ragged"
IMPL_RAGGED_EMULATED = "ragged_emulated"  # tests: same routing, dense collective
IMPL_DENSE = "dense"
LOOKUP_IMPLS = (IMPL_AUTO, IMPL_RAGGED, IMPL_RAGGED_EMULATED, IMPL_DENSE)


@dataclasses.dataclass(frozen=True)
class ParallelContext:
    """Trace-time description of how the current step is parallelized.

    Passed by the trainer into ``ModelSpec.apply`` so embedding ops know
    whether tables are mesh-sharded (ParameterServer strategy) or replicated
    (AllReduce/Local).  ``axis_name`` is the mesh axis the step runs under
    (None when not inside shard_map).  ``embedding_impl`` picks the sharded
    lookup route; ``auto`` resolves to ragged on TPU meshes and dense
    elsewhere (the trainer resolves it before tracing).
    """

    axis_name: Optional[str] = None
    sharded_embeddings: bool = False
    embedding_impl: str = IMPL_AUTO


def pad_vocab(vocab_size: int, multiple: int = DEFAULT_VOCAB_MULTIPLE) -> int:
    return ((vocab_size + multiple - 1) // multiple) * multiple


def flat_table_size(vocab_size: int, dim: int) -> int:
    """Storage length of a flat table with a padded vocab.

    Flat offsets are computed as ``id * dim`` in int32 (jax's default —
    x64 is disabled), so the whole table must stay addressable in int32;
    beyond that the old 2-D path would be required (or id-space sharding
    across multiple tables).  Raise loudly instead of wrapping silently.
    """
    size = pad_vocab(vocab_size) * dim
    if size > 2**31 - 1:
        raise ValueError(
            f"flat table of {pad_vocab(vocab_size)} rows x dim {dim} exceeds "
            "int32 addressing; shard the id space over multiple tables"
        )
    return size


def init_flat_table(rng: jax.Array, vocab_size: int, dim: int, scale: float = 0.01):
    """A freshly initialized flat [pad_vocab(V)*dim] table."""
    return jax.random.normal(rng, (flat_table_size(vocab_size, dim),)) * scale


def gather_rows(flat_table: jax.Array, ids: jax.Array, dim: int) -> jax.Array:
    """Rows ``ids`` of a flat table as ``ids.shape + (dim,)``.

    Contiguous-slice gather; its AD transpose is a contiguous scatter-add.
    Out-of-range ids fill with NaN (floats) so id-generation bugs surface
    immediately instead of silently training on a clamped row.  The
    FILL_OR_DROP transpose likewise drops OOB cotangents.
    """
    # Mark out-of-range ids BEFORE the ``* dim`` scaling: a junk id large
    # enough to overflow int32 in ``id * dim`` could wrap back into range and
    # silently gather a wrong row, breaking the NaN-fill guarantee.  Rows
    # outside [0, num_rows) get an explicitly OOB start (the flat length), so
    # FILL_OR_DROP always sees them as out of bounds.
    num_rows = flat_table.shape[0] // dim
    ids_flat = ids.reshape(-1, 1)
    oob = (ids_flat < 0) | (ids_flat >= num_rows)
    starts = jnp.where(oob, flat_table.shape[0], ids_flat * dim).astype(jnp.int32)
    out = lax.gather(
        flat_table,
        starts,
        _GATHER_DNUMS,
        slice_sizes=(dim,),
        mode=lax.GatherScatterMode.FILL_OR_DROP,
        fill_value=jnp.nan if jnp.issubdtype(flat_table.dtype, jnp.floating) else 0,
    )
    return out.reshape(ids.shape + (dim,))


def embedding_lookup(
    table: jax.Array,
    ids: jax.Array,
    ctx: ParallelContext,
    dim: Optional[int] = None,
) -> jax.Array:
    """Look up ``ids`` in ``table``.

    ``table`` is either flat 1-D ``[V'*dim]`` (preferred on TPU — pass
    ``dim``) or 2-D ``[V', dim]``.  In sharded mode (inside shard_map) the
    array is this device's local row range of the padded global table and
    the lookup is collective, as described in the module docstring.

    ids may have any shape; output has shape ``ids.shape + (dim,)``.
    """
    if table.ndim == 2:
        if dim is not None and dim != table.shape[1]:
            raise ValueError(f"dim={dim} but table has dim {table.shape[1]}")
        dim = table.shape[1]
        flat = table.reshape(-1)
    elif table.ndim == 1:
        if dim is None:
            raise ValueError("flat tables need an explicit dim")
        flat = table
    else:
        raise ValueError(f"table must be 1-D or 2-D, got shape {table.shape}")

    if not (ctx.sharded_embeddings and ctx.axis_name):
        return gather_rows(flat, ids, dim)
    impl = resolve_impl(ctx.embedding_impl)
    # n=1 degenerates to a local gather (dense short-circuits it); an
    # EXPLICIT ragged request is still honored so the real op can be
    # smoke-tested on a single chip.
    if impl == IMPL_DENSE or (
        lax.axis_size(ctx.axis_name) == 1 and impl == IMPL_RAGGED_EMULATED
    ):
        return _dense_lookup(flat, ids, ctx.axis_name, dim)
    return _ragged_lookup(
        flat, ids, ctx.axis_name, dim, impl == IMPL_RAGGED_EMULATED
    )


def resolve_impl(impl: str, platform: Optional[str] = None) -> str:
    """Resolve ``auto`` to a concrete impl for ``platform`` (default: the
    current default backend).  XLA:CPU has no ragged-all-to-all HLO, so auto
    means dense there; on TPU it means the ragged route."""
    if impl not in LOOKUP_IMPLS:
        raise ValueError(f"unknown embedding lookup impl {impl!r}")
    if impl != IMPL_AUTO:
        return impl
    platform = platform or jax.default_backend()
    return IMPL_RAGGED if platform == "tpu" else IMPL_DENSE


# ---------------------------------------------------------------------------
# dense route: all_gather ids -> masked local gather -> psum_scatter vectors
# ---------------------------------------------------------------------------


def _dense_lookup(local_flat: jax.Array, ids: jax.Array, axis_name: str, dim: int):
    n = lax.axis_size(axis_name)
    my_shard = lax.axis_index(axis_name)
    rows_local = local_flat.shape[0] // dim

    ids_shape = ids.shape
    flat_ids = ids.reshape(-1)
    bad = (flat_ids < 0) | (flat_ids >= n * rows_local)
    if n == 1:
        out = gather_rows(local_flat, flat_ids, dim)  # NaN-fills OOB itself
        return out.reshape(ids_shape + (dim,))

    # [n * local_ids] — every device's flat id list.
    all_ids = lax.all_gather(flat_ids, axis_name).reshape(-1)

    owner = all_ids // rows_local
    local_row = all_ids - owner * rows_local
    mine = owner == my_shard
    safe_row = jnp.where(mine, local_row, 0)
    vectors = jnp.where(mine[:, None], gather_rows(local_flat, safe_row, dim), 0)

    # Route each device its own block, summing over shards (one nonzero each).
    vectors = vectors.reshape(n, -1, dim)
    out = lax.psum_scatter(vectors, axis_name, scatter_dimension=0, tiled=False)
    # Fail-loud OOV: an id owned by NO shard summed to zeros above; surface
    # it as NaN to match gather_rows' single-device contract.
    out = jnp.where(bad[:, None], jnp.nan, out)
    return out.reshape(ids_shape + (dim,))


# ---------------------------------------------------------------------------
# ragged route: sort by owner -> ragged all-to-all ids -> local gather ->
# ragged all-to-all vectors back -> unsort        (custom_vjp: retrace route)
# ---------------------------------------------------------------------------


def _ragged_collective(operand, output, in_off, send, out_off, recv, axis_name,
                       emulate: bool):
    """``lax.ragged_all_to_all`` or a semantically-identical dense emulation.

    The emulation exists because XLA:CPU lacks the ragged-all-to-all HLO: it
    all_gathers every device's operand and offset metadata, then each device
    assembles its output buffer position-by-position from the senders' chunks
    — exactly the op's documented placement semantics (chunk ``j`` of device
    ``k``'s operand, ``[in_off[j], +send[j])``, lands in device ``j``'s output
    at ``[out_off[j], +send[j])``).  O(n·len(output)) masks — test-only.
    """
    if not emulate:
        return lax.ragged_all_to_all(
            operand, output,
            in_off.astype(jnp.int32), send.astype(jnp.int32),
            out_off.astype(jnp.int32), recv.astype(jnp.int32),
            axis_name=axis_name,
        )
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    ops = lax.all_gather(operand, axis_name)          # [n, L, ...]
    IN = lax.all_gather(in_off, axis_name)            # [n, n] sender-major
    SE = lax.all_gather(send, axis_name)              # [n, n]
    OUT = lax.all_gather(out_off, axis_name)          # [n, n]
    L_out = output.shape[0]
    pos = jnp.arange(L_out)
    # For sender k, its chunk to me sits at my [OUT[k,me], +SE[k,me]).
    start = OUT[:, me][:, None]                       # [n, 1]
    size = SE[:, me][:, None]
    src0 = IN[:, me][:, None]
    inside = (pos[None, :] >= start) & (pos[None, :] < start + size)  # [n, L_out]
    k_of = jnp.argmax(inside, axis=0)                 # sender for each position
    valid = jnp.any(inside, axis=0)
    src = src0[k_of, 0] + pos - start[k_of, 0]
    flat_src = k_of * ops.shape[1] + jnp.clip(src, 0, ops.shape[1] - 1)
    picked = ops.reshape((-1,) + ops.shape[2:])[flat_src]
    mask = valid.reshape((-1,) + (1,) * (output.ndim - 1))
    return jnp.where(mask, picked, output)


def _routing_plan(ids: jax.Array, axis_name: str, rows_local: int):
    """Per-device routing metadata for the ragged route.

    Returns (perm, sorted_ids, send_sizes, in_off, out_off, recv_sizes,
    back_out_off).  ``S[k, j]`` (how many ids device k sends to shard j) is
    shared via one tiny [n, n] int32 all_gather; every offset both directions
    derives from it, so forward and backward use one consistent plan.
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    # Junk ids get a clamped owner; their original value then misses that
    # owner's row range and NaN-fills (fail-loud OOV, see module docstring).
    owner = jnp.clip(ids // rows_local, 0, n - 1)
    perm = jnp.argsort(owner)
    sorted_ids = ids[perm]
    send_sizes = jnp.bincount(owner, length=n).astype(jnp.int32)
    in_off = _exclusive_cumsum(send_sizes)
    S = lax.all_gather(send_sizes, axis_name)          # [n, n]
    recv_sizes = S[:, me]
    # Where my chunk starts in shard j's recv buffer: senders before me.
    before_me = (jnp.arange(n) < me)[:, None]
    out_off = jnp.sum(jnp.where(before_me, S, 0), axis=0).astype(jnp.int32)
    # Where shard j's RETURN chunk starts in my [L] buffer: my ids are sorted
    # by owner, so it's my in_off — but computed on j's side it must be the
    # same value; return routing reuses in_off/out_off with roles swapped.
    return perm, sorted_ids, send_sizes, in_off, out_off, recv_sizes, S


def _exclusive_cumsum(x: jax.Array) -> jax.Array:
    return jnp.concatenate(
        [jnp.zeros((1,), x.dtype), jnp.cumsum(x)[:-1].astype(x.dtype)]
    )


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _ragged_lookup(local_flat, ids, axis_name: str, dim: int, emulate: bool):
    out, _ = _ragged_lookup_fwd(local_flat, ids, axis_name, dim, emulate)
    return out


def _ragged_lookup_fwd(local_flat, ids, axis_name: str, dim: int, emulate: bool):
    n = lax.axis_size(axis_name)
    rows_local = local_flat.shape[0] // dim
    ids_shape = ids.shape
    flat_ids = ids.reshape(-1)
    L = flat_ids.shape[0]

    (perm, sorted_ids, send, in_off, out_off, recv, S) = _routing_plan(
        flat_ids, axis_name, rows_local
    )
    # ids -> owners.  Buffer statically sized n*L (worst-case skew: every
    # shard's batch hits my rows); -1 padding = OOB = NaN row if ever read.
    id_buf = jnp.full((n * L,), -1, dtype=flat_ids.dtype)
    recv_ids = _ragged_collective(
        sorted_ids, id_buf, in_off, send, out_off, recv, axis_name, emulate
    )
    local_rows = recv_ids - lax.axis_index(axis_name) * rows_local
    vecs = gather_rows(local_flat, local_rows, dim)    # [n*L, dim], NaN on OOB

    # vectors -> requesters: exactly the reverse plan.  My block offsets are
    # recv's exclusive cumsum (received chunks are sender-ordered); my chunk
    # lands back where requester j's sorted block for me starts — j's in_off
    # for me, which is S[j, :me].sum() row-wise.
    me = lax.axis_index(axis_name)
    back_in_off = _exclusive_cumsum(recv)
    before = (jnp.arange(n) < me)[None, :]
    back_out_off = jnp.sum(jnp.where(before, S, 0), axis=1).astype(jnp.int32)
    vec_buf = jnp.zeros((L, dim), vecs.dtype)
    sorted_out = _ragged_collective(
        vecs, vec_buf, back_in_off, recv, back_out_off, send, axis_name, emulate
    )
    inv = jnp.zeros_like(perm).at[perm].set(jnp.arange(L))
    out = sorted_out[inv].reshape(ids_shape + (dim,))
    residuals = (perm, send, in_off, out_off, recv, back_in_off, back_out_off,
                 local_rows, local_flat.shape[0], ids_shape)
    return out, residuals


def _ragged_lookup_bwd(axis_name: str, dim: int, emulate: bool, residuals, g):
    (perm, send, in_off, out_off, recv, back_in_off, back_out_off,
     local_rows, flat_len, ids_shape) = residuals
    n = lax.axis_size(axis_name)
    L = perm.shape[0]
    # Cotangents retrace the forward id route (requester -> owner): sort by
    # owner, ragged a2a with the SAME plan, then contiguous scatter-add into
    # the local shard.  Stale buffer slots hold local_rows=-1 (OOB), so
    # FILL_OR_DROP's transpose drops them — as it drops junk-id cotangents.
    g_sorted = g.reshape(L, dim)[perm]
    g_buf = jnp.zeros((n * L, dim), g_sorted.dtype)
    g_at_owner = _ragged_collective(
        g_sorted, g_buf, in_off, send, out_off, recv, axis_name, emulate
    )
    zeros = jnp.zeros((flat_len,), g_at_owner.dtype)
    _, pull = jax.vjp(lambda t: gather_rows(t, local_rows, dim), zeros)
    (table_bar,) = pull(g_at_owner)
    ids_bar = np.zeros(ids_shape, jax.dtypes.float0)
    return table_bar, ids_bar


_ragged_lookup.defvjp(_ragged_lookup_fwd, _ragged_lookup_bwd)
