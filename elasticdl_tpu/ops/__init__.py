from elasticdl_tpu.ops.embedding import (  # noqa: F401
    ParallelContext,
    embedding_lookup,
    init_table,
    pack_table,
    pad_vocab,
    table_shape,
)
