from elasticdl_tpu.ops.embedding import (  # noqa: F401
    ParallelContext,
    embedding_lookup,
    pad_vocab,
)
