"""Pallas TPU flash attention — the hot-op kernel for the transformer LM.

The XLA blockwise path (ops/ring_attention.py) materializes [B, H, Lq, Lk]
f32 score/prob tensors in HBM — ~800 MB per layer at the MFU-bench shape
(b=16, h=12, L=1024) — which HBM bandwidth, not the MXU, then bounds.  This
kernel tiles queries over a Pallas grid, keeps the whole K/V block resident
in VMEM (256 KB at L=1024 lane-padded — far under the ~16 MB/core budget),
and never writes an O(L^2) tensor to HBM: scores live in VMEM per q-tile.

Scope: exact (non-ring) causal/full self-attention — the single-device and
dp-only configurations, and the n=1 degenerate ring.  The n>1 sequence-
parallel ring keeps the XLA streaming-softmax path: its per-device L is
already sharded n-fold, so the O(L^2) HBM pressure this kernel removes
drops quadratically exactly when the ring turns on.

Layouts: public API takes the model layout [B, L, H, D]; kernels run on
[B*H, L, Dp] with the head dim lane-padded to 128 (D=64 at the GPT-2-small
shape; the MXU is 128 wide, so zero-padding costs nothing the idle lanes
were not already wasting).  Per-query vectors (logsumexp, the backward's
delta) use a tile-legal [BH, n_q, 8, TQ] layout — row 0 carries the data —
because Mosaic requires the last two block dims be (8k, 128k).

Training runs through a custom_vjp (standard flash backward: save out +
logsumexp, recompute probabilities per tile; dq recomputes its own softmax
stats since it re-derives full score rows anyway).

VMEM bound: whole-K/V residency asserts L <= 8192 (per-program footprint
~4 MB f32 scores at that limit); longer sequences are what sequence
parallelism is for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# q rows per grid program: one MXU face; f32 (8,128) and bf16 (16,128) min
# tiles both divide it.
_TQ = 128
_LANE = 128     # head-dim lane padding target
_SUB = 8        # sublane rows in the vector layout (row 0 is the data)
_MAX_L = 8192   # whole-K/V-in-VMEM bound (see module docstring)


def _use_interpret() -> bool:
    # Any non-TPU backend (CPU tests/dryruns, GPU, METAL, …) runs the
    # kernel in interpreter mode — slow but exact, keeping one code path
    # under test everywhere.  Gating on "not tpu" rather than "cpu":
    # ``supports()`` passes wherever the op is mathematically valid, and a
    # compiled Pallas-TPU lowering on a non-TPU backend fails in Mosaic
    # after that check has already admitted the op.
    return jax.default_backend() != "tpu"


def _causal_mask(qi, lk: int):
    """[TQ, lk] bool: query global row >= key global col."""
    q_pos = qi * _TQ + jax.lax.broadcasted_iota(jnp.int32, (_TQ, lk), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (_TQ, lk), 1)
    return q_pos >= k_pos


def _dot(a, b, dims):
    return jax.lax.dot_general(
        a, b, (dims, ((), ())), preferred_element_type=jnp.float32
    )


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale):
    qi = pl.program_id(1)
    q, k, v = q_ref[0], k_ref[0], v_ref[0]
    s = _dot(q, k, ((1,), (1,))) * scale          # [TQ, Lk] f32, VMEM-only
    if causal:
        s = jnp.where(_causal_mask(qi, k.shape[0]), s, -jnp.inf)
    m = jnp.max(s, axis=-1)                       # [TQ]
    safe_m = jnp.where(jnp.isneginf(m), 0.0, m)   # all-masked row guard
    p = jnp.exp(s - safe_m[:, None])
    l = jnp.sum(p, axis=-1)
    o = _dot(p.astype(q.dtype), v, ((1,), (0,)))  # [TQ, Dp] f32
    o_ref[0] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, 0, :] = safe_m + jnp.log(jnp.maximum(l, 1e-30))


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, delta_ref, dq_ref, *, causal,
               scale):
    qi = pl.program_id(1)
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    delta = delta_ref[0, 0, 0, :]                 # [TQ] f32
    # Recompute softmax stats: this kernel derives full score rows anyway,
    # so the lse residual is not needed here.
    s = _dot(q, k, ((1,), (1,))) * scale
    if causal:
        s = jnp.where(_causal_mask(qi, k.shape[0]), s, -jnp.inf)
    m = jnp.max(s, axis=-1)
    safe_m = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - safe_m[:, None])
    p = p / jnp.maximum(jnp.sum(p, axis=-1), 1e-30)[:, None]
    dp = _dot(do, v, ((1,), (1,)))                # [TQ, Lk]
    ds = p * (dp - delta[:, None])
    dq_ref[0] = (_dot(ds.astype(q.dtype), k, ((1,), (0,))) * scale).astype(
        dq_ref.dtype
    )


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, causal, scale, n_q):
    ki = pl.program_id(1)
    k, v = k_ref[0], v_ref[0]                     # [TK, Dp] (TK == TQ)
    tk = k.shape[0]
    # ROLLED loop over q tiles (fori_loop, buffers reused): an unrolled
    # Python loop at n_q=64 (L=8192) accumulated per-iteration [TK, TQ]
    # temporaries on Mosaic's VMEM stack past the 16 MB budget.  Per-query
    # vectors are read by dynamic sublane index from the [n_q, 8, TQ]
    # resident block.  Under causal masking, q tiles strictly above the
    # diagonal (qi < ki) contribute nothing — lax.cond skips their three
    # dots at runtime, reclaiming ~half the backward's key-side FLOPs.

    def body(qi, acc):
        dk, dv = acc
        q = q_ref[0, pl.ds(qi * _TQ, _TQ)]        # [TQ, Dp]
        do = do_ref[0, pl.ds(qi * _TQ, _TQ)]
        lse = lse_ref[0, qi, 0, :]                # [TQ] f32
        delta = delta_ref[0, qi, 0, :]

        def _contrib():
            st = _dot(k, q, ((1,), (1,))) * scale   # [TK, TQ]
            pt = jnp.exp(st - lse[None, :])
            if causal:
                k_pos = ki * _TQ + jax.lax.broadcasted_iota(
                    jnp.int32, (tk, _TQ), 0
                )
                q_pos = qi * _TQ + jax.lax.broadcasted_iota(
                    jnp.int32, (tk, _TQ), 1
                )
                pt = jnp.where(q_pos >= k_pos, pt, 0.0)
            dv_c = _dot(pt.astype(q.dtype), do, ((1,), (0,)))
            dpt = _dot(v, do, ((1,), (1,)))         # [TK, TQ]
            dst = pt * (dpt - delta[None, :])
            dk_c = _dot(dst.astype(q.dtype), q, ((1,), (0,))) * scale
            return dk + dk_c, dv + dv_c

        if causal:
            return jax.lax.cond(qi >= ki, _contrib, lambda: (dk, dv))
        return _contrib()

    dk0 = jnp.zeros((tk, k.shape[1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n_q, body, (dk0, jnp.zeros_like(dk0)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _to_kernel_layout(x):
    """[B, L, H, D] -> [B*H, L, Dp] with the head dim lane-padded."""
    b, l, h, d = x.shape
    x = x.transpose(0, 2, 1, 3).reshape(b * h, l, d)
    if d < _LANE:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, _LANE - d)))
    return x


def _from_kernel_layout(x, b, h, d):
    x = x[..., :d]
    return x.reshape(b, h, x.shape[1], d).transpose(0, 2, 1, 3)


def _vec4(x_bh_lq, n_q):
    """[BH, Lq] f32 -> tile-legal [BH, n_q, 8, TQ] with data in row 0."""
    bh = x_bh_lq.shape[0]
    r = x_bh_lq.reshape(bh, n_q, 1, _TQ)
    return jnp.concatenate(
        [r, jnp.zeros((bh, n_q, _SUB - 1, _TQ), x_bh_lq.dtype)], axis=2
    )


def supports(q, k, v) -> bool:
    """True when these shapes are inside the kernel's contract (callers use
    this to fall back to the XLA path instead of tripping _check)."""
    b, lq, h, d = q.shape
    return bool(
        lq % _TQ == 0
        and lq <= _MAX_L
        and d <= _LANE
        and k.shape == q.shape
        and v.shape == q.shape
    )


def _check(q, k, v):
    if not supports(q, k, v):
        raise ValueError(
            f"flash_attention supports self-attention with L a multiple of "
            f"{_TQ}, L <= {_MAX_L}, head_dim <= {_LANE}; got q{q.shape} "
            f"k{k.shape} v{v.shape} (use ops.ring_attention's XLA path)"
        )


def _specs(lq, n_q):
    tile = pl.BlockSpec(
        (1, _TQ, _LANE), lambda bh, i: (bh, i, 0), memory_space=pltpu.VMEM
    )
    whole = pl.BlockSpec(
        (1, lq, _LANE), lambda bh, i: (bh, 0, 0), memory_space=pltpu.VMEM
    )
    vec_tile = pl.BlockSpec(
        (1, 1, _SUB, _TQ), lambda bh, i: (bh, i, 0, 0),
        memory_space=pltpu.VMEM,
    )
    vec_whole = pl.BlockSpec(
        (1, n_q, _SUB, _TQ), lambda bh, i: (bh, 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    return tile, whole, vec_tile, vec_whole


def _fwd_impl(q, k, v, causal):
    _check(q, k, v)
    b, lq, h, d = q.shape
    scale = d**-0.5
    qk, kk, vk = (_to_kernel_layout(x) for x in (q, k, v))
    bh, n_q = b * h, lq // _TQ
    tile, whole, vec_tile, _ = _specs(lq, n_q)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, scale=scale),
        grid=(bh, n_q),
        in_specs=[tile, whole, whole],
        out_specs=[tile, vec_tile],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, _LANE), q.dtype),
            jax.ShapeDtypeStruct((bh, n_q, _SUB, _TQ), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(qk, kk, vk)
    # Residuals are saved UNPADDED: the lane padding is pure zeros and the
    # backward re-pads in O(L*D) — at d=64 the padded copies would hold 2x
    # the bytes across every layer of a remat-off forward, material next to
    # the batch-32 HBM margin this kernel exists to widen.
    res = (
        qk[..., :d], kk[..., :d], vk[..., :d], o[..., :d], lse, b, h, d
    )
    return _from_kernel_layout(o, b, h, d), res


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal=False):
    """Exact (non-ring) attention, [B, L, H, D] -> [B, L, H, D]."""
    return _fwd_impl(q, k, v, causal)[0]


def _fa_fwd(q, k, v, causal):
    return _fwd_impl(q, k, v, causal)


def _fa_bwd(causal, res, g):
    qs, ks, vs, os_, lse, b, h, d = res
    pad = ((0, 0), (0, 0), (0, _LANE - d)) if d < _LANE else None
    qk, kk, vk, o = (
        (jnp.pad(x, pad) if pad else x) for x in (qs, ks, vs, os_)
    )
    bh, lq, _ = qk.shape
    scale = d**-0.5
    n_q = lq // _TQ
    gk = _to_kernel_layout(g)
    # delta = rowsum(dO * O) in f32 — O(L*D) precompute, standard flash bwd.
    delta = _vec4(
        jnp.sum(gk.astype(jnp.float32) * o.astype(jnp.float32), axis=-1),
        n_q,
    )
    tile, whole, vec_tile, vec_whole = _specs(lq, n_q)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale),
        grid=(bh, n_q),
        in_specs=[tile, whole, whole, tile, vec_tile],
        out_specs=tile,
        out_shape=jax.ShapeDtypeStruct((bh, lq, _LANE), qk.dtype),
        interpret=_use_interpret(),
    )(qk, kk, vk, gk, delta)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, causal=causal, scale=scale, n_q=n_q
        ),
        grid=(bh, n_q),
        in_specs=[whole, tile, tile, whole, vec_whole, vec_whole],
        out_specs=[tile, tile],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, _LANE), qk.dtype),
            jax.ShapeDtypeStruct((bh, lq, _LANE), vk.dtype),
        ],
        interpret=_use_interpret(),
    )(qk, kk, vk, gk, lse, delta)
    return tuple(_from_kernel_layout(x, b, h, d) for x in (dq, dk, dv))


flash_attention.defvjp(_fa_fwd, _fa_bwd)
