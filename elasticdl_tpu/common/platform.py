"""Platform selection helper.

Some images register an out-of-process TPU PJRT plugin from
``sitecustomize`` and force ``jax_platforms`` to it at interpreter start,
overriding the ``JAX_PLATFORMS`` environment variable.  Worker/master
subprocesses spawned with ``JAX_PLATFORMS=cpu`` (tests, CPU-only control
planes) would silently grab the TPU anyway — and hang or fight the parent
for the chip.  Calling :func:`apply_platform_env` right after process start
re-asserts the environment variable's choice through ``jax.config``, which
wins over the sitecustomize default.
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    jax.config.update("jax_platforms", platforms)


def enable_compile_cache(path: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache.

    Elastic resizes and repeat bench runs re-jit the train step for a new
    mesh; with the cache on, a previously seen (computation, topology) pair
    loads its executable from disk instead of paying the full XLA compile
    (~20-40 s on TPU).
    """
    import jax

    cache_dir = (
        path
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.path.expanduser("~/.cache/elasticdl_tpu/jax_cache")
    )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache even fast compiles: elastic resizes re-trace many small steps.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
