"""Platform selection helper.

Some images register an out-of-process TPU PJRT plugin from
``sitecustomize`` and force ``jax_platforms`` to it at interpreter start,
overriding the ``JAX_PLATFORMS`` environment variable.  Worker/master
subprocesses spawned with ``JAX_PLATFORMS=cpu`` (tests, CPU-only control
planes) would silently grab the TPU anyway — and hang or fight the parent
for the chip.  Calling :func:`apply_platform_env` right after process start
re-asserts the environment variable's choice through ``jax.config``, which
wins over the sitecustomize default.
"""

from __future__ import annotations

import contextlib
import os
import subprocess
import sys
import time


def free_port() -> int:
    """An OS-assigned free TCP port (bind-port-0 probe) — for coordinator
    ports in single-machine multi-process harnesses, where a fixed default
    would collide across concurrent gangs.  Inherently racy (the port is
    released before the caller binds it); fine for tests/benches, real
    deployments configure the coordinator port explicitly.  Lives here
    (not parallel.distributed) so jax-free master/bench processes can
    allocate ports without importing jax."""
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def apply_platform_env() -> None:
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    jax.config.update("jax_platforms", platforms)


def enable_compile_cache(path: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache.

    Elastic resizes and repeat bench runs re-jit the train step for a new
    mesh; with the cache on, a previously seen (computation, topology) pair
    loads its executable from disk instead of paying the full XLA compile
    (~20-40 s on TPU; elastic relaunches on the CPU harness also lean on it
    — disabling it there regressed the warm re-rendezvous 2.5 s -> 8 s).

    Known hazard, handled at the one affected call site instead of here:
    this jax build's XLA:CPU loader can hard-abort reloading an entry via
    the ``lower().compile()`` cost-analysis path (machine-feature
    round-trip mismatch).  Every OTHER reload pattern is empirically fine —
    cross-process relaunches and same-process re-jits after elastic resizes
    have run cache-on through five rounds of the suite (incl. the 4->8->4
    resize tests) without an abort; a blanket CPU skip was tried and
    regressed warm re-rendezvous 2.5 s -> 8 s.  tools/bench_all.py bypasses
    the cache around exactly the crashing call (``suspend_compile_cache``).
    """
    import jax

    cache_dir = (
        path
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.path.expanduser("~/.cache/elasticdl_tpu/jax_cache")
    )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache even fast compiles: elastic resizes re-trace many small steps.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


@contextlib.contextmanager
def suspend_compile_cache():
    """Temporarily disable the persistent compilation cache.

    For the one known-poisonous pattern: an XLA:CPU ``lower().compile()``
    re-reading an AOT entry the same process just wrote hard-aborts in the
    loader (machine-feature round-trip bug in this jax build).  Wrap such
    compiles; everything else keeps the cache (see enable_compile_cache)."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


# The probe must honor JAX_PLATFORMS the way apply_platform_env() does —
# the image's sitecustomize forces jax_platforms to the tunneled TPU plugin,
# so a bare ``jax.devices()`` subprocess spawned from a CPU-only test/tool
# would try the real (possibly hung) chip regardless of the env var.
# Inlined (not imported) so the subprocess needs nothing on sys.path.
_PROBE_CODE = (
    "import os, sys; import jax; "
    "p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); "
    "d = jax.devices(); "
    "sys.stdout.write('%d %s' % (len(d), d[0].platform))"
)


def probe_devices(
    attempts: int = 4,
    timeout_s: float = 100.0,
    backoff_s: float = 10.0,
    log=None,
) -> str:
    """Probe the JAX backend in killable subprocesses before touching it.

    The twice-recorded chip failure mode (BENCH_r02/r04) is a *hang* inside
    ``jax.devices()`` — not an exception — so retry-on-exception loops never
    fire and the first in-process backend touch burns the whole watchdog
    budget.  The only killable unit is a separate process: spawn
    ``python -c 'jax.devices()'`` (inheriting the parent environment
    unchanged, so the out-of-process TPU plugin registration survives) with
    a hard timeout, bounded attempts, backoff between them.  A transient
    "chip flaky at minute 0, fine at minute 2" then costs one killed probe
    instead of a null artifact.

    Returns the successful probe's ``"<n> <platform>"`` line.  Raises
    ``RuntimeError`` once every attempt has hung or failed — callers turn
    that into an immediate partial artifact instead of a watchdog
    force-exit.
    """
    say = log or (lambda m: print(m, file=sys.stderr, flush=True))
    if os.environ.get("EDL_SKIP_PROBE") == "1":
        # The battery (tools/chip_battery.sh) gates every stage with its own
        # probe; the tools' internal probes would then pay a redundant full
        # backend init per stage — it exports this to skip them.
        say("device probe skipped (EDL_SKIP_PROBE=1)")
        return "skipped"
    last = ""
    for attempt in range(1, attempts + 1):
        t0 = time.time()
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            last = f"probe hung {timeout_s:.0f}s (killed)"
            say(f"device probe {attempt}/{attempts}: {last}")
            continue  # the hang already consumed the backoff and then some
        if out.returncode == 0 and out.stdout.strip():
            summary = out.stdout.strip()
            say(
                f"device probe {attempt}/{attempts}: ok in "
                f"{time.time() - t0:.1f}s ({summary})"
            )
            return summary
        last = (out.stderr.strip() or f"rc={out.returncode}")[-300:]
        say(f"device probe {attempt}/{attempts}: failed: {last}")
        if attempt < attempts:
            time.sleep(backoff_s)
    raise RuntimeError(
        f"device probe failed {attempts}x (timeout {timeout_s:.0f}s each); "
        f"last: {last}"
    )
