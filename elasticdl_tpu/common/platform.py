"""Platform selection helper.

Some images register an out-of-process TPU PJRT plugin from
``sitecustomize`` and force ``jax_platforms`` to it at interpreter start,
overriding the ``JAX_PLATFORMS`` environment variable.  Worker/master
subprocesses spawned with ``JAX_PLATFORMS=cpu`` (tests, CPU-only control
planes) would silently grab the TPU anyway — and hang or fight the parent
for the chip.  Calling :func:`apply_platform_env` right after process start
re-asserts the environment variable's choice through ``jax.config``, which
wins over the sitecustomize default.
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    jax.config.update("jax_platforms", platforms)
