"""graftgauge — the live metrics plane's recording half.

Everything this repo measured before r14 was post-hoc: the JSONL
``MetricsWriter`` stream, ``DumpTrace`` merges, and ``artifacts/*.json``
stamped after a run ends.  A wedged gang or a serving p99 blowout was
invisible until the job was over.  This module is the process-local
registry — counters, gauges, histograms — cheap enough to update from
``# hot-path`` functions, and ``common/metrics_http.py`` is the reading
half (a ``/metrics`` + ``/healthz`` scrape server on its own daemon
thread, so a wedged task loop still answers).

Design constraints, in the grafttrace/graftchaos order:

- **Hot-path safe.**  An update is one attribute check when the registry
  is disabled, and one leaf-lock add when enabled — the exact cost
  profile of ``PhaseTimers.add``, which has lived inside the task loop
  since r6.  The lock (one shared locksan-leaf name per metric) exists
  for the MULTI-FIELD ops: a histogram observe touches a bucket counter,
  the sum and the count together, and a torn pair would render a
  histogram whose ``_sum`` disagrees with its buckets.  Single-field
  counter adds ride the same lock so the concurrency tests can assert
  EXACT totals — an approximate examples-trained counter would make the
  goodput computer lie.
- **Stdlib only.**  The master control plane, the PS shards and the
  lint/bench tools are jax-free by contract (graftlint import-hygiene);
  the registry rides in all of them.
- **Scrape work stays off the hot path.**  ``snapshot()`` /
  ``render_prometheus()`` walk every family and run the registered
  collectors — that is scrape-side work, and the ``gauge-discipline``
  lint rule forbids it inside ``# hot-path`` functions, exactly as
  ``trace-discipline`` forbids ring exports there.

Histograms use the ONE shared log-spaced millisecond grid
(``DEFAULT_BUCKET_EDGES_MS`` — canonical here since r14;
``tools/artifact.latency_stats`` imports it), with identical bucket
semantics: ``counts[i]`` holds samples in ``(edges[i-1], edges[i]]``,
``counts[0]`` the under-first-edge bin, ``counts[-1]`` the overflow —
pinned against ``latency_stats`` by test, so a live scrape and a stamped
artifact bucket the same sample identically.

Registries are INSTANCES, not a process singleton: an in-process test
fleet runs several workers in one process, and each worker's families
must stay its own (the master's fleet aggregation is exactly the sum of
per-worker views).  ``default()`` exists for cross-cutting client-side
consumers constructed deep inside the trainer — the PS client's retry
counter — and the worker/PS/serving mains hand the same registry to
their server objects so one scrape endpoint serves everything the
process recorded.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from elasticdl_tpu.common import locksan

#: Shared log-spaced histogram bucket edges (MILLISECONDS).  One FIXED
#: grid across every consumer — live registry histograms here, stamped
#: artifact histograms via ``tools/artifact.latency_stats`` (which
#: imports this constant) — so a tail shape read off a live scrape is
#: comparable bucket-for-bucket with a committed artifact.
DEFAULT_BUCKET_EDGES_MS = (
    0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
    100.0, 200.0, 500.0, 1000.0, 2000.0, 5000.0, 10000.0,
)

# ---------------------------------------------------------------------------
# The one naming table.
#
# The master mirrors worker gauge envelopes into the JSONL metrics stream
# (kind="gauge" records) under EXACTLY these family names, and the live
# scrape serves the same names — one table, asserted by test, so offline
# JSONL analysis and live scrapes cannot drift apart.  Scalar families
# only (histograms stay scrape-side; a JSONL line per bucket would flood
# the stream without adding an offline signal the seconds/counts lack).

#: Worker hot-path families (the JSONL mirror set).
EXAMPLES_TRAINED = "edl_examples_trained_total"
STEPS_DISPATCHED = "edl_steps_dispatched_total"
TASKS_DONE = "edl_tasks_done_total"
LEASE_DEPTH = "edl_lease_depth"
PREP_QUEUE_DEPTH = "edl_prep_queue_depth"

#: The families the master's JSONL "gauge" records mirror, in stream
#: order.  ``MasterServicer._record_gauges`` writes these keys and no
#: others; ``tests/test_gauge.py`` asserts the table matches both the
#: JSONL records and the registry families a worker actually publishes.
JSONL_GAUGE_FAMILIES = (
    EXAMPLES_TRAINED,
    STEPS_DISPATCHED,
    TASKS_DONE,
    LEASE_DEPTH,
    PREP_QUEUE_DEPTH,
)


def _labels_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    body = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + body + "}"


class _Metric:
    """One (family, labelset) series.  ``enabled`` is synced from the
    owning registry so a disabled registry costs one attribute check per
    update call — the grafttrace stance."""

    __slots__ = ("_lock", "enabled", "labels_key")

    def __init__(self, enabled: bool, labels_key):
        # One shared leaf name for every metric instance (peer instances
        # of one locksan name are exempt from pairwise order — the
        # class-level contract): nothing is ever acquired under it.
        self._lock = locksan.lock("_Metric._lock", leaf=True)  # lock-order: leaf
        self.enabled = enabled
        self.labels_key = labels_key


class Counter(_Metric):
    """Monotonic float counter (``*_total`` families)."""

    __slots__ = ("_v",)

    def __init__(self, enabled: bool = True, labels_key=()):
        super().__init__(enabled, labels_key)
        self._v = 0.0  # guarded-by: _lock

    def inc(self, v: float = 1.0) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._v += v

    def set_total(self, v: float) -> None:
        """Scrape-side mirror of an EXTERNAL monotonic aggregate (the
        locksan acquire counts): a collector overwrites the cumulative
        total it reads elsewhere.  Hot-path update sites keep using
        ``inc`` — mixing the two on one series would lose counts."""
        if not self.enabled:
            return
        with self._lock:
            self._v = float(v)

    def value(self) -> float:
        with self._lock:
            return self._v


class Gauge(_Metric):
    """Point-in-time value (depths, versions, ratios)."""

    __slots__ = ("_v",)

    def __init__(self, enabled: bool = True, labels_key=()):
        super().__init__(enabled, labels_key)
        self._v = 0.0  # guarded-by: _lock

    def set(self, v: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._v = float(v)

    def add(self, v: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._v += v

    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram(_Metric):
    """Fixed-edge histogram on the shared millisecond grid.

    Bucket semantics match ``tools/artifact.latency_stats(buckets=True)``
    exactly (``bisect_left`` = numpy ``searchsorted(side="left")``):
    ``counts[i]`` holds samples in ``(edges[i-1], edges[i]]`` with
    ``counts[0]`` the under-first-edge bin and ``counts[-1]`` the
    overflow — one more bin than edges.
    """

    __slots__ = ("edges", "_counts", "_sum", "_count")

    def __init__(self, enabled: bool = True, labels_key=(),
                 edges: Optional[Sequence[float]] = None):
        super().__init__(enabled, labels_key)
        self.edges = tuple(
            float(e) for e in (edges or DEFAULT_BUCKET_EDGES_MS)
        )
        self._counts = [0] * (len(self.edges) + 1)  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock

    def observe(self, v: float) -> None:
        if not self.enabled:
            return
        idx = bisect.bisect_left(self.edges, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "edges": list(self.edges),
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }

    def load_snapshot(self, snap: dict) -> None:
        """Scrape-side mirror of an EXTERNAL histogram aggregate (the
        locksan wait-time buckets): a collector overwrites this series
        with the cumulative state it reads elsewhere.  The edge grid must
        match bucket-for-bucket — a silent re-bucketing would render a
        histogram whose counts mean nothing."""
        if not self.enabled:
            return
        edges = tuple(float(e) for e in snap.get("edges") or ())
        counts = list(snap.get("counts") or ())
        if edges != self.edges or len(counts) != len(self.edges) + 1:
            raise ValueError(
                "load_snapshot edge grid does not match this histogram's"
            )
        with self._lock:
            self._counts = counts
            self._sum = float(snap.get("sum", 0.0))
            self._count = int(snap.get("count", 0))

    def quantile(self, q: float) -> Optional[float]:
        """Approximate quantile by linear interpolation inside the owning
        bucket (the live p99 estimator behind the serving SLO gauge).
        Grid-resolution approximate BY DESIGN — the same fidelity the
        stamped artifact histograms have; overflow-bucket hits return the
        last edge (a lower bound, which is the honest direction for an
        SLO ratio).  None when empty."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total <= 0:
            return None
        target = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if c <= 0:
                continue
            if seen + c >= target:
                lo = self.edges[i - 1] if i >= 1 else 0.0
                hi = self.edges[i] if i < len(self.edges) else self.edges[-1]
                frac = (target - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.edges[-1]


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    """Named metric families -> labeled series, plus scrape-time
    collectors.

    ``counter``/``gauge``/``histogram`` are get-or-create (idempotent —
    instrumentation sites may be constructed more than once); a name
    re-registered under a different TYPE raises, because one family
    serving two types would render self-contradictory scrape output.

    ``add_collector(fn)`` registers a callable run at ``snapshot()`` /
    ``render_prometheus()`` time — the pull-model half: state that is
    cheap to READ but lives elsewhere (dispatcher counts, batcher stats,
    gang arrival lags) is collected fresh per scrape instead of being
    pushed on the hot path.  Collectors run OUTSIDE every registry lock
    (they call back into ``gauge(...).set``).
    """

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._lock = locksan.lock("Registry._lock", leaf=True)  # lock-order: leaf
        # family name -> {"type", "help", "series": {labels_key: metric}}
        self._families: Dict[str, dict] = {}  # guarded-by: _lock
        self._collectors: List[Callable[[], None]] = []  # guarded-by: _lock

    # -- registration (hot-path legal: dict lookup + rare creation) --

    def _metric(self, kind: str, name: str, help_: str,
                labels: Optional[Dict[str, str]], **kw):
        key = _labels_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = {
                    "type": kind, "help": help_, "series": {},
                }
            elif fam["type"] != kind:
                raise ValueError(
                    f"metric family {name!r} is a {fam['type']}, not a "
                    f"{kind} — one family cannot serve two types"
                )
            metric = fam["series"].get(key)
            if metric is None:
                metric = fam["series"][key] = _TYPES[kind](
                    enabled=self.enabled, labels_key=key, **kw
                )
            return metric

    def counter(self, name: str, help_: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._metric("counter", name, help_, labels)

    def gauge(self, name: str, help_: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._metric("gauge", name, help_, labels)

    def histogram(self, name: str, help_: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  edges: Optional[Sequence[float]] = None) -> Histogram:
        return self._metric("histogram", name, help_, labels, edges=edges)

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def remove_collector(self, fn: Callable[[], None]) -> None:
        """Unregister a collector (no-op if absent).  A stopped server
        whose collector stays registered would keep re-publishing its
        frozen stats over a successor's live families — and the registry
        reference would pin the dead server in memory for the process's
        life."""
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def clear_family(self, name: str) -> None:
        """Drop every series of ``name`` (type/help stay registered).
        Collectors that re-publish a per-ENTITY labeled family call this
        before repopulating: entities come and go (a killed worker, a
        dissolved gang), and a series that stops being set would
        otherwise serve its last value forever — a dead worker's frozen
        rate beside a live fleet total is exactly the lie a metrics
        plane must not tell."""
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                fam["series"] = {}

    def configure(self, enabled: bool) -> None:
        """Flip the registry (and every existing metric) on or off —
        disabled update sites cost one attribute check."""
        with self._lock:
            self.enabled = bool(enabled)
            metrics = [
                m for fam in self._families.values()
                for m in fam["series"].values()
            ]
        for m in metrics:
            m.enabled = self.enabled

    # -- scrape side (forbidden in # hot-path functions: gauge-discipline) --

    def _collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                # A broken collector must not take the whole scrape down:
                # the other families are exactly what the operator needs
                # to diagnose it.
                import logging

                logging.getLogger("gauge").exception("collector failed")

    def snapshot(self, collect: bool = True) -> Dict[str, dict]:
        """Plain-JSON view of every family: the heartbeat envelope / the
        /healthz payload / the aggregation input.  Scalar series render
        as floats, histograms as their edges/counts/sum/count dict."""
        if collect:
            self._collect()
        with self._lock:
            fams = {
                name: (fam["type"], fam["help"], list(fam["series"].items()))
                for name, fam in self._families.items()
            }
        out: Dict[str, dict] = {}
        for name, (kind, help_, series) in sorted(fams.items()):
            samples = []
            for key, metric in series:
                value = (
                    metric.snapshot() if kind == "histogram"
                    else metric.value()
                )
                samples.append({"labels": dict(key), "value": value})
            out[name] = {"type": kind, "help": help_, "samples": samples}
        return out

    def render_prometheus(self, collect: bool = True) -> str:
        """Prometheus text exposition (version 0.0.4): HELP/TYPE headers,
        one line per series; histograms expand to cumulative
        ``_bucket{le=...}`` lines plus ``_sum``/``_count``."""
        return render_families(self.snapshot(collect=collect))

    def scalar_values(self, families: Sequence[str]) -> Dict[str, float]:
        """Unlabeled scalar series of ``families`` that exist — the JSONL
        mirror's input (the one naming table, ``JSONL_GAUGE_FAMILIES``)."""
        out: Dict[str, float] = {}
        with self._lock:
            for name in families:
                fam = self._families.get(name)
                if fam is None or fam["type"] == "histogram":
                    continue
                metric = fam["series"].get(())
                if metric is not None:
                    out[name] = metric
        return {k: m.value() for k, m in out.items()}


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_families(families: Dict[str, dict]) -> str:
    """Prometheus text from a ``Registry.snapshot()``-shaped family dict.

    A module function (not a Registry method) on purpose: the master's
    fleet view renders MERGED per-worker snapshots (``merge_snapshots``)
    that never lived in a local registry, and both paths must produce
    byte-identical exposition for the same families.  Malformed samples
    (an envelope is remote input) are skipped, never a scrape 500."""
    lines: List[str] = []
    for name, fam in families.items():
        if not isinstance(fam, dict):
            continue
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        kind = fam.get("type", "gauge")
        lines.append(f"# TYPE {name} {kind}")
        for s in fam.get("samples") or []:
            if not isinstance(s, dict):
                continue
            key = _labels_key(s.get("labels"))
            value = s.get("value")
            if kind != "histogram":
                if isinstance(value, (int, float)) and not isinstance(
                    value, bool
                ):
                    lines.append(
                        f"{name}{_render_labels(key)} {_fmt(value)}"
                    )
                continue
            if not isinstance(value, dict):
                continue
            edges = value.get("edges") or []
            counts = value.get("counts") or []
            if len(counts) != len(edges) + 1:
                continue
            cum = 0
            for edge, c in zip(edges, counts):
                cum += c
                le = key + (("le", _fmt(edge)),)
                lines.append(f"{name}_bucket{_render_labels(le)} {cum}")
            cum += counts[-1]
            inf = key + (("le", "+Inf"),)
            lines.append(f"{name}_bucket{_render_labels(inf)} {cum}")
            lines.append(
                f"{name}_sum{_render_labels(key)} {_fmt(value.get('sum', 0.0))}"
            )
            lines.append(
                f"{name}_count{_render_labels(key)} {value.get('count', 0)}"
            )
    return "\n".join(lines) + ("\n" if lines else "")


# -- the process-default registry ------------------------------------------
#
# Cross-cutting client-side instrumentation (the PS client's retry
# counter rides inside RemoteEmbeddingStore, constructed deep in the
# trainer) records here; worker/PS/serving mains hand this registry to
# their Worker/PSServer/ServingServer so the one scrape endpoint serves
# everything the process recorded.  In-process test fleets pass explicit
# instances instead and never touch this.

_DEFAULT = Registry()


def default() -> Registry:
    return _DEFAULT


# -- locksan contention bridge (r16) ---------------------------------------


def install_lock_collector(registry: Registry) -> Callable[[], None]:
    """Expose locksan's per-lock-name contention aggregates as
    ``edl_lock_acquire_total`` / ``edl_lock_wait_ms{lock=...}`` on
    ``registry`` — a scrape-side collector (the pull model: lock waits
    are cheap to READ in aggregate but must cost the acquire path
    nothing when nobody scrapes).  Recording in locksan starts at
    install time; with the sanitizer off (``GRAFT_LOCKSAN`` unset) locks
    are plain and the families simply stay empty.  Returns the collector
    (for ``remove_collector`` in tests)."""
    locksan.enable_contention_stats(DEFAULT_BUCKET_EDGES_MS)

    def _collect() -> None:
        for name, rec in locksan.contention_snapshot().items():
            labels = {"lock": name}
            registry.counter(
                "edl_lock_acquire_total",
                "sanitized-lock acquisitions by lock name",
                labels=labels,
            ).set_total(rec["acquires"])
            registry.histogram(
                "edl_lock_wait_ms",
                "wall waited inside sanitized-lock acquire, by lock name",
                labels=labels,
            ).load_snapshot(rec["wait_ms"])

    registry.add_collector(_collect)
    return _collect


# -- jitsan compile bridge (v6) --------------------------------------------


def install_jit_collector(registry: Registry) -> Callable[[], None]:
    """Expose jitsan's per-name lowering counts as
    ``edl_jit_compiles_total{fn=...}`` on ``registry`` — scrape-side,
    like the locksan bridge: the counting itself rides the jit tracer
    (common/jitsan.py), this only mirrors the aggregates, so a scrape
    costs the hot path nothing.  With ``GRAFT_JITSAN`` unset the jitted
    functions are plain and the family simply stays empty.  A count that
    climbs after warmup IS the signal: the step is retracing in
    production (watch_job.py renders the family with per-scrape deltas).
    Returns the collector (for ``remove_collector`` in tests)."""
    from elasticdl_tpu.common import jitsan

    def _collect() -> None:
        for name, rec in jitsan.stats().items():
            registry.counter(
                "edl_jit_compiles_total",
                "XLA lowerings per declared jit site (jitsan; a climb "
                "after warmup means the step is retracing)",
                labels={"fn": name},
            ).set_total(rec["compiles"])

    registry.add_collector(_collect)
    return _collect


# -- wiresan unknown-field bridge (v8) -------------------------------------


def install_wire_collector(registry: Registry) -> Callable[[], None]:
    """Expose wiresan's per-method unknown-field counts as
    ``edl_wire_unknown_fields_total{method=...}`` on ``registry`` —
    scrape-side, like the locksan/jitsan bridges: the counting rides the
    rpc boundary hooks (common/wiresan.py), this only mirrors the
    aggregates.  With ``GRAFT_WIRESAN`` unset the hooks are skipped and
    the family simply stays empty.  A non-zero count is the version-skew
    dashboard signal: a NEWER peer is sending fields this process's
    schema predates — legal (additive-compat), but the operator should
    know the fleet is mixed-version before debugging anything else.
    Returns the collector (for ``remove_collector`` in tests)."""
    from elasticdl_tpu.common import wiresan

    def _collect() -> None:
        for method, n in wiresan.stats()["unknown_fields"].items():
            registry.counter(
                "edl_wire_unknown_fields_total",
                "unknown wire fields seen per method (wiresan; non-zero "
                "means a newer peer is talking to this process)",
                labels={"method": method},
            ).set_total(n)

    registry.add_collector(_collect)
    return _collect


# -- fleet-view helpers (jax-free; the master's aggregation math) ----------


def merge_snapshots(snapshots: Dict[str, Dict[str, dict]]) -> Dict[str, dict]:
    """Fold per-process ``Registry.snapshot()`` payloads into ONE family
    view with a ``worker`` label per series — the master's fleet page.
    Series are RELABELED, never summed: per-worker visibility is the
    point (a straggler hides inside a fleet-summed histogram), and the
    fleet-level numbers that matter are the goodput computer's own
    gauges, derived from the scalar counters (master/fleet_metrics.py).
    Cross-worker sums stay the scraper's job — Prometheus sums a
    ``worker``-labeled family in one expression."""
    out: Dict[str, dict] = {}
    for worker, families in sorted(snapshots.items()):
        if not isinstance(families, dict):
            continue
        for name, fam in families.items():
            if not isinstance(fam, dict) or "samples" not in fam:
                continue
            slot = out.setdefault(
                name,
                {"type": fam.get("type", "gauge"),
                 "help": fam.get("help", ""), "samples": []},
            )
            for s in fam.get("samples") or []:
                labels = dict(s.get("labels") or {})
                labels["worker"] = worker
                slot["samples"].append(
                    {"labels": labels, "value": s.get("value")}
                )
    return out


class RateWindow:
    """Per-key (counter total, wall time) pairs -> live rate.

    The goodput computer's primitive: feed it each worker's cumulative
    ``edl_examples_trained_total`` as envelopes arrive; ``rate()`` is the
    summed per-key delta over the observation window, robust to a worker
    restarting (a total that went BACKWARDS re-anchors that key instead
    of stamping a negative rate)."""

    def __init__(self, window_s: float = 30.0, clock=time.monotonic):
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = locksan.lock("RateWindow._lock", leaf=True)  # lock-order: leaf
        self._points: Dict[str, List[Tuple[float, float]]] = {}  # guarded-by: _lock

    def update(self, key: str, total: float) -> None:
        now = self._clock()
        with self._lock:
            pts = self._points.setdefault(key, [])
            if pts and total < pts[-1][1]:
                pts.clear()  # restarted counter: re-anchor, don't go negative
            pts.append((now, float(total)))
            cutoff = now - self.window_s
            while len(pts) > 2 and pts[1][0] <= cutoff:
                pts.pop(0)

    def rates(self) -> Dict[str, float]:
        """Per-key rate over each key's window (absent until a key has
        two points).  Keys silent past the window drop out — a dead
        worker's stale pair must not keep inflating the live rate."""
        now = self._clock()
        out: Dict[str, float] = {}
        with self._lock:
            for key, pts in self._points.items():
                if len(pts) < 2 or now - pts[-1][0] > self.window_s:
                    continue
                dt = pts[-1][0] - pts[0][0]
                if dt > 0:
                    out[key] = (pts[-1][1] - pts[0][1]) / dt
        return out

    def rate(self) -> float:
        """Summed per-key rate (the fleet total)."""
        return sum(self.rates().values())
