"""JSON-over-gRPC plumbing.

The reference defines its master/PS contract in protobuf (SURVEY.md §2 #12
[U]).  This image ships ``grpcio`` but not ``grpc_tools`` (no protoc python
plugin), so the rebuild keeps gRPC as the wire protocol — HTTP/2, the same
operational surface — with JSON message bodies registered through generic
method handlers instead of generated stubs.  The method table in
``master/servicer.py`` is the contract.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict

import grpc

SERVICE_NAME = "elasticdl.Master"


def _serialize(msg: Dict[str, Any]) -> bytes:
    return json.dumps(msg).encode()


def _deserialize(payload: bytes) -> Dict[str, Any]:
    return json.loads(payload.decode()) if payload else {}


def make_generic_handler(
    service_name: str, methods: Dict[str, Callable[[dict], dict]]
) -> grpc.GenericRpcHandler:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            lambda req, ctx, fn=fn: fn(req),
            request_deserializer=_deserialize,
            response_serializer=_serialize,
        )
        for name, fn in methods.items()
    }
    return grpc.method_handlers_generic_handler(service_name, handlers)


class JsonRpcClient:
    """Typed-enough client for a JSON-over-gRPC service."""

    def __init__(self, address: str, service_name: str = SERVICE_NAME):
        self._channel = grpc.insecure_channel(address)
        self._service = service_name
        self._stubs: Dict[str, Callable] = {}

    def wait_ready(self, timeout_s: float = 10.0) -> None:
        grpc.channel_ready_future(self._channel).result(timeout=timeout_s)

    def call(self, method: str, request: Dict[str, Any], timeout_s: float = 30.0):
        if method not in self._stubs:
            self._stubs[method] = self._channel.unary_unary(
                f"/{self._service}/{method}",
                request_serializer=_serialize,
                response_deserializer=_deserialize,
            )
        return self._stubs[method](request, timeout=timeout_s)

    def close(self) -> None:
        self._channel.close()
