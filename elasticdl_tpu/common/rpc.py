"""JSON-over-gRPC plumbing.

The reference defines its master/PS contract in protobuf (SURVEY.md §2 #12
[U]).  This image ships ``grpcio`` but not ``grpc_tools`` (no protoc python
plugin), so the rebuild keeps gRPC as the wire protocol — HTTP/2, the same
operational surface — with JSON message bodies registered through generic
method handlers instead of generated stubs.  The method table in
``master/servicer.py`` is the contract.
"""

from __future__ import annotations

import dataclasses
import json
import random
import time
from typing import Any, Callable, Dict, Optional, Tuple

import grpc

from elasticdl_tpu import chaos
from elasticdl_tpu.common import gauge as gaugelib
from elasticdl_tpu.common import trace
from elasticdl_tpu.common import wiresan

SERVICE_NAME = "elasticdl.Master"

#: gRPC message cap for the master service, BOTH sides (same stance as the
#: PS tier's GRPC_MAX_MESSAGE_BYTES): the control-plane default of 4 MB
#: was fine for task/report traffic, but a DumpTrace response carries up
#: to a full 65536-event ring per process (~10-16 MB of JSON) — the
#: live-job introspection tool must not break exactly when the trace is
#: large.  64 MB covers several full rings with headroom.
GRPC_MAX_MESSAGE_BYTES = 64 << 20

#: Channel/server options applying the cap (send AND receive: the server
#: sends the big dump, the tool receives it).
GRPC_MESSAGE_OPTIONS = [
    ("grpc.max_send_message_length", GRPC_MAX_MESSAGE_BYTES),
    ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE_BYTES),
]

#: CLIENT channel options: the message caps plus a bounded reconnection
#: backoff.  gRPC's default re-dial schedule backs off to 120 s — after
#: ~15 s of refused connections the channel can sit in TRANSIENT_FAILURE
#: for a minute-plus after the server is BACK, failing every call fast
#: without attempting a connection.  That silently defeats the r18
#: master-outage ride-through (the proxy's own jittered backoff governs
#: the retry cadence; the CHANNEL must merely keep probing), so re-dial
#: attempts are capped at 5 s apart.
GRPC_CLIENT_CHANNEL_OPTIONS = GRPC_MESSAGE_OPTIONS + [
    ("grpc.initial_reconnect_backoff_ms", 500),
    ("grpc.min_reconnect_backoff_ms", 500),
    ("grpc.max_reconnect_backoff_ms", 5000),
]

#: Wire-contract version, negotiated at RegisterWorker (the one RPC every
#: worker must issue first).  Bump when a message's shape changes
#: incompatibly; the master rejects a mismatched worker AT REGISTRATION with
#: a structured error naming both versions — not N tasks later with a
#: schema violation mid-job.  A request without the field is accepted
#: (proto3 unknown-field stance: absent = pre-versioning peer).
PROTOCOL_VERSION = 1


@dataclasses.dataclass(frozen=True)
class MessageSchema:
    """Required/optional field names -> accepted python types.

    The proto-less stand-in for the reference's protobuf message definitions:
    a malformed request fails AT THE BOUNDARY with a structured
    INVALID_ARGUMENT naming the field, instead of as a KeyError deep inside a
    handler (VERDICT r2 Missing #5).

    ``since`` (r22) maps a field name to the wire REVISION (the repo's
    r-number) that added it; a field absent from the map is part of the
    v1 baseline.  Only OPTIONAL fields carry a ``since`` — the additive-
    compat stance makes every post-baseline field optional by definition
    (a new REQUIRED field is a PROTOCOL_VERSION bump, which graftlint's
    wire-evolution rule enforces against the committed schema lock).
    The map powers wiresan's version mask: ``GRAFT_WIRESAN_MASK=<rev>``
    emulates an old peer by stripping every field newer than ``rev``
    from outgoing requests and incoming responses."""

    required: Dict[str, Tuple[type, ...]] = dataclasses.field(default_factory=dict)
    optional: Dict[str, Tuple[type, ...]] = dataclasses.field(default_factory=dict)
    since: Dict[str, int] = dataclasses.field(default_factory=dict)


_STR = (str,)
_INT = (int,)
_NUM = (int, float)
_BOOL = (bool,)
_DICT = (dict,)
_LIST = (list,)

#: The master wire contract (kept in lockstep with MasterServicer's method
#: table — asserted by tests).  Unknown fields pass through (forward
#: compatibility, like proto3 unknown fields).
MASTER_SCHEMAS: Dict[str, MessageSchema] = {
    # lease (r9): how many tasks the caller can accept in one response —
    # the master may return up to that many in the response's "tasks"
    # (GetTask) / "entries" (GetGroupTask) list, amortizing one RPC RTT
    # over the batch.  Optional and additive: an absent field means 1,
    # and old callers ignore the extra response keys, so no PROTOCOL_VERSION
    # bump (proto3 unknown-field stance on both sides).
    "GetTask": MessageSchema(
        required={"worker_id": _STR}, optional={"lease": _INT},
        since={"lease": 9},
    ),
    "GetGroupTask": MessageSchema(
        required={"worker_id": _STR, "seq": _INT, "version": _INT},
        optional={"lease": _INT},
        since={"lease": 9},
    ),
    "ReportTaskResult": MessageSchema(
        required={"worker_id": _STR, "task_id": _INT, "success": _BOOL},
        optional={
            "task_type": _STR,
            # requeue (r9): success=False with requeue=True means the task
            # was returned UNSTARTED (lease/prep abandon on preemption or
            # membership change) — the dispatcher requeues it without
            # charging the retry budget, so routine elastic churn cannot
            # poison-abandon a healthy task.  Additive; absent = a real
            # failure.
            "requeue": _BOOL,
            "metrics": _DICT,
            "weight": _NUM,
            "model_version": _INT,
            # Cumulative task-loop wall decomposition (common/metrics.py
            # PhaseTimers.snapshot): {phase_name: seconds}.  Rides every
            # report so the master's JobStatus and the train-job artifact
            # can attribute throughput to named phases without a new RPC.
            "phase_times": _DICT,
            # seq (r18): per-worker monotonically increasing report
            # sequence number.  The master journals the highest seq seen
            # per worker (master/journal.py) and DEDUPES a replayed seq
            # — the exactly-once guard that lets the proxy's outage
            # ride-through retry a report whose first attempt the dying
            # master may or may not have applied.  Additive and
            # optional: an absent field keeps the pre-r18 at-least-once
            # semantics, so no PROTOCOL_VERSION bump (the r9 stance).
            "seq": _INT,
        },
        since={"requeue": 9, "seq": 18},
    ),
    "ReportVersion": MessageSchema(
        required={"model_version": _INT}, optional={"worker_id": _STR}
    ),
    # incarnation/held_tasks (r18): the lease-reconciliation handshake a
    # worker runs after its proxy rode out a master outage (and, with an
    # empty list, at every fresh boot).  ``held_tasks`` is the exact set
    # of training-task ids the worker still holds (buffered leases,
    # in-flight preps, the pipelined pending slot); the master requeues
    # its journal-replayed ``doing`` entries for this worker that the
    # worker does NOT hold (handouts lost in flight during the crash,
    # requeued now instead of after task_timeout_s) and answers with
    # ``stale_tasks`` — held ids the master no longer attributes to this
    # worker, which the worker must drop unstarted (training them would
    # double-train records the master already re-leased).  Additive:
    # absent fields skip the reconcile entirely.
    "RegisterWorker": MessageSchema(
        required={"worker_id": _STR},
        optional={
            "address": _STR, "proto": _INT,
            "incarnation": _STR, "held_tasks": _LIST,
        },
        since={"proto": 9, "incarnation": 18, "held_tasks": 18},
    ),
    "DeregisterWorker": MessageSchema(required={"worker_id": _STR}),
    "Heartbeat": MessageSchema(
        required={"worker_id": _STR},
        # phase_times: group-mode non-rank-0 members never send task
        # reports (rank-0-gated), so their phase snapshot rides the
        # heartbeat — without it the master's per-worker decomposition
        # only ever held rank 0 and a straggler rank was invisible.
        # gang_seq (r13): the rank's lockstep ARRIVAL progress (entries
        # whose device dispatch it has begun), the deadline-bounded gang
        # boundary's per-rank signal.  Consumption counters (boundary
        # ask seq) cannot carry it: prep-ahead and lease batching freeze
        # every rank's consumption at the same value when the gang
        # wedges, so only begun-dispatch — riding the background beat,
        # the one RPC a wedged gang still sends — tells the straggler
        # from the ranks blocked in the collective on it.
        # collective_skips (r15): cumulative in-collective straggler
        # exclusions charged by the worker's in-step deadline gate
        # (graftreduce) — the master banks the newest value per worker
        # into the same bounded-skip ledger the r13 boundary deadline
        # feeds (JobStatus).  Additive and optional: no PROTOCOL_VERSION
        # bump, the r9/r12/r14 stance.
        optional={
            "version": _INT, "phase_times": _DICT, "gang_seq": _INT,
            "collective_skips": _INT,
        },
        since={"gang_seq": 13, "collective_skips": 15},
    ),
    "GetMembership": MessageSchema(),
    "GetCheckpoint": MessageSchema(),
    "ReportCheckpoint": MessageSchema(
        required={"path": _STR, "step": _INT},
        # Same phase snapshot as ReportTaskResult: the final/periodic
        # checkpoint report is the last word a worker sends, so it carries
        # the checkpoint-wire time the task reports cannot yet include.
        # worker_id keys the snapshot to the SAME per-worker slot the task
        # reports fill — without it the master would hold one worker's
        # cumulative timers under two keys and consumers would double-count.
        optional={"phase_times": _DICT, "worker_id": _STR},
    ),
    "JobStatus": MessageSchema(),
    # DumpTrace (r12): the live-job introspection pull — returns every
    # process's shipped trace buffer plus the master's own recorder window
    # (tools/trace_dump.py merges them into one Chrome-trace JSON with
    # clock alignment).  Non-draining: repeated dumps see the same window.
    # A new METHOD is additive by construction (an old master returns
    # UNIMPLEMENTED, an old worker never calls it) — no PROTOCOL_VERSION
    # bump, the same stance as r9's lease field.
    "DumpTrace": MessageSchema(),
}

# trace (r12): the cross-process trace envelope, additive and optional on
# EVERY master method (same no-version-bump stance as r9's lease):
#   {"ctx": [span_id]}            — the caller's live span, injected by
#                                   JsonRpcClient so the servicer's span
#                                   can name its remote parent;
#   {"events": [...],             — a bounded slice of the worker's ring
#    "clock_offset_us": float,      buffer riding the Heartbeat/Report
#    "dropped": int}                channel (the pull path's supply side),
#                                   with the worker's RTT-midpoint clock
#                                   offset vs the master.
# phase_counts rides beside phase_times on the report/heartbeat methods:
# PhaseTimers.counts() — per-phase entry counts, so consumers can compute
# per-phase AVERAGES, not just cumulative sums, from artifacts.
for _method_schema in MASTER_SCHEMAS.values():
    _method_schema.optional.setdefault("trace", _DICT)
    _method_schema.since.setdefault("trace", 12)
for _method in ("ReportTaskResult", "Heartbeat", "ReportCheckpoint"):
    MASTER_SCHEMAS[_method].optional.setdefault("phase_counts", _DICT)
    MASTER_SCHEMAS[_method].since.setdefault("phase_counts", 12)
# gauge (r14): the live-metrics envelope — a worker/PS process's
# ``gauge.Registry.snapshot()`` ({"families": {...}}) riding the same
# heartbeat/report channel as the trace slices, so the master's /metrics
# endpoint can serve the FLEET view (aggregated examples/sec, per-rank
# gang lag, goodput) without a new RPC.  Additive and optional on the
# same three methods as phase_counts — no PROTOCOL_VERSION bump (the
# r9/r12 stance: old peers ignore the field in either direction).
for _method in ("ReportTaskResult", "Heartbeat", "ReportCheckpoint"):
    MASTER_SCHEMAS[_method].optional.setdefault("gauge", _DICT)
    MASTER_SCHEMAS[_method].since.setdefault("gauge", 14)


SERVING_SERVICE_NAME = "elasticdl.Serving"

#: The serving tier's wire contract (serving/server.py's method table —
#: asserted in lockstep by tests, like MASTER_SCHEMAS above).  Feature
#: values ride as JSON lists: online requests are a handful of examples, so
#: JSON's ~4x float inflation is noise here (the bulk-tensor path that
#: justified the PS tier's binary frames moves 6.8 MB pulls; a Predict
#: moves tens of floats).
SERVING_SCHEMAS: Dict[str, MessageSchema] = {
    # features: {feature_name: nested list}, shaped per the model's feature
    # template (ModelInfo reports it).  A single example may omit the
    # leading batch dim; multi-example requests carry it.  lane (optional,
    # r19): priority lane — "online" (default, the latency-SLO lane) or
    # "bulk" (eval scoring; weighted admission, shed first).  Optional so
    # pre-lane clients keep working unchanged — the r9/r12 stance.
    "Predict": MessageSchema(
        required={"features": _DICT}, optional={"lane": _STR},
        since={"lane": 19},
    ),
    "ModelInfo": MessageSchema(),
}


#: Response contracts (r22): the other half of every method's wire shape.
#: Until r22 only REQUESTS were schema-checked — a master returning a
#: malformed response surfaced as a KeyError deep in the worker's task
#: loop, the exact failure mode validate_message exists to prevent.  The
#: same additive-compat grammar applies: every post-baseline field is
#: OPTIONAL with a ``since`` revision (old masters omit it; consumers use
#: ``.get()``, which graftlint's wire-discipline rule enforces), unknown
#: fields pass through counted-not-rejected (common/wiresan.py), and
#: shape violations raise deterministically when GRAFT_WIRESAN=1 arms
#: the checks on both ends of the wire.
MASTER_RESPONSE_SCHEMAS: Dict[str, MessageSchema] = {
    # task is optional because "no task right now" is encoded as an
    # explicit null; tasks (r9) batches up to ``lease`` task dicts with
    # task mirroring the first entry for pre-lease consumers.
    "GetTask": MessageSchema(
        required={"finished": _BOOL},
        optional={"task": _DICT, "tasks": _LIST},
        since={"tasks": 9},
    ),
    "GetGroupTask": MessageSchema(
        required={"finished": _BOOL, "stale": _BOOL},
        optional={"task": _DICT, "entries": _LIST},
        since={"entries": 9},
    ),
    # duplicate (r18): accepted=True with duplicate=True marks a
    # seq-deduped replay — the retried report was already applied before
    # the master restart; the worker treats it as a normal ack.
    "ReportTaskResult": MessageSchema(
        required={"accepted": _BOOL},
        optional={"duplicate": _BOOL},
        since={"duplicate": 18},
    ),
    "ReportVersion": MessageSchema(),
    # The rendezvous membership view; stale_tasks (r18) rides only the
    # reconcile path (a register that declared held_tasks).
    "RegisterWorker": MessageSchema(
        required={
            "version": _INT, "workers": _LIST, "ranks": _DICT,
            "world_size": _INT, "expected": _INT, "confirmed": _DICT,
            "addresses": _DICT,
        },
        optional={"stale_tasks": _LIST},
        since={"stale_tasks": 18},
    ),
    "DeregisterWorker": MessageSchema(required={"version": _INT}),
    # The beat's reply carries every master->worker hint: eval_pending /
    # draining (r9, the lease-recall hints), server_ts_us (r12, the
    # clock-offset stamp), standby_pool (r13).  All optional — a worker
    # masked to an older revision still gets the one field it needs
    # (the membership version driving restart decisions).
    "Heartbeat": MessageSchema(
        required={"version": _INT},
        optional={
            "server_ts_us": _NUM, "eval_pending": _BOOL,
            "standby_pool": _INT, "draining": _BOOL,
        },
        since={
            "eval_pending": 9, "draining": 9, "server_ts_us": 12,
            "standby_pool": 13,
        },
    ),
    "GetMembership": MessageSchema(
        required={
            "version": _INT, "workers": _LIST, "ranks": _DICT,
            "world_size": _INT, "expected": _INT, "confirmed": _DICT,
            "addresses": _DICT,
        },
    ),
    # path is optional because "no checkpoint yet" is an explicit null.
    "GetCheckpoint": MessageSchema(
        required={"step": _INT}, optional={"path": _STR}
    ),
    "ReportCheckpoint": MessageSchema(),
    # The dispatcher counts plus every banked per-worker view.  The
    # conditional sections (journal replay stats, standby depth, eval
    # aggregates) are optional; the rest rides every response.
    "JobStatus": MessageSchema(
        required={
            "todo": _INT, "doing": _INT, "done": _INT, "abandoned": _INT,
            "epoch": _INT, "skipped": _INT, "skip_counts": _DICT,
            "duplicate_done": _INT, "finished": _BOOL,
            "model_version": _INT, "phase_times": _DICT,
            "phase_counts": _DICT, "skipped_ranks": _DICT,
            "collective_skips": _DICT, "stale_reports": _INT,
        },
        optional={
            "journal": _DICT, "standby_pool": _INT,
            "eval_metrics": _DICT, "eval_rounds": _INT,
        },
        since={"journal": 18, "standby_pool": 13, "eval_rounds": 9},
    ),
    "DumpTrace": MessageSchema(
        required={
            "processes": _DICT, "master_events": _LIST,
            "master_dropped": _INT, "master_now_us": _NUM,
        },
    ),
}

#: Serving responses: outputs may be a list (the common case) or a dict
#: of named output heads (_listify preserves dict-shaped model outputs).
SERVING_RESPONSE_SCHEMAS: Dict[str, MessageSchema] = {
    "Predict": MessageSchema(
        required={"outputs": (list, dict), "model": _STR, "step": _INT},
    ),
    "ModelInfo": MessageSchema(
        required={
            "model": _STR, "step": _INT, "max_batch": _INT,
            "max_delay_ms": _NUM, "batch_buckets": _LIST,
            "features": _DICT, "requests": _INT, "reloads": _INT,
            "last_swap_ms": _NUM, "last_load_s": _NUM, "batcher": _DICT,
            "cache": _DICT,
        },
    ),
}

#: service name -> (request schemas, response schemas): the lookup both
#: JsonRpcClient and make_generic_handler default from, so every client
#: and server of a known service validates both directions without each
#: call site wiring the tables through.
SERVICE_SCHEMAS: Dict[str, Tuple[Dict[str, MessageSchema], Dict[str, MessageSchema]]] = {
    SERVICE_NAME: (MASTER_SCHEMAS, MASTER_RESPONSE_SCHEMAS),
    SERVING_SERVICE_NAME: (SERVING_SCHEMAS, SERVING_RESPONSE_SCHEMAS),
}


class SchemaError(ValueError):
    """A message violated its method's schema (the structured boundary error)."""


class RpcOverloaded(RuntimeError):
    """A handler shed the request: the service is past its capacity knee
    and refusing work ON PURPOSE.  The generic handler surfaces any
    subclass as RESOURCE_EXHAUSTED — the structured back-off-or-add-
    capacity signal callers branch on (e.g. the serving fleet client
    never retries it) — instead of an unstructured UNKNOWN."""


# -- the ONE retry/backoff policy (r18) -------------------------------------
#
# Before r18 the repo had three hand-rolled retry loops — the PS client's
# fixed backoff table, the worker's transient-collective retry, and a
# hard-failing channel-readiness wait — each with its own schedule, its own
# (or no) jitter, and its own observability.  They are now ONE code path:
# ``call_with_backoff`` owns exponential backoff + jitter + max-attempts +
# a wall budget, emits ``edl_rpc_retry_total{service=}`` into the
# process-default gauge registry and an ``rpc:retry`` trace instant per
# retry, and every adopter (PS ``RemoteEmbeddingStore._retry``, the
# worker's ``_retry_transient_collective``, ``RpcMasterProxy``'s outage
# ride-through and every readiness wait via ``wait_channel_ready``) just
# declares its schedule and its transience predicate.  The graftlint
# ``rpc-discipline`` rule enforces the readiness half: the raw
# ``grpc.channel_ready_future`` primitive is legal only in this module.


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff schedule: ``base_s * multiplier**n`` capped at
    ``max_s``, each delay jittered by ``±jitter`` (a fraction).  Retrying
    stops at ``max_attempts`` total attempts (0 = unbounded) or once
    ``budget_s`` of wall clock has elapsed since the first attempt (0 =
    no wall budget); at least one of the two should bound the loop."""

    base_s: float = 0.5
    multiplier: float = 2.0
    max_s: float = 8.0
    jitter: float = 0.2
    max_attempts: int = 0
    budget_s: float = 0.0


def call_with_backoff(
    fn: Callable[[], Any],
    *,
    service: str,
    is_transient: Callable[[BaseException], bool],
    policy: BackoffPolicy,
    on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
    terminal: Optional[Callable[[BaseException, int, float], BaseException]] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    budget_s_fn: Optional[Callable[[], float]] = None,
) -> Any:
    """Run ``fn()``, retrying errors ``is_transient`` accepts under
    ``policy``.  Non-transient errors surface immediately.  On exhaustion
    the ORIGINAL error re-raises (so adopters' callers keep their error
    contracts), unless ``terminal`` builds a clearer one — it is raised
    ``from`` the original.  ``on_retry(error, attempt, delay_s)`` runs
    before each sleep (adopter-specific logging/instants); the shared
    ``edl_rpc_retry_total{service=}`` counter and ``rpc:retry`` instant
    fire here for every adopter.  ``budget_s_fn`` makes the wall budget
    DYNAMIC — re-read every attempt, so a caller can shrink it under an
    in-flight retry loop (the preemption path cutting a parked
    ride-through short); it overrides ``policy.budget_s``."""
    attempt = 0
    start = clock()
    while True:
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — filtered by predicate
            if not is_transient(e):
                raise
            attempt += 1
            elapsed = clock() - start
            # A STATIC budget of 0 means "no wall budget" (attempts bound
            # the loop); a DYNAMIC budget is always active — its 0 means
            # "exhausted NOW" (the preemption path shrinking an in-flight
            # ride-through must fail it fast, never unbound it).
            if budget_s_fn is not None:
                budget_s = budget_s_fn()
                budget_active = True
            else:
                budget_s = policy.budget_s
                budget_active = bool(budget_s)
            exhausted = (
                policy.max_attempts and attempt >= policy.max_attempts
            ) or (budget_active and elapsed >= budget_s)
            if exhausted:
                if terminal is not None:
                    raise terminal(e, attempt, elapsed) from e
                raise
            delay = min(
                policy.base_s * policy.multiplier ** (attempt - 1),
                policy.max_s,
            )
            if policy.jitter:
                delay *= 1.0 + random.uniform(-policy.jitter, policy.jitter)
            if budget_active:
                delay = min(delay, max(0.0, budget_s - elapsed))
            gaugelib.default().counter(
                "edl_rpc_retry_total",
                "transient-error retries through the shared backoff helper",
                labels={"service": service},
            ).inc()
            trace.instant(
                "rpc:retry", cat="rpc.client", service=service,
                attempt=attempt, delay_ms=round(delay * 1e3, 1),
                error=type(e).__name__,
            )
            if on_retry is not None:
                on_retry(e, attempt, delay)
            sleep(delay)


def wait_channel_ready(
    channel,
    *,
    service: str,
    budget_s: float,
    per_try_s: float = 5.0,
    terminal: Optional[Callable[[BaseException, int, float], BaseException]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> None:
    """THE readiness wait: short ``channel_ready_future`` probes under the
    shared backoff until the channel is ready or ``budget_s`` elapses.
    One hard ``result(timeout=budget)`` (the pre-r18 shape) spends the
    whole budget inside grpc with no retry accounting and no jitter — a
    thundering herd of relaunched workers all re-dialing a restarting
    master at once is exactly when the jitter matters.  graftlint's
    rpc-discipline rule pins every readiness wait to this helper."""

    def probe():
        grpc.channel_ready_future(channel).result(
            timeout=min(per_try_s, budget_s) if budget_s else per_try_s
        )

    call_with_backoff(
        probe,
        service=service,
        is_transient=lambda e: isinstance(e, grpc.FutureTimeoutError),
        policy=BackoffPolicy(
            base_s=0.2, multiplier=2.0, max_s=2.0, jitter=0.2,
            budget_s=budget_s,
        ),
        terminal=terminal,
        sleep=sleep,
    )


def validate_message(
    method: str, msg: Any, schemas: Dict[str, MessageSchema]
) -> None:
    """Raise SchemaError naming every violation in ``msg`` for ``method``."""
    schema = schemas.get(method)
    if schema is None:
        raise SchemaError(f"unknown method {method!r}")
    if not isinstance(msg, dict):
        raise SchemaError(f"{method}: request must be an object, got {type(msg).__name__}")
    def type_ok(value, types) -> bool:
        # bool subclasses int: reject it for int/float fields, else
        # {"model_version": true} would silently bump the version to 1.
        if isinstance(value, bool):
            return bool in types
        return isinstance(value, types)

    problems = []
    for field, types in schema.required.items():
        if field not in msg:
            problems.append(f"missing required field {field!r}")
        elif not type_ok(msg[field], types):
            problems.append(
                f"field {field!r} must be {'/'.join(t.__name__ for t in types)}, "
                f"got {type(msg[field]).__name__}"
            )
    for field, types in schema.optional.items():
        if field in msg and msg[field] is not None and not type_ok(msg[field], types):
            problems.append(
                f"field {field!r} must be {'/'.join(t.__name__ for t in types)}, "
                f"got {type(msg[field]).__name__}"
            )
    if problems:
        raise SchemaError(f"{method}: " + "; ".join(problems))


def _serialize(msg: Dict[str, Any]) -> bytes:
    return json.dumps(msg).encode()


def _deserialize(payload: bytes) -> Dict[str, Any]:
    return json.loads(payload.decode()) if payload else {}


def make_generic_handler(
    service_name: str,
    methods: Dict[str, Callable[[dict], dict]],
    schemas: Optional[Dict[str, MessageSchema]] = None,
    response_schemas: Optional[Dict[str, MessageSchema]] = None,
) -> grpc.GenericRpcHandler:
    """gRPC handler table; with ``schemas``, every request is validated at
    the server boundary and violations abort with INVALID_ARGUMENT (unknown
    methods already return UNIMPLEMENTED via the generic handler).  With
    GRAFT_WIRESAN=1 armed, undeclared request fields are counted per
    method and each handler's OWN response is validated against
    ``response_schemas`` before it serializes (defaulted from
    SERVICE_SCHEMAS for known services) — a malformed response is a
    server bug and raises WireSanViolation in the handler's frame, where
    the stack names the culprit, instead of as a client-side KeyError."""
    if response_schemas is None:
        known = SERVICE_SCHEMAS.get(service_name)
        if known is not None:
            response_schemas = known[1]

    def wrap(name: str, fn: Callable[[dict], dict]):
        def handler(req, ctx):
            if schemas is not None:
                try:
                    validate_message(name, req, schemas)
                except SchemaError as e:
                    ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            if wiresan.enabled():
                # Counts undeclared request fields (the additive-compat
                # visibility counter); the shape itself was validated
                # above, so a violation here can only be an undeclared
                # SERVICE — schemas=None — which stays unjudged.
                wiresan.check(name, req, schemas, "request")
            # Server half of the RPC span: names its remote parent (the
            # client span id propagated in the trace envelope) so the
            # merged view links one logical RPC across the two processes.
            remote = 0
            if isinstance(req, dict):
                tctx = req.get("trace")
                if isinstance(tctx, dict):
                    # Shape-checked, never trusted: the schema only says
                    # "trace is a dict", and a malformed envelope must
                    # degrade to "no parent" — not turn every method into
                    # an unstructured INTERNAL before its handler runs.
                    tc = tctx.get("ctx")
                    if (
                        isinstance(tc, (list, tuple)) and tc
                        and isinstance(tc[0], int)
                    ):
                        remote = tc[0]
            try:
                with trace.span(
                    f"rpc:{name}", cat="rpc.server",
                    method=name, remote_parent=remote,
                ):
                    resp = fn(req)
                    if wiresan.enabled():
                        wiresan.check(name, resp, response_schemas, "response")
                    return resp
            except SchemaError as e:
                # Contract violations detected INSIDE a handler (e.g. the
                # RegisterWorker protocol-version check) surface as the same
                # structured boundary error, not a generic INTERNAL.
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
            except RpcOverloaded as e:
                ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))

        return handler

    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            wrap(name, fn),
            request_deserializer=_deserialize,
            response_serializer=_serialize,
        )
        for name, fn in methods.items()
    }
    return grpc.method_handlers_generic_handler(service_name, handlers)


class JsonRpcClient:
    """Typed-enough client for a JSON-over-gRPC service.

    Requests to the master service are validated against MASTER_SCHEMAS
    BEFORE they hit the wire, so a malformed message fails in the caller's
    stack frame with a field-naming SchemaError rather than as a remote
    INVALID_ARGUMENT (the server still enforces the same schemas)."""

    def __init__(
        self,
        address: str,
        service_name: str = SERVICE_NAME,
        schemas: Optional[Dict[str, MessageSchema]] = None,
        response_schemas: Optional[Dict[str, MessageSchema]] = None,
    ):
        self._channel = grpc.insecure_channel(
            address, options=GRPC_CLIENT_CHANNEL_OPTIONS
        )
        self._service = service_name
        self._stubs: Dict[str, Callable] = {}
        known = SERVICE_SCHEMAS.get(service_name)
        if schemas is None and known is not None:
            schemas = known[0]
        if response_schemas is None and known is not None:
            response_schemas = known[1]
        self._schemas = schemas
        self._response_schemas = response_schemas

    def wait_ready(self, timeout_s: float = 10.0) -> None:
        wait_channel_ready(
            self._channel, service=self._service, budget_s=timeout_s
        )

    def call(self, method: str, request: Dict[str, Any], timeout_s: float = 30.0):
        if self._schemas is not None:
            validate_message(method, request, self._schemas)
        if method not in self._stubs:
            # graftlint: allow[shared-state] idempotent per-method stub memo: racing creators (loop + beat threads) build equivalent stubs and the dict item set is atomic
            self._stubs[method] = self._channel.unary_unary(
                f"/{self._service}/{method}",
                request_serializer=_serialize,
                response_deserializer=_deserialize,
            )
        # Client half of the RPC span (deadline attribute included — a
        # deadline-bounded wait that times out shows as a span of exactly
        # that length).  The span id propagates in the request's trace
        # envelope; the request dict is COPIED before injection so a caller
        # reusing its dict (retries, pipelined reports) is never mutated.
        sp = trace.span(
            f"rpc:{method}", cat="rpc.client",
            method=method, deadline_s=timeout_s,
        )
        with sp:
            if sp.span_id and isinstance(request, dict):
                envelope = dict(request.get("trace") or {})
                envelope["ctx"] = [sp.span_id]
                request = dict(request)
                request["trace"] = envelope
            # graftchaos hook (no-op when disabled): an armed delay_rpc
            # sleeps HERE — inside the client span, so the injected
            # latency shows in the trace exactly where real network
            # latency would — and a drop_rpc raises ChaosRpcDropped, which
            # the call site sees as a failed RPC (lossy-network shape).
            chaos.hook("rpc:client", method=method)
            if wiresan.active():
                # Outgoing: count undeclared request fields (validation
                # is already always-on above) and apply the version mask
                # — a masked client sends exactly what a peer built at
                # that revision would.
                wiresan.check(method, request, self._schemas, "request")
                rev = wiresan.mask_rev()
                if rev is not None:
                    request = wiresan.mask(method, request, self._schemas, rev)
                response = self._stubs[method](request, timeout=timeout_s)
                # Incoming: the response is validated as sent (a current
                # master's response must satisfy the full contract), then
                # masked — the caller sees the old peer's view of it.
                wiresan.check(
                    method, response, self._response_schemas, "response"
                )
                if rev is not None:
                    response = wiresan.mask(
                        method, response, self._response_schemas, rev
                    )
                return response
            return self._stubs[method](request, timeout=timeout_s)

    def close(self) -> None:
        self._channel.close()
