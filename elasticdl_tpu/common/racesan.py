"""Runtime shared-state sanitizer — the dynamic twin of graftlint's
``shared-state`` pass (v5).

The static pass (analysis/shared_state.py) judges the LEXICAL picture:
``self.<attr>`` sites, inferred thread roles, lexically held locks.  It
is blind to instance confinement, to callables handed through
containers, and to roles only runtime wiring creates.  This module
closes that half, the way ``locksan`` does for lock order:

- ``@racesan.instrument`` opts a class in.  With ``GRAFT_RACESAN`` !=
  ``1`` the decorator returns the class UNTOUCHED — zero overhead in
  production (the grafttrace stance: disabled means not even a wrapper).
  Enabled (tests/conftest.py sets it for the whole tier-1 suite, like
  ``GRAFT_LOCKSAN``), it installs a checking ``__setattr__`` and a
  SAMPLED ``__getattribute__``.
- Every write (and every Nth read) records, per instance and attribute,
  the observing (thread-role, held-locks) pair.  The thread role comes
  from an explicit ``racesan.set_role(...)`` override or the thread's
  name with trailing instance digits stripped (``edl-ingest_3`` ->
  ``edl-ingest``) — the runtime mirror of the static role model.  Held
  locks are ``locksan``'s per-thread stack (enable both sanitizers
  together: with locksan off, wrapped locks are plain and invisible
  here).
- A WRITE raises :class:`RaceSanViolation` when a prior observation on a
  DIFFERENT role shares no held lock with it — the cross-role unguarded
  write, caught deterministically on the second access (edge-based, like
  locksan: the threads never need to actually collide).  Reads only
  record; a racy read surfaces when the writer next writes.

Observations live on the instance itself (per-instance by design: a
thread-confined instance of a shared class must not trip the checks —
the runtime counterpart of the static pass's instance-confinement blind
spot), so the record dies with the object and no global registry grows.

Exemptions mirror the static escape hatches: construction writes —
everything the constructing thread does before any OTHER thread touches
the instance (the happens-before edge is the spawn/hand-off that
publishes ``self``, so this covers subclass ``__init__`` bodies and
pre-publication setup alike) — attributes named in the decorator's
``atomic=`` set (the ``# gil-atomic`` twin), and
``single_writer={"_attr": "role"}`` declarations, which raise on any
write from another role regardless of locks (the ``# single-writer:``
twin) while their legal writes skip the lock-based cross-role check —
reads on other roles ride GIL-atomic loads by declaration.

Pure stdlib, jax-free (imported by master-process control-plane classes).
"""

from __future__ import annotations

import os
import re
import threading
from typing import Dict, Iterable, Optional

from elasticdl_tpu.common import locksan

__all__ = [
    "RaceSanViolation", "enabled", "instrument", "set_role", "thread_role",
]


class RaceSanViolation(AssertionError):
    """A cross-role unguarded write (or a write outside a declared
    single-writer role) on an instrumented attribute.  Raised AT the
    offending write, naming both observations, so the race is a loud
    deterministic failure instead of a once-a-week corruption."""


def enabled() -> bool:
    return os.environ.get("GRAFT_RACESAN", "") == "1"


_tls = threading.local()

#: Read-sampling period: record every Nth read per process.  Writes are
#: never sampled (writes are rare on control planes and are the raising
#:  side); reads only feed the observation set.
_READ_SAMPLE = 8
_read_tick = 0

_DIGITS = re.compile(r"[-_ ]*\d+$")


def set_role(role: Optional[str]) -> None:
    """Explicit role for the CURRENT thread (e.g. a gRPC handler wrapper
    sets ``grpc:MasterServicer``); ``None`` reverts to name inference."""
    _tls.role = role


def thread_role() -> str:
    role = getattr(_tls, "role", None)
    if role is not None:
        return role
    name = threading.current_thread().name
    if name == "MainThread":
        return "main"
    # Peer instances of one pool ("edl-ingest_0/1", "Thread-3") share a
    # role: the role is the concurrency DOMAIN, instance-agnostic —
    # same stance as locksan's name-level lock contract.
    return _DIGITS.sub("", name) or name


def _held_names() -> frozenset:
    return frozenset(locksan.held_names())


def instrument(cls=None, *, atomic: Iterable[str] = (),
               single_writer: Optional[Dict[str, str]] = None):
    """Class decorator opting into runtime shared-state checking.

    ``atomic`` names attributes exempt from cross-role checks (the
    runtime twin of ``# gil-atomic``); ``single_writer`` maps attribute
    -> role that alone may write it (the ``# single-writer:`` twin —
    violations raise regardless of locks held).
    """
    atomic_set = frozenset(atomic)
    writers = dict(single_writer or {})

    def wrap(klass):
        if not enabled():
            return klass  # production: the class is untouched

        orig_init = klass.__init__
        orig_setattr = klass.__setattr__
        orig_getattribute = klass.__getattribute__

        def __init__(self, *args, **kw):
            object.__setattr__(self, "_racesan_obs", {})
            # Construction tracking: everything the constructing thread
            # does before any OTHER thread touches the instance is
            # pre-publication (the hand-off IS the happens-before edge) —
            # this covers subclass __init__ bodies running after
            # super().__init__() returns, which a plain in-init flag
            # cannot see.
            object.__setattr__(
                self, "_racesan_ctor", threading.get_ident()
            )
            object.__setattr__(self, "_racesan_published", False)
            orig_init(self, *args, **kw)

        def _pre_publication(self) -> bool:
            """True while the constructing thread is still the only one
            to have touched the instance (construction exemption); flips
            the published flag on the first other-thread access."""
            inst = object.__getattribute__(self, "__dict__")
            if inst.get("_racesan_published", False):
                return False
            if threading.get_ident() == inst.get("_racesan_ctor"):
                return True
            object.__setattr__(self, "_racesan_published", True)
            return False

        def __setattr__(self, name, value):
            if (
                name.startswith("_racesan")
                or name in atomic_set
                or "_racesan_obs" not in object.__getattribute__(
                    self, "__dict__"
                )
                or _pre_publication(self)
            ):
                orig_setattr(self, name, value)
                return
            role = thread_role()
            declared = writers.get(name)
            if declared is not None:
                if role != declared:
                    raise RaceSanViolation(
                        f"racesan: {klass.__name__}.{name} is declared "
                        f"single-writer role {declared!r} but written from "
                        f"role {role!r}"
                    )
                # The declared writer's writes are legal by contract:
                # record the observation but skip the lock-based
                # cross-role check (readers on other roles ride
                # GIL-atomic loads — the # single-writer: stance).
                _check_and_record(
                    self, klass, name, role, _held_names(), write=False,
                )
            else:
                _check_and_record(
                    self, klass, name, role, _held_names(), write=True,
                )
            orig_setattr(self, name, value)

        def __getattribute__(self, name):
            value = orig_getattribute(self, name)
            if name.startswith("_racesan") or name.startswith("__"):
                return value
            global _read_tick
            _read_tick += 1  # sampling only: a torn tick skews nothing
            if _read_tick % _READ_SAMPLE:
                return value
            try:
                inst = object.__getattribute__(self, "__dict__")
                if (
                    name in inst
                    and name not in atomic_set
                    and "_racesan_obs" in inst
                    and not _pre_publication(self)
                ):
                    _check_and_record(
                        self, klass, name, thread_role(), _held_names(),
                        write=False,
                    )
            except RaceSanViolation:
                raise
            except Exception:
                pass  # the sanitizer must never break a working read
            return value

        klass.__init__ = __init__
        klass.__setattr__ = __setattr__
        klass.__getattribute__ = __getattribute__
        klass._racesan_instrumented = True
        return klass

    return wrap if cls is None else wrap(cls)


def _check_and_record(self, klass, name, role, held, write: bool) -> None:
    """Record the (role, held) observation; on a WRITE, raise when any
    prior observation on another role shares no lock with it."""
    try:
        obs = object.__getattribute__(self, "_racesan_obs")
    except AttributeError:
        # Instrumented subclass whose __init__ never ran (rare: __new__
        # tricks) — observe from here on.
        obs = {}
        object.__setattr__(self, "_racesan_obs", obs)
    by_role = obs.setdefault(name, {})
    if write:
        for other_role, heldsets in by_role.items():
            if other_role == role:
                continue
            for other_held in heldsets:
                if held.isdisjoint(other_held):
                    raise RaceSanViolation(
                        f"racesan: cross-role unguarded write — "
                        f"{klass.__name__}.{name} written on role {role!r} "
                        f"holding {sorted(held) or 'no locks'} after an "
                        f"access on role {other_role!r} holding "
                        f"{sorted(other_held) or 'no locks'}; guard both "
                        "sides with one lock (or declare the attribute "
                        "single-writer/atomic at the opt-in site)"
                    )
    by_role.setdefault(role, set()).add(held)
