"""The live metrics plane's reading half: a /metrics + /healthz server.

One of these runs beside every process of a job — master, each worker,
each PS shard, the serving replica — on its OWN daemon threads
(``ThreadingHTTPServer``), never the task loop: a gang wedged inside a
collective, a PS shard blocked in a save, a batcher past its knee must
all still answer a scrape, because the wedge is exactly when the
operator needs the numbers (the r13 chaos stance: the instrument must
survive the failure it exists to show).

Stdlib only (``http.server``): the master control plane and the PS
shards are jax-free by contract, and pulling an HTTP framework in for
two GET routes would be the heaviest import in the process.

Routes:

- ``GET /metrics``  -> Prometheus text (the ``render_fn``, usually a
  ``gauge.Registry.render_prometheus`` bound method — collectors run per
  scrape, so pull-model families are fresh);
- ``GET /healthz``  -> JSON liveness (``health_fn`` -> dict; always
  ``{"status": "ok", ...}`` while the process answers at all — liveness
  is "the scrape thread is alive", not "the job is healthy": health
  judgements belong to the metrics themselves).

Port 0 (the default) binds ephemeral and the caller logs the bound
address — a job's processes share ONE config bus, so a fixed port would
collide the moment two workers land on a host.  Every process logs the
``[graftgauge] serving /metrics on <addr>`` line at startup; benches and
operators discover endpoints from the pod logs exactly as the chaos
bench reads ``[graftchaos]`` audit lines.
"""

from __future__ import annotations

import json
import socket
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("metrics_http")

#: Prometheus text exposition content type.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    """Scrape server over a render callable (and an optional health one).

    ``start()`` spawns the accept loop on a daemon thread and returns
    self; ``stop()`` shuts it down.  Handler errors answer 500 with the
    error text — a broken collector must be visible to the scraper, not
    a silent empty page.
    """

    def __init__(
        self,
        render_fn: Callable[[], str],
        health_fn: Optional[Callable[[], Dict]] = None,
        port: int = 0,
        host: str = "0.0.0.0",
    ):
        self._render = render_fn
        self._health = health_fn

        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — http.server contract
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._respond_with(outer._render_bytes)
                elif path == "/healthz":
                    self._respond_with(
                        outer._health_bytes, "application/json"
                    )
                else:
                    self.send_error(404, "try /metrics or /healthz")

            def _respond_with(self, fn, ctype: str = CONTENT_TYPE) -> None:
                try:
                    body = fn()
                except Exception as e:  # broken render must be VISIBLE
                    logger.exception("metrics render failed")
                    body = f"render failed: {e}".encode()
                    self.send_response(500)
                else:
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass  # scrapes every few seconds must not spam the pod log

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        # The address OTHER hosts can dial (the pod-log discovery line):
        # a wildcard bind advertises this host's name — logging
        # "localhost" for a worker pod on another machine would hand the
        # operator an address that points at their own box.
        self._advertise_host = (
            socket.gethostname() if host in ("", "0.0.0.0", "::") else host
        )
        self._thread: Optional[threading.Thread] = None

    def _render_bytes(self) -> bytes:
        return self._render().encode()

    def _health_bytes(self) -> bytes:
        payload = {"status": "ok"}
        if self._health is not None:
            payload.update(self._health() or {})
        return json.dumps(payload, sort_keys=True).encode()

    @property
    def address(self) -> str:
        """Loopback view — for same-process/same-host consumers (the
        benches, in-process tests).  Cross-host discovery uses the
        logged ``advertise_address``."""
        return f"localhost:{self.port}"

    @property
    def advertise_address(self) -> str:
        return f"{self._advertise_host}:{self.port}"

    def start(self) -> "MetricsHTTPServer":
        t = threading.Thread(
            target=self._httpd.serve_forever,
            name="edl-metrics-http",
            daemon=True,
        )
        t.start()
        self._thread = t
        # The discovery line (the [graftchaos] pod-log pattern): with
        # ephemeral ports this is how benches and operators find the
        # endpoint of an out-of-process pod — so it must carry an
        # address reachable from OFF this host.
        logger.info(
            "[graftgauge] serving /metrics on %s", self.advertise_address
        )
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def maybe_start(
    port: int,
    render_fn: Callable[[], str],
    health_fn: Optional[Callable[[], Dict]] = None,
    registry=None,
) -> Optional[MetricsHTTPServer]:
    """The one wiring idiom every main shares: ``port < 0`` = disabled
    (None), else bind-and-start (0 = ephemeral).  A bind failure logs and
    returns None — observability must never take the job down.

    ``registry`` (a ``gauge.Registry``, usually the one behind
    ``render_fn``) additionally installs the locksan contention
    collector (r16): lock acquire counts + wait-time histograms join the
    endpoint as ``edl_lock_acquire_total`` / ``edl_lock_wait_ms`` —
    only once an endpoint exists does anyone pay for recording them."""
    if port < 0:
        return None
    try:
        server = MetricsHTTPServer(
            render_fn, health_fn=health_fn, port=port
        ).start()
        if registry is not None:
            # AFTER the successful bind: a failed endpoint must not leave
            # contention recording permanently on with nobody scraping.
            from elasticdl_tpu.common import gauge

            gauge.install_lock_collector(registry)
            # jitsan compile counts (v6) ride the same wiring idiom: the
            # edl_jit_compiles_total family joins every endpoint so a
            # production retrace shows up in watch_job, not just tests.
            gauge.install_jit_collector(registry)
            # wiresan unknown-field counts (v8): the
            # edl_wire_unknown_fields_total family is the mixed-version-
            # fleet signal — a newer peer's additive fields, visible on
            # every endpoint.
            gauge.install_wire_collector(registry)
        return server
    except OSError:
        logger.exception(
            "metrics endpoint failed to bind port %d; continuing without",
            port,
        )
        return None


# ---- scrape client (the OTHER end of the endpoint above) ----------------
#
# Moved here from tools/watch_job.py (r19): the serving fleet controller
# scrapes its replicas' /metrics endpoints as the autoscaling signal, and a
# framework module cannot import from tools/ — so the fetch/parse pair
# lives beside the server it reads and watch_job re-imports it.  Still
# stdlib-only: this file stays legal for the jax-free control plane AND
# the operator's laptop.


def _url(address: str, path: str = "/metrics") -> str:
    if address.startswith(("http://", "https://")):
        base = address.rstrip("/")
        # An explicit path in the URL wins (scraping through a proxy).
        return base if "/" in base.split("//", 1)[1] else base + path
    return f"http://{address}{path}"


def fetch_text(address: str, path: str = "/metrics",
               timeout_s: float = 5.0) -> str:
    with urllib.request.urlopen(_url(address, path), timeout=timeout_s) as r:
        return r.read().decode()


def _parse_labels(body: str) -> Dict[str, str]:
    """``a="b",c="d"`` -> dict.  The renderer never emits quotes/commas
    inside values (labels come from worker ids / phase names), so a
    simple split is exact for our own exposition."""
    out: Dict[str, str] = {}
    for part in body.split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip().strip('"')
    return out


def parse_prometheus(text: str) -> Dict[str, dict]:
    """Prometheus text -> ``{family: {"type", "help", "samples": [
    {"name", "labels", "value"}]}}`` — the inverse of
    ``gauge.render_families`` (histogram ``_bucket``/``_sum``/``_count``
    series stay flat samples under their family).  Malformed lines are
    skipped: this parses OUR renderer's output, but a scrape racing a
    process exit may truncate mid-line."""
    families: Dict[str, dict] = {}

    def fam(name: str) -> dict:
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[: -len(suffix)] in families:
                base = base[: -len(suffix)]
                break
        return families.setdefault(
            base, {"type": "untyped", "help": "", "samples": []}
        )

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):].split(" ", 1)
            fam(rest[0])["help"] = rest[1] if len(rest) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):].split(" ", 1)
            fam(rest[0])["type"] = rest[1].strip() if len(rest) > 1 else ""
            continue
        if line.startswith("#"):
            continue
        try:
            metric, value_s = line.rsplit(" ", 1)
            value = float(value_s)
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        name = metric
        if "{" in metric and metric.endswith("}"):
            name, body = metric.split("{", 1)
            labels = _parse_labels(body[:-1])
        fam(name)["samples"].append(
            {"name": name, "labels": labels, "value": value}
        )
    return families


def fetch(address: str, timeout_s: float = 5.0) -> Dict[str, dict]:
    """One scrape, parsed — the programmatic entry (benches stamp this as
    their ``live_metrics`` snapshot; the fleet controller reads its knee
    signal from it)."""
    return parse_prometheus(fetch_text(address, timeout_s=timeout_s))
