"""crashsan — durable-write crash-point sanitizer (GRAFT_CRASHSAN).

The dynamic twin of graftlint v7's durability rules, in the locksan /
racesan / jitsan stance: the static pass (analysis/durability.py) proves
every durable write ROUTES through ``common/durable.py``; this module
proves each of those routed writes actually RECOVERS.  Every durable op
(append, whole-file publish, external-tmp replace) crosses ``crossing()``
before touching disk; a test arms :func:`crash_at` and the crossing then
deterministically produces ON DISK the exact state a real process death
at that point leaves — a torn final append, a fully-fsync'd temp whose
rename never landed, an fsync that was skipped before the crash — and
raises :class:`CrashPoint`.  The recovery reader under test then runs
against that state and must land inside its documented contract
(docs/robustness.md "Durability contracts"): bit-identical, watermark
fallback, or at-least-once — never silent corruption.

Crash modes, per op kind (the matrix tools/crashsan_matrix.py sweeps):

=============  ==========================================================
``append``     ``torn_append``  the single ``os.write`` was cut short: a
               torn FINAL line lands on disk, unsynced, process dies.
               ``append_lost``  the crash beat the fsync: the appended
               bytes died in the page cache — nothing lands at all.
``publish``    ``tmp_torn``     death mid-write of the temp: a torn temp
               exists, the target is untouched.
               ``rename_lost``  the temp is complete and fsync'd but the
               rename never landed: the target still holds the PREVIOUS
               version.
               ``published_torn``  a non-compliant writer renamed before
               fsync and the data died after the rename: the TARGET
               itself is torn.  atomic_publish makes this impossible;
               the mode exists to prove the reader's tolerance contract
               holds even against it.
``replace``    same three modes over an externally-written temp
               (``durable.atomic_replace``): the temp is truncated to a
               prefix instead of rewritten, since its content is opaque.
=============  ==========================================================

Cost contract: the crossing is called only from ``common/durable.py`` ops
that already pay an fsync (milliseconds), so its disabled cost — one lock
guarded counter bump feeding the per-file op index the chaos grammar's
``torn_write:file=<durable>,op=N`` matches against — is noise.  Crash
injection itself (the state production) only runs when a test armed
:func:`crash_at` or a chaos ``torn_write`` fault requested it.

:class:`CrashPoint` subclasses ``BaseException`` ON PURPOSE: production
recovery code legitimately wraps durable ops in ``except Exception`` /
``except OSError`` handlers, and a simulated process death those handlers
could swallow would test the handler, not the crash.  Only the test
harness catches CrashPoint.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict, List, Optional

APPEND_MODES = ("torn_append", "append_lost")
PUBLISH_MODES = ("tmp_torn", "rename_lost", "published_torn")

#: Every mode a chaos ``torn_write`` fault may name (parse-time check).
ALL_MODES = APPEND_MODES + PUBLISH_MODES


class CrashPoint(BaseException):
    """Simulated process death at a durable-op boundary.

    BaseException, not Exception: recovery code's own ``except Exception``
    handlers must not be able to swallow a crash — a real ``os._exit``
    gives them no such chance, and the simulation must not either."""


class CrashSanError(AssertionError):
    """Misuse of the sanitizer itself (bad mode, bad kind)."""


_lock = threading.Lock()  # lock-order: leaf
_op_count = 0  # guarded-by: _lock
_per_file: Dict[str, int] = {}  # guarded-by: _lock
_recorders: List[List[dict]] = []  # guarded-by: _lock
_armed: Optional[Dict[str, Any]] = None  # guarded-by: _lock
#: The chaos plan's torn_write faults, handed over at chaos.configure()
#: time.  Matching lives HERE, not in the injector's hook: durable ops
#: fire under leaf-declared subsystem locks (the journal appends under
#: TaskDispatcher._lock), and acquiring the locksan-wrapped
#: ChaosInjector._lock there is a lock-order violation — this module's
#: plain lock is a true leaf the sanitizers cannot see or order.
_torn_plan: List[Dict[str, Any]] = []  # guarded-by: _lock

# test seam (the ChaosInjector._exit pattern): a chaos-driven crash must be
# observable without killing the test runner.
_exit = os._exit


def enabled() -> bool:
    return os.environ.get("GRAFT_CRASHSAN") == "1"


def op_count() -> int:
    with _lock:
        return _op_count


def reset() -> None:
    """Forget counters, recorders and the armed crash (test isolation).
    The chaos torn_write plan is NOT cleared — chaos.configure owns it."""
    global _op_count, _armed
    with _lock:
        _op_count = 0
        _per_file.clear()
        _recorders.clear()
        _armed = None


@contextlib.contextmanager
def record():
    """Capture every durable-op crossing in the block: yields a list of
    ``{"index", "kind", "file", "path", "file_op"}`` dicts — the op
    enumeration the matrix driver sweeps crash points over."""
    buf: List[dict] = []
    with _lock:
        _recorders.append(buf)
    try:
        yield buf
    finally:
        with _lock:
            _recorders.remove(buf)


def arm(nth: int, mode: str) -> None:
    """Crash at the ``nth`` durable-op crossing from now (0-based)."""
    if mode not in ALL_MODES:
        raise CrashSanError(
            f"unknown crash mode {mode!r} (known: {', '.join(ALL_MODES)})"
        )
    if not enabled():
        # Fail LOUD: a test that arms a crash point with the sanitizer off
        # would otherwise "pass" by never crashing anything.
        raise CrashSanError("GRAFT_CRASHSAN=1 required to arm crash points")
    global _armed
    with _lock:
        _armed = {"remaining": int(nth), "mode": mode, "fired": None}


def disarm() -> Optional[dict]:
    """Disarm; returns the fired record (or None if it never fired)."""
    global _armed
    with _lock:
        state, _armed = _armed, None
        return state["fired"] if state else None


@contextlib.contextmanager
def crash_at(nth: int, mode: str):
    """Arm a deterministic crash at the nth crossing inside the block.
    The CrashPoint propagates out — wrap in ``pytest.raises(CrashPoint)``."""
    arm(nth, mode)
    try:
        yield
    finally:
        disarm()


def set_torn_plan(faults: List[Dict[str, Any]]) -> None:
    """Install the chaos plan's torn_write faults (called by
    ``chaos.configure`` — empty clears).  Each fault:
    ``{"file": basename, "op": exact-per-file-index-or-None,
    "mode": crash-mode-or-"", "count": max-fires (0=unlimited),
    "skip": ignore-first-N-matches}``.  Firing state resets —
    reconfiguring IS a new experiment (the injector's stance)."""
    global _torn_plan
    plan = [dict(f, seen=0, fired=0) for f in faults]
    with _lock:
        _torn_plan = plan


def note_op(kind: str, path: str) -> tuple:
    """Record one durable-op crossing.  Returns ``(file_op_index,
    armed_mode_or_None, chaos_mode_or_None)``: the per-file 0-based op
    index, the crash mode when a :func:`crash_at` countdown hit zero on
    this crossing, and the chaos mode (possibly ``""`` = kind default)
    when a torn_write fault matched — the caller produces that state and
    dies for real."""
    global _op_count
    if not enabled() and not _torn_plan:
        return 0, None, None
    fname = os.path.basename(path)
    with _lock:
        idx = _op_count
        _op_count += 1
        file_op = _per_file.get(fname, 0)
        _per_file[fname] = file_op + 1
        rec = {
            "index": idx, "kind": kind, "file": fname, "path": path,
            "file_op": file_op,
        }
        for buf in _recorders:
            buf.append(dict(rec, index=len(buf)))
        mode = None
        if _armed is not None and _armed["fired"] is None:
            if _armed["remaining"] <= 0:
                _armed["fired"] = rec
                mode = _armed["mode"]
            else:
                _armed["remaining"] -= 1
        chaos_mode = None
        for fault in _torn_plan:
            if fault["file"] != fname:
                continue
            if fault["op"] is not None and fault["op"] != file_op:
                continue
            fault["seen"] += 1
            if fault["seen"] <= fault.get("skip", 0):
                continue
            count = fault.get("count", 1)
            if count and fault["fired"] >= count:
                continue
            fault["fired"] += 1
            chaos_mode = fault.get("mode", "")
            break
    return file_op, mode, chaos_mode


def simulate(
    kind: str,
    mode: str,
    *,
    path: str,
    fd: Optional[int] = None,
    data: Optional[bytes] = None,
    tmp: Optional[str] = None,
    die: Optional[int] = None,
) -> None:
    """Produce the on-disk state a real crash at this op leaves, then die
    — :class:`CrashPoint` for test-armed crashes, ``os._exit(die)`` for
    chaos-driven ones (the chaos ``kill`` stance: a real crash skips
    interpreter teardown, so the simulated one must too)."""
    if kind == "append":
        if mode not in APPEND_MODES:
            raise CrashSanError(f"mode {mode!r} does not apply to appends")
        if mode == "torn_append" and data:
            # The single os.write was cut short: a torn prefix of the
            # final line lands, never fsync'd (a real torn tail may or
            # may not survive; landing it is the harder case).
            os.write(fd, data[: max(1, len(data) // 2)])
        # append_lost: the bytes died in the page cache — write nothing.
    elif kind == "publish":
        if mode not in PUBLISH_MODES:
            raise CrashSanError(f"mode {mode!r} does not apply to publishes")
        half = (data or b"x")[: max(1, len(data or b"x") // 2)]
        if mode == "tmp_torn":
            with open(tmp, "wb") as f:
                f.write(half)
        elif mode == "rename_lost":
            with open(tmp, "wb") as f:
                f.write(data or b"")
                f.flush()
                os.fsync(f.fileno())
        else:  # published_torn: rename-before-fsync, data died after
            with open(tmp, "wb") as f:
                f.write(half)
            os.replace(tmp, path)
    elif kind == "replace":
        # The temp was written EXTERNALLY (its full content is already on
        # disk, fsync pending): torn = truncate to a prefix.
        if mode not in PUBLISH_MODES:
            raise CrashSanError(f"mode {mode!r} does not apply to replaces")
        if mode == "tmp_torn":
            _truncate_half(tmp)
        elif mode == "published_torn":
            _truncate_half(tmp)
            os.replace(tmp, path)
        # rename_lost: leave the complete temp where it is, no rename.
    else:
        raise CrashSanError(f"unknown durable op kind {kind!r}")
    if die is not None:
        import sys

        print(
            f"[crashsan] chaos torn_write: {mode} at {path} (op kind "
            f"{kind}); dying", file=sys.stderr, flush=True,
        )
        _exit(die)
    raise CrashPoint(f"simulated crash: {mode} during {kind} of {path}")


def _truncate_half(path: str) -> None:
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    with open(path, "rb+") as f:
        f.truncate(max(1, size // 2))
