"""Job configuration — the cross-process "config bus".

The reference (ElasticDL) uses a layered argparse flag set
(``elasticdl/python/common/args.py`` [U: mount empty at survey time]) that the
client validates, the master re-parses, and the master serializes into worker /
PS pod command lines.  We keep the same pattern with one typed dataclass that
(a) parses from the same flag names the reference exposes
(``--distribution_strategy``, ``--model_zoo``, ``--model_def``,
``--minibatch_size``, ...), and (b) round-trips losslessly through a JSON
environment variable so the master can hand it to worker pods.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import Any, Dict, List, Optional


class DistributionStrategy:
    """Mirrors the reference's --distribution_strategy values.

    In the TPU rebuild both strategies compile to a single jitted step over a
    mesh; the difference is how *sparse* parameters are laid out:

    - ALLREDUCE: all params replicated, grads pmean'd over the ``dp`` axis.
    - PARAMETER_SERVER: embedding tables row-sharded over the mesh (the
      HBM-resident "parameter server"), dense params replicated + pmean.
      Lookups are collective (all_gather ids + reduce_scatter vectors)
      instead of the reference's gRPC pull/push.
    - LOCAL: single device, no collectives (reference's Local mode).
    """

    LOCAL = "Local"
    ALLREDUCE = "AllReduce"
    PARAMETER_SERVER = "ParameterServer"

    ALL = (LOCAL, ALLREDUCE, PARAMETER_SERVER)


@dataclasses.dataclass
class JobConfig:
    """All knobs for one training/evaluation/prediction job."""

    # --- model zoo contract (reference: --model_zoo / --model_def) ---
    model_zoo: str = "elasticdl_tpu.models"
    model_def: str = "mnist.model_spec"
    model_params: str = ""  # free-form "k=v;k=v" forwarded to the model fn

    # --- job identity / mode ---
    job_name: str = "elasticdl-job"
    job_type: str = "training"  # training | evaluation | prediction
    distribution_strategy: str = DistributionStrategy.ALLREDUCE

    # --- data (reference: --training_data / --validation_data etc.) ---
    training_data: str = ""
    validation_data: str = ""
    prediction_data: str = ""
    prediction_outputs: str = ""  # dir for predict-mode outputs (.npy per task)
    data_reader_params: str = ""
    # Decoded batches prepared ahead of the device step by a background
    # thread (data/prefetch.py) — the tf.data-pipeline role of the
    # reference's ingest (SURVEY §2 #14).  0 disables (strict alternation:
    # decode, step, decode, ...); the default keeps the host decoding while
    # the TPU computes, bounding host memory at ``depth`` extra batches.
    prefetch_depth: int = 2
    # Whole-task fused dispatch: all of a task's full minibatches run as ONE
    # jitted lax.scan — one decode, one H2D transfer, one dispatch per task
    # (per-step dispatch costs ~half the step wall-clock on a
    # remote-attached chip; docs/perf.md).  Its own knob: r4 gated this on
    # ``prefetch_depth > 0``, so the data-pipeline debugging setting
    # ``--prefetch_depth=0`` silently reverted the worker to per-step
    # dispatch (VERDICT r4 Weak #4).  Off = per-step dispatch (per-step
    # metrics visibility, smaller transfers — a debugging mode).
    fused_task_scan: bool = True
    # Task-level pipelining (single-worker-process mode): overlap the
    # previous task's metrics fetch + report with this task's dispatched
    # steps.  Formerly also coupled to --prefetch_depth; same fix.
    task_pipelining: bool = True
    # Parallel ingest (r9, data/ingest_pool.py): a task's record range is
    # split into minibatch-aligned sub-chunks read+decoded concurrently on
    # a bounded thread pool (the C++ codec and recordio read release the
    # GIL), reassembled in order so the stacked batch is bit-identical to
    # the serial path.  0 = auto (host cores, capped at 4); 1 = serial
    # (the pre-r9 path, byte for byte).  Only engages on readers declaring
    # thread_safe_ranges.
    ingest_threads: int = 0
    # Prep-ahead pipeline depth: up to this many leased tasks have their
    # host half (read + decode + stack) in flight concurrently while
    # earlier tasks' device work streams.  1 = the r6 one-slot behavior.
    # Each in-flight prep holds one task's stacked host batch in memory.
    prep_depth: int = 2
    # Batched task leases: GetTask/GetGroupTask may hand out up to this
    # many tasks per RPC (one control-plane RTT amortized over the batch);
    # the worker buffers the extras locally and returns unstarted ones to
    # the master on preemption or membership change.  1 = one task per
    # RPC (the pre-r9 wire behavior).
    lease_batch: int = 4

    # --- schedule ---
    minibatch_size: int = 64
    num_epochs: int = 1
    num_minibatches_per_task: int = 8  # shard granularity, as in the reference
    max_steps: int = 0  # 0 = until tasks exhausted
    evaluation_steps: int = 0  # 0 = eval at epoch end only
    learning_rate: float = 1e-3

    # --- cluster shape ---
    # (The reference's --use_tpu flag is intentionally absent: the platform
    # comes from the environment/driver, so the flag could not change
    # behavior here, and dead flags lie.)
    num_workers: int = 1
    # PS pods for the HOST tier (ps/service.py): 0 = host-tier tables live in
    # an in-process store on the (single) worker host; n > 0 = the master
    # launches n PS service pods and every table partitions by id mod n
    # across them — required for host-tier tables on multi-process meshes.
    # Mesh-sharded (HBM) tables never use PS pods; they shard over the whole
    # mesh by construction (ops/embedding.py).
    num_ps_pods: int = 0
    # Async parameter-server mode (the reference's --use_async): host-tier
    # row pulls for the next minibatch overlap the in-flight device step,
    # reading rows one un-applied push stale (bounded staleness 1).  False =
    # sync-by-version (every pull sees every prior push).  Only host-tier
    # tables are affected: mesh-sharded tables and dense params live inside
    # the jitted step and are always exact.
    use_async: bool = False
    # Staleness bound for --use_async: up to this many steps' host-tier
    # pushes may be outstanding when a pull happens (1 = the classic
    # async-PS window).  Deeper bounds hide more host RPC latency behind
    # device steps at the cost of staler rows; tools/async_depth_bench.py
    # measures the trade.  Three on-chip sweeps (artifacts/
    # async_depth_r05.json carries the latest, with its link probe;
    # chip_battery_r05*.log hold the other two): async reliably beats sync
    # (+10-30%) but the 1-vs-2-vs-4 ranking flips run to run on the
    # tunnel's bimodal wire — no reproducible win past the classic window,
    # so the default stays at the least-stale depth.
    async_staleness: int = 1
    # host:port list of the PS shards, comma-separated, in shard order.  Set
    # by the master onto the worker pod env; settable by hand to point
    # workers at an externally managed PS fleet.
    ps_addresses: str = ""
    # How the master launches workers: "process" (local subprocesses),
    # "kubernetes" (GKE TPU pods), or "fake" (tests).  The reference's
    # equivalent choice is implicit in running on k8s at all.
    pod_backend: str = "process"
    worker_image: str = "elasticdl-tpu:latest"  # pod image (kubernetes backend)
    namespace: str = "default"
    # Host workers use to reach the master service.  Empty = auto: localhost
    # for local backends, this pod's IP (MY_POD_IP downward API) or FQDN for
    # the kubernetes backend.
    master_advertise_host: str = ""
    # Multi-host: workers advertise their host and join a jax.distributed
    # world (rank 0 hosts the coordination service on this port) so one mesh
    # spans every worker's chips.  Leave False for single-host jobs.
    multihost: bool = False
    coordinator_port: int = 8476
    # jax.distributed coordination-service peer-death detection bound.
    # Governs how long a survivor blocked in a collective on a dead peer
    # waits before aborting into the RESTART/re-join path (JAX's own
    # default is 100 s — measured 83 s of a 99 s re-rendezvous).  30 s
    # tolerates heartbeat starvation on oversubscribed hosts; dedicated TPU
    # hosts can drop to 10 s (25.7 s total re-rendezvous, docs/perf.md).
    distributed_heartbeat_timeout_s: float = 30.0
    # Master->survivor death push: the liveness-heartbeat thread polls the
    # master's membership, and when a gang peer has DEPARTED while the main
    # thread stays wedged in a blocked collective for this grace window, the
    # process force-exits RESTART immediately instead of waiting out
    # --distributed_heartbeat_timeout_s (the avoidable middle of the r4
    # 25.7 s re-rendezvous; Worker.death_watch_tick documents the exact
    # conditions).  <= 0 disables the push.  1.5 s: long enough for an
    # unblocked main thread to hit its per-task membership check first,
    # short enough to beat the coordination-heartbeat abort by 25x.
    death_push_grace_s: float = 1.5
    # Hierarchical mesh (parallel/mesh.py): > 1 builds a 2-D (dp, ep) mesh
    # whose outer dp axis strides across hosts/slices — gradient psums ride
    # DCN, but embedding tables shard over the inner ep axis so the
    # latency-sensitive ragged all-to-all stays on ICI within a slice.
    # 1 (default) keeps the flat 1-D mesh.  Must divide the device count
    # (elastic resizes that break divisibility fall back to 1-D).
    dcn_data_parallelism: int = 1
    # Hybrid-parallel mesh (r20, parallel/mesh.py): > 1 builds the 2-D
    # (dp, tp) mesh — models declaring a tensor_sharding plan split their
    # weight matrices over the inner tp axis (Megatron column/row splits)
    # and the batch shards over the outer dp axis.  This is the CONFIGURED
    # tensor-parallel degree; elastic reform resolves the legal shape for
    # the live device count (resolve_2d_shape: dp shrinks first, tp only
    # degrades along its divisor chain when fewer than tp devices remain).
    # Mutually exclusive with dcn_data_parallelism > 1.
    tensor_parallelism: int = 1

    # --- collectives (r15, parallel/collectives.py — graftreduce) ---
    # How gradient/metric reductions run over the data-parallel axis:
    #   flat         — one all-replica collective per reduction (pre-r15);
    #   hierarchical — big leaves reduce intra-host first (reduce-scatter
    #                  over the cheap hop), then inter-host over the
    #                  1/n_local residue, then re-gather locally — cutting
    #                  inter-host bytes by the local fan-in.  Falls back
    #                  to flat when the mesh presents no (host, local)
    #                  factorization (single host and no
    #                  --collective_local_size override);
    #   auto         — hierarchical exactly when the mesh's real process
    #                  grouping (or the override) factors the axis.
    # Flat-vs-hierarchical parity is float reduction order only
    # (artifacts/COLLECT_r15.json stamps the probe).
    collective: str = "auto"
    # Pin (or, on the CPU harness, emulate) the intra-host fan-in: how
    # many consecutive positions of the dp axis count as one host's
    # local group.  0 = derive from the mesh's process grouping
    # (parallel/mesh.dp_factorization).  Must divide the axis size.
    collective_local_size: int = 0
    # Leaves smaller than this many elements always reduce with ONE flat
    # collective — a scalar's three hierarchical launches cost more than
    # the inter-host bytes they save.
    collective_min_elems: int = 4096
    # In-step (in-collective) straggler deadline, milliseconds.  > 0 arms
    # the worker's collective gate (single-process meshes): each dp
    # shard's host-side contribution must be ready within this bound or
    # the step dispatches WITHOUT it — the shard's weight in the
    # subgroup mask drops to 0, every mean renormalizes over the
    # survivors (sum/|G'|), and the exclusion is charged against the
    # same bounded skip accounting as the r13 task-boundary deadline
    # (gang_skip_budget consecutive exclusions of one shard escalate to
    # waiting it out, so a dead contributor surfaces as a visible stall,
    # never silent data loss).  The exclusion mask is an INPUT to the
    # jitted step: changing the excluded set never recompiles.  0 =
    # disabled (a stalled contributor blocks the dispatch, pre-r15).
    collective_deadline_ms: float = 0.0

    # --- elasticity ---
    relaunch_on_worker_failure: bool = True
    max_worker_relaunch: int = 3
    # Process backend only: keep one pre-booted spare worker parked (python
    # + jax + framework imports already paid, ~13 s here) that a relaunch
    # adopts by writing its worker id to a go-file — the boot-tail half of
    # the re-rendezvous cut (docs/perf.md).  Costs one idle interpreter's
    # memory; off by default.
    warm_worker_standby: bool = False

    # --- checkpoint (reference: --checkpoint_steps / --checkpoint_dir) ---
    checkpoint_steps: int = 0
    checkpoint_dir: str = ""
    keep_checkpoint_max: int = 3

    # --- master / control plane ---
    master_addr: str = ""  # host:port of the master gRPC service
    # Port the master gRPC service binds (0 = ephemeral).  A FIXED port is
    # what makes a master restart a blip instead of a job failure (r18):
    # workers ride out the outage re-dialing the address they already
    # hold, so the relaunched master must answer at the same one.
    master_port: int = 0
    # Per-call deadline on every worker->master RPC (RpcMasterProxy).  Was
    # a hardcoded 60 s before r18; jobs with huge trace envelopes or slow
    # control planes tune it here.
    master_call_timeout_s: float = 60.0
    # Master-outage ride-through budget (r18): on a transport-level
    # failure (UNAVAILABLE — the master is down/restarting) the worker's
    # proxy retries the call under the shared exponential-backoff-with-
    # jitter helper for up to this many seconds of outage, holding its
    # buffered leases and in-flight prep, then re-registers + reconciles
    # when the master answers again.  Exceeding the budget is a terminal
    # error (the task loop fails loud).  0 disables the ride-through
    # (pre-r18 behavior: first UNAVAILABLE surfaces immediately).
    master_outage_tolerance_s: float = 120.0
    task_timeout_s: float = 600.0
    # How long the master waits after the job finishes for workers to exit on
    # their own (they are writing final checkpoints — orbax + host-tier
    # snapshots); the teardown then proceeds regardless.  Raise for jobs
    # whose final snapshot is large.
    shutdown_grace_s: float = 120.0

    # --- observability ---
    log_level: str = "INFO"
    # grafttrace (common/trace.py): per-process span recorder for the
    # cross-process structured trace.  Workers emit spans for every
    # PhaseTimers phase, RPC boundary, gang wait and elastic transition,
    # ship bounded slices to the master on the heartbeat/report channel,
    # and tools/trace_dump.py merges a live job's buffers into one
    # Perfetto-loadable file (docs/observability.md).  Off by default;
    # measured overhead on the ingest bench is <2% (artifacts/
    # TRACE_r12.json), so flipping it on a production job is safe.
    trace: bool = False
    # Ring capacity (events) of the per-process trace buffer; oldest events
    # are overwritten, so the buffer always holds the most recent window.
    trace_buffer_events: int = 65536
    # graftgauge (r14, common/gauge.py + common/metrics_http.py): every
    # process of the job — master, workers, PS shards — serves a live
    # Prometheus-text /metrics (+ /healthz JSON) scrape endpoint when
    # this is >= 0.  0 = bind an ephemeral port (the only collision-safe
    # choice on a shared config bus: two workers on one host cannot
    # share a fixed port) — each process logs its bound address as a
    # "[graftgauge] serving /metrics on ..." pod-log line, the same
    # discovery channel the chaos bench uses for its audit lines.  > 0 =
    # bind exactly that port (single-process-per-host deployments).
    # -1 (default) = no endpoint; the registry still records (its cost
    # is the point: one leaf-lock add per update, measured on the ingest
    # A/B harness — docs/observability.md), so flipping the endpoint on
    # is purely additive.
    gauge_port: int = -1
    profile_dir: str = ""  # worker: jax.profiler trace of one training task
    metrics_dir: str = ""  # master: JSONL + TensorBoard scalar stream
    # Process backend: capture each worker pod's stdout+stderr to
    # {pod_log_dir}/{pod-name}.log (the local analog of kubectl logs; pod
    # names are unique per incarnation, so one file per life).  "" =
    # inherit the master's stdio.
    pod_log_dir: str = ""
    # Spares kept parked when --warm_worker_standby: 1 covers a lone
    # relaunch; a peer-death recovery relaunches TWO processes (the dead
    # pod + the survivor's RESTART), so multihost fleets that want the
    # whole recovery warm use 2.  Each spare holds one idle interpreter.
    standby_pool: int = 1

    # --- tail tolerance / fault injection (r13, chaos/inject.py) ---
    # graftchaos plan: scheduled faults (kill rank-k at step N, stall a
    # prep, drop/delay a master RPC, delay a PS pull) delivered through
    # no-op-when-disabled hook points in the worker, the RPC client and
    # the PS service — docs/robustness.md documents the plan grammar.
    # Rides the config bus so worker/PS pods inherit it; the GRAFT_CHAOS
    # env var arms processes the bus does not reach.  "" = disabled
    # (bit-exact no-op: one attribute check per hook crossing).
    chaos: str = ""
    # Deadline-bounded gang boundary (master-side, lockstep mode only):
    # when a rank lags the gang's newest lockstep seq by more than this
    # many milliseconds, the master SKIPS the straggler — its in-flight
    # gang tasks requeue with bounded skip accounting (gang_skip_budget)
    # and the rank is evicted so the gang re-forms without waiting out
    # the full task/heartbeat timeouts (OptiReduce's timeout-bounded
    # collective, done at the boundary this architecture owns).  The
    # evicted rank restarts and rejoins the next reform; nothing is
    # trained twice or lost (dispatcher skip accounting, proven by
    # test).  0 = disabled (the pre-r13 wait-forever boundary).
    gang_deadline_ms: float = 0.0
    # How many times one task may be deadline-skipped before a further
    # skip is charged like a FAILURE (retry budget -> poison-abandon): a
    # shard that deterministically stalls a rank must not ping-pong the
    # gang through skip-reform cycles forever.
    gang_skip_budget: int = 2

    # --- optimizer state layout (parallel/trainer.py) ---
    # ZeRO-style cross-replica sharding of the optimizer update: every
    # param-shaped optimizer-state leaf for a REPLICATED (dense) param is
    # partitioned over the data-parallel mesh axis (flattened and
    # zero-padded to divisibility), the train step reduce-scatters dense
    # grads, applies the optax update on each replica's 1/dp shard only,
    # and all-gathers the fresh params — all inside the one jitted XLA
    # program.  Cuts per-replica optimizer HBM by ~dp and removes the
    # redundant full weight update every replica used to compute
    # ("Automatic Cross-Replica Sharding of Weight Update", PAPERS.md).
    #   replicated — every replica holds full state (pre-r11 behavior);
    #   sharded    — always shard (dp > 1 meshes; dp == 1 is a no-op);
    #   auto       — shard when the replicated dense optimizer state would
    #                exceed --optimizer_sharding_auto_mb per replica.
    # Mesh-sharded embedding tables are unaffected either way: their
    # optimizer slots already co-shard with the table rows.  Checkpoints
    # are written in the canonical (unsharded) layout in every mode, so
    # they restore into any world size and either mode.
    optimizer_sharding: str = "replicated"
    optimizer_sharding_auto_mb: float = 64.0
    # Donate the train-state buffers into the jitted train step so XLA
    # reuses them for the output state (halves peak state memory; the
    # donated-input discipline TrainLoopError documents).  Off = a
    # debugging mode: failed steps keep their input state alive at the
    # cost of a second resident copy.
    donate_train_state: bool = True

    # --- precision ---
    compute_dtype: str = "bfloat16"  # MXU-native; params stay f32

    # --- sharded embedding lookup route (ops.embedding) ---
    # auto = ragged all-to-all on TPU meshes, dense (all_gather+psum_scatter)
    # on CPU; ragged_emulated exists for CPU tests of the ragged routing.
    embedding_lookup_impl: str = "auto"

    def validate(self) -> None:
        if self.distribution_strategy not in DistributionStrategy.ALL:
            raise ValueError(
                f"--distribution_strategy must be one of "
                f"{DistributionStrategy.ALL}, got {self.distribution_strategy!r}"
            )
        if self.minibatch_size <= 0:
            raise ValueError("--minibatch_size must be positive")
        if self.num_minibatches_per_task <= 0:
            raise ValueError("--num_minibatches_per_task must be positive")
        if self.job_type not in ("training", "evaluation", "prediction"):
            raise ValueError(f"unknown job_type {self.job_type!r}")
        if self.pod_backend not in ("process", "kubernetes", "fake"):
            raise ValueError(
                f"--pod_backend must be process|kubernetes|fake, got "
                f"{self.pod_backend!r}"
            )
        if self.num_ps_pods < 0:
            raise ValueError("--num_ps_pods cannot be negative")
        if self.prefetch_depth < 0:
            raise ValueError("--prefetch_depth cannot be negative")
        if self.ingest_threads < 0:
            raise ValueError("--ingest_threads cannot be negative (0 = auto)")
        if self.prep_depth < 1:
            raise ValueError("--prep_depth must be >= 1")
        if self.lease_batch < 1:
            raise ValueError("--lease_batch must be >= 1")
        if self.async_staleness < 1:
            raise ValueError("--async_staleness must be >= 1")
        if self.dcn_data_parallelism < 1:
            raise ValueError("--dcn_data_parallelism must be >= 1")
        if self.tensor_parallelism < 1:
            raise ValueError("--tensor_parallelism must be >= 1")
        if self.tensor_parallelism > 1 and self.dcn_data_parallelism > 1:
            raise ValueError(
                "--tensor_parallelism and --dcn_data_parallelism are "
                "mutually exclusive (no 3-D mesh)"
            )
        # Kept in sync with parallel.collectives.MODES (asserted by
        # tests); not imported from there so this module stays jax-free.
        if self.collective not in ("flat", "hierarchical", "auto"):
            raise ValueError(
                f"--collective must be flat|hierarchical|auto, got "
                f"{self.collective!r}"
            )
        if self.collective_local_size < 0:
            raise ValueError(
                "--collective_local_size cannot be negative (0 = derive "
                "from the mesh's process grouping)"
            )
        if self.collective_min_elems < 1:
            raise ValueError("--collective_min_elems must be >= 1")
        if self.collective_deadline_ms < 0:
            raise ValueError("--collective_deadline_ms cannot be negative")
        if self.optimizer_sharding not in ("replicated", "sharded", "auto"):
            raise ValueError(
                f"--optimizer_sharding must be replicated|sharded|auto, got "
                f"{self.optimizer_sharding!r}"
            )
        if self.optimizer_sharding_auto_mb <= 0:
            raise ValueError("--optimizer_sharding_auto_mb must be positive")
        if self.trace_buffer_events < 1:
            raise ValueError("--trace_buffer_events must be >= 1")
        if self.gauge_port < -1:
            raise ValueError(
                "--gauge_port must be -1 (off), 0 (ephemeral) or a port"
            )
        if self.chaos:
            # Parse-validate HERE (jax-free, stdlib): a typo'd fault plan
            # must fail the job submission, not silently never fire and
            # let a chaos run report tolerance it never exercised.
            from elasticdl_tpu.chaos.inject import parse_plan

            parse_plan(self.chaos)
        if self.master_port < 0:
            raise ValueError("--master_port must be 0 (ephemeral) or a port")
        if self.master_call_timeout_s <= 0:
            raise ValueError("--master_call_timeout_s must be positive")
        if self.master_outage_tolerance_s < 0:
            raise ValueError(
                "--master_outage_tolerance_s cannot be negative (0 = no "
                "ride-through)"
            )
        if self.gang_deadline_ms < 0:
            raise ValueError("--gang_deadline_ms cannot be negative")
        if self.gang_skip_budget < 0:
            raise ValueError("--gang_skip_budget cannot be negative")
        # Kept in sync with ops.embedding.LOOKUP_IMPLS (asserted by tests);
        # not imported from there so this module stays jax-free (the master
        # control plane and pod manager must run without jax).
        impls = ("auto", "ragged", "ragged_emulated", "dense")
        if self.embedding_lookup_impl not in impls:
            raise ValueError(
                f"--embedding_lookup_impl must be one of {impls}, got "
                f"{self.embedding_lookup_impl!r}"
            )

    # -- serialization: the config bus between master and worker pods --

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, payload: str) -> "JobConfig":
        raw = json.loads(payload)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def to_env(self) -> Dict[str, str]:
        return {"ELASTICDL_JOB_CONFIG": self.to_json()}

    @classmethod
    def from_env(cls, environ: Optional[Dict[str, str]] = None) -> "JobConfig":
        environ = os.environ if environ is None else environ
        payload = environ.get("ELASTICDL_JOB_CONFIG")
        if not payload:
            raise KeyError("ELASTICDL_JOB_CONFIG not set")
        return cls.from_json(payload)

    def parsed_model_params(self) -> Dict[str, Any]:
        return _parse_kv_string(self.model_params)

    def parsed_data_reader_params(self) -> Dict[str, Any]:
        return _parse_kv_string(self.data_reader_params)


def _parse_kv_string(spec: str) -> Dict[str, Any]:
    """Parse the reference-style "key=value;key=value" param strings."""
    out: Dict[str, Any] = {}
    for item in filter(None, (s.strip() for s in spec.split(";"))):
        if "=" not in item:
            raise ValueError(f"malformed param {item!r}, expected key=value")
        key, value = item.split("=", 1)
        try:
            out[key.strip()] = json.loads(value)
        except json.JSONDecodeError:
            out[key.strip()] = value.strip()
    return out


def build_arg_parser() -> argparse.ArgumentParser:
    """Argparse surface mirroring the reference client's flag names."""
    parser = argparse.ArgumentParser(prog="elasticdl", add_help=True)
    for field in dataclasses.fields(JobConfig):
        flag = "--" + field.name
        if field.type == "bool" or isinstance(field.default, bool):
            parser.add_argument(
                flag,
                type=lambda v: str(v).lower() in ("1", "true", "yes"),
                default=field.default,
            )
        else:
            parser.add_argument(flag, type=type(field.default), default=field.default)
    return parser


def parse_args(argv: Optional[List[str]] = None) -> JobConfig:
    namespace = build_arg_parser().parse_args(argv)
    config = JobConfig(**vars(namespace))
    config.validate()
    return config
