"""Checkpoint save/restore on Orbax.

Reference parity (SURVEY.md §2 #18, §5 [U]): the reference snapshots the
model every ``--checkpoint_steps`` (PS shards dump their slices; in AllReduce
mode worker-0 saves) and restores on restart — checkpoint restore is also how
an elastically re-formed job resumes.  Here Orbax saves the full TrainState
pytree — including mesh-sharded embedding tables, which Orbax reads/writes
per-shard from each device's HBM — and restores it **into any mesh shape**,
which is exactly the elastic 4->8->4 path: the checkpoint is
topology-agnostic, the restore target's shardings belong to the new mesh.

Format contract (r11): optimizer state is ALWAYS stored in the CANONICAL
layout — param-shaped leaves, never the flat dp-sharded layout of
``--optimizer_sharding`` — because the flat layout's global shapes depend
on the world size that wrote them.  Writers go through
``Trainer.host_state`` (or the jitted ``Trainer.snapshot_state`` for
group-mode collective saves); readers restore through
``Trainer.restore_template`` / ``adopt_restored``, which re-shard the
canonical leaves into whatever layout the live mesh runs.  This is what
lets a checkpoint written by a 4-way sharded job restore into an 8-way or
replicated one (tests/test_elastic.py).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

from elasticdl_tpu.common import durable, trace
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("checkpoint")

#: The published-checkpoint manifest: a tiny JSON file next to the Orbax
#: step dirs naming the newest step whose save (dense state AND host-store
#: shards) is COMPLETE.  The serving tier's checkpoint watcher keys off this
#: file — never off directory listings, which show steps mid-write.
MANIFEST_NAME = "checkpoint_manifest.json"  # durable-file


def publish_manifest(
    directory: str,
    step: int,
    code_rev: str = "",
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Atomically publish ``step`` as the newest complete checkpoint.

    The durable.atomic_publish commit: a reader (the serving watcher,
    possibly in another process) sees either the previous manifest or the
    new one, never a half-written file.  The caller must only publish
    AFTER the checkpoint itself is fully committed (Orbax wait + host-store
    snapshot): the manifest is the happens-after edge serving relies on.
    """
    path = os.path.join(directory, MANIFEST_NAME)
    payload = {
        "step": int(step),
        "code_rev": code_rev,
        "published_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if extra:
        payload.update(extra)
    durable.atomic_publish_json(path, payload)
    # The publish is the training->serving hand-off edge: its instant in
    # the merged trace is what publish-to-live latency is measured between
    # (pairs with the watcher's serving:hot_reload instant).
    trace.instant("ckpt:publish", cat="elastic", step=int(step))
    return path


# recovery-path
def read_manifest(directory: str) -> Optional[Dict[str, Any]]:
    """The published manifest, or None when absent/unreadable.  Tolerant by
    design (durable.read_json_tolerant): a missing or garbage manifest
    means "nothing published yet", not an error — fresh checkpoint dirs
    and pre-manifest checkpoints both look that way."""
    path = os.path.join(directory, MANIFEST_NAME)
    m = durable.read_json_tolerant(path)
    if not isinstance(m, dict) or not isinstance(m.get("step"), int):
        return None
    return m


class CheckpointManager:
    def __init__(self, directory: str, keep_max: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_max, create=True, enable_async_checkpointing=True
            ),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        """Async snapshot (training continues while Orbax writes)."""
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the sharding/structure of ``state_like`` (an abstract
        or concrete TrainState whose arrays carry the TARGET mesh's
        shardings — this is what makes restore-into-new-topology work)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if hasattr(x, "sharding")
            else jax.ShapeDtypeStruct(x.shape, x.dtype),
            state_like,
        )
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def publish(
        self,
        step: int,
        code_rev: str = "",
        extra: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Publish ``step`` for online consumers (the serving watcher) —
        AFTER draining any in-flight async save, so the manifest can never
        name a step Orbax has not finished committing.  Host-store snapshots
        must already be on disk when this is called (the worker save paths
        order it last)."""
        self._mgr.wait_until_finished()
        return publish_manifest(self.directory, step, code_rev=code_rev, extra=extra)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Retained checkpoint steps, newest first (torn-checkpoint fallback
        walks these until one restores completely)."""
        return sorted(self._mgr.all_steps(), reverse=True)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
