"""Checkpoint save/restore on Orbax.

Reference parity (SURVEY.md §2 #18, §5 [U]): the reference snapshots the
model every ``--checkpoint_steps`` (PS shards dump their slices; in AllReduce
mode worker-0 saves) and restores on restart — checkpoint restore is also how
an elastically re-formed job resumes.  Here Orbax saves the full TrainState
pytree — including mesh-sharded embedding tables, which Orbax reads/writes
per-shard from each device's HBM — and restores it **into any mesh shape**,
which is exactly the elastic 4->8->4 path: the checkpoint is
topology-agnostic, the restore target's shardings belong to the new mesh.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("checkpoint")


class CheckpointManager:
    def __init__(self, directory: str, keep_max: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_max, create=True, enable_async_checkpointing=True
            ),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        """Async snapshot (training continues while Orbax writes)."""
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the sharding/structure of ``state_like`` (an abstract
        or concrete TrainState whose arrays carry the TARGET mesh's
        shardings — this is what makes restore-into-new-topology work)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if hasattr(x, "sharding")
            else jax.ShapeDtypeStruct(x.shape, x.dtype),
            state_like,
        )
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Retained checkpoint steps, newest first (torn-checkpoint fallback
        walks these until one restores completely)."""
        return sorted(self._mgr.all_steps(), reverse=True)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
