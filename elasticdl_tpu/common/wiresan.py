"""wiresan — wire-schema sanitizer for the JSON-RPC control plane
(GRAFT_WIRESAN).

The dynamic twin of graftlint v8's wire-discipline / wire-evolution
passes, in the locksan / racesan / jitsan / crashsan stance: the static
passes prove every sender payload and receiver field access matches the
``MessageSchema`` tables in ``common/rpc.py``; this module proves the
MESSAGES THEMSELVES match at runtime, on BOTH ends of the wire.  Armed
(GRAFT_WIRESAN=1, tier-1-wide via conftest), every request AND response
crossing ``JsonRpcClient.call`` / ``make_generic_handler`` is validated
against its method's schema — until r22 only master requests were
checked, so a master returning a malformed response surfaced as a
KeyError deep inside the worker's task loop instead of at the boundary.

Violation grammar (the validate_message contract):

- a missing REQUIRED field, or a required/optional field of the wrong
  type, raises :class:`WireSanViolation` deterministically — a schema
  bug must fail the test that exercises it, not corrupt downstream
  state;
- an UNKNOWN field is counted per method into the stats this module
  serves (``edl_wire_unknown_fields_total{method=}`` via
  ``gauge.install_wire_collector``), never raised: unknown fields are
  the additive-compat mechanism itself (proto3 unknown-field stance —
  a NEWER peer's extra fields must pass through old code unharmed), so
  the right response is visibility, not rejection.

Version mask (``GRAFT_WIRESAN_MASK=<rev>`` or :func:`set_mask`): emulate
an OLD peer by stripping every field whose ``MessageSchema.since``
revision is newer than ``rev`` from outgoing requests and incoming
responses — the client behaves exactly like a peer built at revision
``rev``, which is how tools/wire_skew.py proves a v1-masked worker
completes a real gRPC job against a current master with zero errors and
zero double-trains (the additive-compat proof stamped into the LINT
artifact).  Masking requires the sanitizer armed: a mask with
GRAFT_WIRESAN off would silently strip nothing, so it fails loud
instead (the crashsan arm stance).

Cost contract: disabled, each hook is one ``os.environ`` read (the
crashsan pattern); the control-plane calls it guards already pay a JSON
serialization, so the armed cost (one dict scan per message) is noise.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional


class WireSanViolation(AssertionError):
    """A message violated its method's declared wire schema."""


class WireSanError(AssertionError):
    """Misuse of the sanitizer itself (mask armed while disabled)."""


_lock = threading.Lock()  # lock-order: leaf
_unknown: Dict[str, int] = {}  # guarded-by: _lock
_violations = 0  # guarded-by: _lock
_mask_override: Optional[int] = None  # guarded-by: _lock


def enabled() -> bool:
    return os.environ.get("GRAFT_WIRESAN") == "1"


def active() -> bool:
    """True when any hook should run: armed, or a mask is requested (the
    latter without arming fails loud inside :func:`mask_rev`)."""
    return enabled() or bool(os.environ.get("GRAFT_WIRESAN_MASK")) or (
        _mask_override is not None
    )


def mask_rev() -> Optional[int]:
    """The active version mask (None = no mask).  :func:`set_mask` wins
    over the env var — a test overriding the suite-wide env must not
    need to mutate os.environ."""
    with _lock:
        override = _mask_override
    if override is None:
        raw = os.environ.get("GRAFT_WIRESAN_MASK", "")
        if not raw:
            return None
        override = int(raw)
    if not enabled():
        # Fail LOUD: a masked run with the sanitizer off would strip
        # nothing and "pass" by testing the current protocol.
        raise WireSanError("GRAFT_WIRESAN=1 required to arm the version mask")
    return override


def set_mask(rev: Optional[int]) -> None:
    """Arm (or with None clear) the version mask for this process."""
    global _mask_override
    if rev is not None and not enabled():
        raise WireSanError("GRAFT_WIRESAN=1 required to arm the version mask")
    with _lock:
        _mask_override = None if rev is None else int(rev)


def reset() -> None:
    """Forget counters and the mask override (test isolation)."""
    global _violations, _mask_override
    with _lock:
        _unknown.clear()
        _violations = 0
        _mask_override = None


def stats() -> Dict[str, Any]:
    """``{"unknown_fields": {method: count}, "violations": n}`` — the
    surface the gauge collector and the LINT artifact read."""
    with _lock:
        return {"unknown_fields": dict(_unknown), "violations": _violations}


def _type_ok(value: Any, types: tuple) -> bool:
    # bool subclasses int: reject it for int/float fields (the
    # validate_message stance — {"step": true} must not read as step 1).
    if isinstance(value, bool):
        return bool in types
    return isinstance(value, types)


def check(method: str, msg: Any, schemas: Optional[dict], direction: str) -> None:
    """Validate ``msg`` against ``schemas[method]`` and count unknown
    fields.  Methods outside the table (the PS tier's binary frames) and
    absent tables pass through unjudged — wiresan only enforces contracts
    that are DECLARED."""
    global _violations
    schema = schemas.get(method) if schemas else None
    if schema is None:
        return
    problems = []
    if not isinstance(msg, dict):
        problems.append(f"must be an object, got {type(msg).__name__}")
    else:
        for field, types in schema.required.items():
            if field not in msg:
                problems.append(f"missing required field {field!r}")
            elif not _type_ok(msg[field], types):
                problems.append(
                    f"field {field!r} must be "
                    f"{'/'.join(t.__name__ for t in types)}, "
                    f"got {type(msg[field]).__name__}"
                )
        for field, types in schema.optional.items():
            if (
                field in msg and msg[field] is not None
                and not _type_ok(msg[field], types)
            ):
                problems.append(
                    f"field {field!r} must be "
                    f"{'/'.join(t.__name__ for t in types)}, "
                    f"got {type(msg[field]).__name__}"
                )
        unknown = sum(
            1 for k in msg
            if k not in schema.required and k not in schema.optional
        )
        if unknown:
            with _lock:
                _unknown[method] = _unknown.get(method, 0) + unknown
    if problems:
        with _lock:
            _violations += 1
        raise WireSanViolation(f"{direction} {method}: " + "; ".join(problems))


def mask(method: str, msg: Any, schemas: Optional[dict], rev: int) -> Any:
    """``msg`` as a peer built at wire revision ``rev`` would see it:
    every field newer than ``rev`` (per ``MessageSchema.since``) removed.
    Returns ``msg`` itself when nothing strips (no copy on the fast
    path)."""
    schema = schemas.get(method) if schemas else None
    if schema is None or not isinstance(msg, dict) or not schema.since:
        return msg
    drop = {f for f, r in schema.since.items() if r > rev}
    if not drop or not any(f in msg for f in drop):
        return msg
    return {k: v for k, v in msg.items() if k not in drop}
