"""durable — the ONE home for the repo's durable-write shapes (r21).

r18's master WAL took six review rounds to get crash-consistent — torn
mid-file lines, a membership record that could land in neither base nor
WAL, thread-colliding registry temp names, short ``os.write`` tears — and
every one of those was an instance of two write shapes the repo already
carried in divergent copies (checkpoint manifest, pod registry, journal
rotation, artifact stamps).  This module is the canonical copy; graftlint
v7 (``analysis/durability.py``, rule ``durable-write-discipline``) makes
routing through it mandatory for any path derived from a ``# durable-file``
constant, and ``common/crashsan.py`` (GRAFT_CRASHSAN) proves each shape's
recovery contract by simulating real crashes at every op boundary.

The two write shapes, plus their read-side halves:

- :func:`atomic_publish` — whole-file commit: thread-unique temp
  (``.tmp<pid>.<tid>`` — a pid-only name lets two threads of one process
  interleave writes and rename corruption into place), write, fsync(file),
  ``os.replace``, fsync(directory) (a rename without the directory fsync
  can vanish with the dirent on power loss).  A reader sees the previous
  complete file or the new complete file, never a tear.
  :func:`atomic_replace` is the same commit for a temp some other code
  already wrote (PS host-store snapshots, dataset caches).
- :func:`open_append` + :func:`append_durable` — WAL append: ONE
  ``os.write`` on an O_APPEND fd (atomic at the file level — writers in
  different lock domains cannot interleave partial lines) then fsync; a
  short write raises :class:`ShortWriteError` LOUDLY instead of finishing
  the line (finishing would interleave with other writers; the caller
  fails the mutation and the record commits whole or not at all).
- :func:`read_wal` — the torn-tail-tolerant line reader (the r12
  MetricsWriter / r18 journal stance, one definition): a torn FINAL line
  is a crash tail and is tolerated (the event was never acknowledged);
  garbage MID-file is corruption and raises :class:`CorruptWalError`.
- :func:`read_json_tolerant` — the atomic-publish reader: a missing or
  unparseable file reads as ``default`` ("nothing published"), because a
  compliant publisher can never leave a tear — torn content only means a
  non-compliant writer or pre-publish state, both of which the documented
  fallback (docs/robustness.md "Durability contracts") covers.

Every op crosses :func:`crashsan.note_op` — the op log, test-armed crash
injection, AND the chaos plan's ``torn_write:file=<durable>,op=N`` faults
(synced into crashsan at ``chaos.configure`` time, so a REAL process dies
at a real durable-op boundary without this crossing ever taking the
injector's lock — see ``_crossing``).  Stdlib-only and jax-free: the
master control plane, the bench tools, and graftlint's artifact writer
all import this.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, List, Optional, Tuple, Union

from elasticdl_tpu.chaos import inject as chaos
from elasticdl_tpu.common import crashsan


class ShortWriteError(OSError):
    """A durable append's single ``os.write`` was cut short (signal
    mid-progress, disk full).  The caller must fail the mutation loudly —
    the torn prefix is on disk as a tolerated crash tail, and retrying
    the whole record keeps appends all-or-nothing."""


class CorruptWalError(ValueError):
    """Garbage MID-file in a WAL: corruption, not a crash tail.  Readers
    must fall back loudly (watermark, full replay), never replay a
    partial history as if it were whole."""


def tmp_path(path: str) -> str:
    """The thread-unique temp name for a publish of ``path``: pid AND
    thread id, because two threads of one process (pod-manager watcher vs
    scale(), worker checkpoint vs drain) can publish the same file
    concurrently and a shared temp name would interleave their writes."""
    return f"{path}.tmp{os.getpid()}.{threading.get_ident()}"


def _crossing(
    kind: str,
    path: str,
    *,
    fd: Optional[int] = None,
    data: Optional[bytes] = None,
    tmp: Optional[str] = None,
) -> None:
    """The injection crossing every durable op makes BEFORE touching disk:
    crashsan's op log, the chaos plan's torn_write faults (handed to
    crashsan at configure time — fired ones die for real via os._exit),
    and the test-armed crash_at countdown.  Deliberately NOT a
    ``chaos.hook`` call: durable ops fire under leaf-declared subsystem
    locks (journal appends under TaskDispatcher._lock) and the injector's
    locksan-wrapped lock must not be acquired there; crashsan's plain
    lock is the one leaf this crossing may take."""
    _file_op, armed, chaos_mode = crashsan.note_op(kind, path)
    if chaos_mode is not None:
        mode = chaos_mode or (
            "torn_append" if kind == "append" else "tmp_torn"
        )
        crashsan.simulate(
            kind, mode, path=path, fd=fd, data=data, tmp=tmp,
            die=chaos.CHAOS_KILL_EXIT_CODE,
        )
    if armed is not None:
        crashsan.simulate(kind, armed, path=path, fd=fd, data=data, tmp=tmp)


def atomic_publish(
    path: str, data: Union[bytes, str], *, fsync: bool = True
) -> str:
    """Commit ``data`` as the complete new content of ``path``.

    Thread-unique temp + write + fsync(file) + ``os.replace`` +
    fsync(directory): a concurrent reader (possibly another process) sees
    the previous complete file or this one, never a tear, and the commit
    survives power loss once this returns.  ``fsync=False`` exists for
    tests that measure everything but the disk."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = tmp_path(path)
    _crossing("publish", path, data=data, tmp=tmp)
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        n = os.write(fd, data)
        if n != len(data):
            raise ShortWriteError(
                f"short write ({n}/{len(data)} bytes) publishing {path}"
            )
        if fsync:
            os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(path)
    return path


def atomic_replace(tmp: str, path: str, *, fsync: bool = True) -> str:
    """The publish commit for a temp some other code already wrote (PS
    host-store snapshots via ``store.save(tmp)``, dataset caches): fsync
    the temp's content, rename, fsync the directory.  Callers name the
    temp via :func:`tmp_path` — thread-uniqueness is part of the shape."""
    _crossing("replace", path, tmp=tmp)
    if fsync:
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(path)
    return path


def atomic_publish_json(path: str, obj: Any, **dumps_kw: Any) -> str:
    """:func:`atomic_publish` of ``json.dumps(obj)`` — the shape every
    JSON durable (manifest, registry, watermark, artifacts) shares."""
    return atomic_publish(path, json.dumps(obj, **dumps_kw))


def open_append(path: str) -> int:
    """The WAL fd: O_APPEND so concurrent writers' single-write appends
    are atomic at the file level (no journal-level lock exists — every
    recording site holds its own subsystem lock)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    return os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)


def append_durable(
    fd: int, data: Union[bytes, str], *, fsync: bool = True, path: str = ""
) -> int:
    """Append one record: ONE ``os.write`` then fsync.  A short write
    raises :class:`ShortWriteError` — the caller fails the mutation (the
    worker retries the RPC; the record commits whole or not at all)
    rather than finishing the line and burying a tear mid-file.
    ``path`` labels the op for crashsan/chaos addressing."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    _crossing("append", path or f"fd{fd}", fd=fd, data=data)
    n = os.write(fd, data)
    if n != len(data):
        raise ShortWriteError(
            f"short durable append ({n}/{len(data)} bytes) to "
            f"{path or fd} — failing the mutation rather than burying a "
            "torn line mid-file"
        )
    if fsync:
        os.fsync(fd)
    return n


def read_wal(
    path: str, decode: Optional[Callable[[str], Any]] = json.loads
) -> Tuple[List[Any], bool]:
    """Parse an append-durable WAL into ``(records, torn_tail)``.

    The one torn-tail-tolerance definition (r12 metrics / r18 journal):
    a record that fails to ``decode`` is tolerated ONLY when nothing but
    whitespace follows it — a crash tail, never acknowledged to anyone.
    Anything unparseable earlier raises :class:`CorruptWalError`; callers
    fall back loudly.  ``decode=None`` yields raw ``bytes`` lines."""
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    records: List[Any] = []
    torn = False
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(
                decode(line.decode("utf-8")) if decode is not None else line
            )
        except (ValueError, UnicodeDecodeError) as e:
            if all(not rest.strip() for rest in lines[i + 1:]):
                torn = True
                break
            raise CorruptWalError(
                f"wal {path} corrupt at line {i + 1} (not a crash tail): {e}"
            ) from e
    return records, torn


def read_json_tolerant(path: str, default: Any = None) -> Any:
    """Read an atomically-published JSON file; absent or unparseable
    reads as ``default``.  Tolerant BY CONTRACT, not by sloppiness: a
    compliant :func:`atomic_publish` can never leave a tear, so garbage
    here means pre-publish state or a non-compliant writer — either way
    "nothing published", and the caller's documented fallback (full
    replay, fresh start, previous manifest) covers it."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return default
    try:
        return json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return default


def _fsync_dir(path: str) -> None:
    """fsync the parent directory so the rename's dirent survives power
    loss.  Best-effort: not every filesystem lets a directory be opened
    (or fsync'd) — degrading beats failing a commit that already renamed."""
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
