"""Runtime jit-compile sanitizer — the dynamic twin of graftlint's
jit-discipline passes (v6).

Every perf result of record assumes the jitted step compiles once and
never silently retraces: r15 pinned mask flips recompile-free, r11's
donation story assumes stable jit identity, and the serving tier promises
one compiled forward per padded batch shape.  The static passes
(``analysis/jit_discipline.py``) prove the LEXICAL picture — jit created
through the shim, bound once, no device->host materialization on the hot
path — but they cannot see a shape drift at runtime.  This module closes
that half, the locksan/racesan pattern:

- ``jax_compat.jit_compiled``/``jit_donating`` route through
  :func:`wrap` when ``GRAFT_JITSAN=1`` (tests/conftest.py arms it for
  the whole tier-1 suite).  Disabled, the wrappers return the PLAIN
  jitted function untouched — zero overhead, not even a shim frame.
- Armed, the to-be-jitted function is wrapped in a counting tracer:
  jax re-traces it exactly once per compile-cache miss, so each trace IS
  one lowering.  Counts aggregate per declared ``name=`` (the registry
  key) and per compiled-callable instance.
- A callable that lowers more times than its declared
  ``expected_variants=`` budget raises :class:`JitSanViolation` AT the
  triggering call — the silent throughput-halving retrace becomes a loud
  deterministic failure naming the site and its budget.
- Each lowering also emits a ``jit:compile`` trace instant
  (``common/trace.py`` ring — non-blocking, hot-path-legal) and the
  aggregate counts bridge into the gauge registry as
  ``edl_jit_compiles_total{fn=...}`` via
  ``gauge.install_jit_collector`` — an unexpected production retrace is
  visible in ``watch_job.py``, not just under tests.
- :func:`transfer_guard` optionally arms ``jax.transfer_guard`` around
  the worker's step dispatch (``GRAFT_JITSAN_TRANSFER_GUARD=1`` on top
  of ``GRAFT_JITSAN=1``): implicit device->host materializations inside
  the dispatch window fail loud while explicit spellings
  (``jax.device_put`` / ``jax.device_get``) stay legal — the runtime
  side of the static ``transfer-discipline`` rule's blind spots
  (values materialized through parameters, dynamic dispatch).

``GRAFT_JITSAN_DUMP=<path>`` writes the per-name stats as JSON at
process exit — ``tools/graftlint.py --artifact`` merges that file into
the LINT artifact so ``bench_regress.py`` can gate compile counts
against declared budgets across revisions.

Pure stdlib at import time (jax is imported only inside
:func:`transfer_guard` when armed): importable by gauge/watch tooling
that must never pay a backend init.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from typing import Callable, Dict, Optional

from elasticdl_tpu.common import trace

__all__ = [
    "JitSanViolation", "enabled", "transfer_guard_armed", "wrap",
    "stats", "compiles", "reset", "transfer_guard", "dump_stats",
]


class JitSanViolation(AssertionError):
    """A compiled callable lowered more times than its declared
    ``expected_variants`` budget.  Raised AT the re-tracing call, so a
    shape/dtype drift is a deterministic failure at the drifting site
    instead of a silent 2x step-time regression."""


def enabled() -> bool:
    return os.environ.get("GRAFT_JITSAN", "") == "1"


def transfer_guard_armed() -> bool:
    """Arm ``jax.transfer_guard`` around step dispatch too — opt-in on
    top of the counter (compilation itself may move constants, so the
    guard is a steady-state assertion the operator arms deliberately)."""
    return enabled() and os.environ.get(
        "GRAFT_JITSAN_TRANSFER_GUARD", ""
    ) == "1"


_lock = threading.Lock()
#: name -> {"compiles", "instances", "budget"}; process-global like
#: locksan's edge table — the budget contract is per declared site name.
_names: Dict[str, dict] = {}
_dump_registered = False


class _Site:
    """One registered compiled callable: its own lowering counter against
    its own budget (two structural variants of one ``name`` are separate
    instances; each may lower ``budget`` times)."""

    __slots__ = ("name", "budget", "lowerings")

    def __init__(self, name: str, budget: int):
        self.name = name
        self.budget = budget
        self.lowerings = 0


def _register(name: str, budget: int) -> _Site:
    global _dump_registered
    site = _Site(name, budget)
    with _lock:
        rec = _names.setdefault(
            name, {"compiles": 0, "instances": 0, "budget": 0}
        )
        rec["instances"] += 1
        rec["budget"] = max(rec["budget"], budget)
        if not _dump_registered and os.environ.get("GRAFT_JITSAN_DUMP"):
            _dump_registered = True
            atexit.register(dump_stats)
    return site


def _note_lowering(site: _Site) -> None:
    with _lock:
        site.lowerings += 1
        # setdefault: reset() may have cleared the aggregates while this
        # instance (and its budget) lives on in a caller's closure.
        rec = _names.setdefault(
            site.name, {"compiles": 0, "instances": 1, "budget": site.budget}
        )
        rec["compiles"] += 1
        n_site, n_total = site.lowerings, rec["compiles"]
    # Record BEFORE judging: the over-budget lowering must be visible in
    # the trace/gauges even when the raise below kills the step.
    trace.instant("jit:compile", cat="jit", fn=site.name, n=n_total)
    if n_site > site.budget:
        raise JitSanViolation(
            f"jitsan: {site.name!r} lowered {n_site} time(s) on one "
            f"compiled callable, past its declared expected_variants="
            f"{site.budget} — a shape/dtype/static-arg drift is retracing "
            "the step (every retrace pays a full XLA compile mid-run). "
            "Stabilize the drifting input, bucket the shapes, or raise "
            "the declared budget at the jit_compiled/jit_donating site "
            "(docs/static_analysis.md, v6)."
        )


def wrap(
    jit_factory: Callable,
    fun: Callable,
    *,
    name: Optional[str] = None,
    expected_variants: int = 1,
    jit_kwargs: Optional[dict] = None,
) -> Callable:
    """Jit ``fun`` through ``jit_factory`` with lowering accounting.

    ``jit_factory`` is passed in (``jax.jit``) rather than imported so
    this module stays jax-free at import time.  The counting wrapper
    rides INSIDE the jit: jax re-traces it once per compile-cache miss,
    which is exactly the lowering count — no private cache probing."""
    import functools

    site = _register(
        name or getattr(fun, "__name__", "<jit>"),
        max(1, int(expected_variants)),
    )

    @functools.wraps(fun)
    def counted(*args, **kwargs):
        _note_lowering(site)
        return fun(*args, **kwargs)

    return jit_factory(counted, **(jit_kwargs or {}))


def stats() -> Dict[str, dict]:
    """Per-name ``{"compiles", "instances", "budget"}`` — the gauge
    collector's and artifact dump's input."""
    with _lock:
        return {name: dict(rec) for name, rec in sorted(_names.items())}


def compiles(name: str) -> int:
    """Total lowerings recorded under ``name`` (0 when never registered)
    — what the recompile-free tests assert deltas over."""
    with _lock:
        rec = _names.get(name)
        return int(rec["compiles"]) if rec else 0


def reset() -> None:
    """Forget aggregate counts (test isolation).  Per-instance budgets on
    already-wrapped callables keep their own counters — the violation
    contract is an instance property, not an aggregate one."""
    with _lock:
        _names.clear()


def transfer_guard(level: str = "disallow", when: bool = True):
    """Context manager for the worker's step-dispatch window: armed
    (:func:`transfer_guard_armed`), implicit transfers raise inside it;
    disarmed, a ``nullcontext`` — the dispatch path pays one env check.

    ``when=False`` keeps the window open even when armed — the caller's
    escape hatch for dispatch paths with a LEGITIMATE implicit transfer
    inside (the worker's host-table push materializes sparse cotangents
    mid-window by design; the runtime guard has no per-line waiver, so
    the exemption is declared at the ``with`` site instead)."""
    if not when or not transfer_guard_armed():
        return contextlib.nullcontext()
    import jax

    return jax.transfer_guard(level)


def dump_stats(path: Optional[str] = None) -> Optional[str]:
    """Write :func:`stats` as JSON to ``path`` (default: the
    ``GRAFT_JITSAN_DUMP`` env var; registered atexit when it is set).
    Returns the path written, or None when there is nowhere to write."""
    path = path or os.environ.get("GRAFT_JITSAN_DUMP")
    if not path:
        return None
    payload = stats()
    # Provenance for consumers (graftlint --artifact): counts are only
    # meaningful for the code that produced them, and this module cannot
    # reach git — the wall-clock stamp lets the artifact writer compare
    # against HEAD's commit time and flag a stale dump.
    payload["_meta"] = {"utc_s": time.time()}
    from elasticdl_tpu.common import durable

    durable.atomic_publish(
        path, json.dumps(payload, indent=1, sort_keys=True) + "\n"
    )
    return path
