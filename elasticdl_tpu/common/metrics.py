"""Metrics writer — the master's structured observability sink.

Reference parity (SURVEY.md §5 "Metrics/logging/observability" [U — mount
empty at survey time]): the reference surfaces eval metrics via gRPC to the
master and optionally TensorBoard through Keras callbacks.  Here the master
appends every training/eval metric report to a JSONL stream (one
machine-parseable record per event, crash-safe append) and mirrors scalars
to TensorBoard when ``tensorboardX`` is importable.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, Optional

from elasticdl_tpu.common import locksan, trace
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("metrics")

#: The master's JSONL scalar stream under its metrics directory.  Durable
#: in the WAL-reader sense (torn-tail-tolerant reads via durable.read_wal)
#: but written ADVISORY: records are flushed, never fsync'd — losing the
#: page-cache tail of a metrics stream costs observability, not
#: correctness, and an fsync per scalar report would serialize the
#: master's report handlers on the disk.
METRICS_FILENAME = "metrics.jsonl"  # durable-file

#: Metric keys with this prefix carry HISTOGRAM vectors, not scalars.  They
#: flow through every aggregation layer (device psum, worker minibatch sums,
#: master cross-worker weighted means) unchanged in meaning — histograms are
#: linear, and the scalars derived from them (AUC) are scale-invariant, so
#: weighted MEANS aggregate as exactly as sums would.  ``finalize_metrics``
#: converts them to their scalar at the last step of each pipeline.
HIST_PREFIX = "__hist__"

#: The one histogram-derived metric so far: ROC AUC from score histograms
#: (the reference evaluates Criteo/DeepFM on AUC via TF's bucketed streaming
#: AUC — same construction).
AUC_POS = HIST_PREFIX + "auc_pos"
AUC_NEG = HIST_PREFIX + "auc_neg"


def auc_from_histograms(pos, neg) -> float:
    """ROC AUC from per-score-bucket positive/negative counts.

    Rank-statistic identity: AUC = P(score_pos > score_neg) + 0.5 *
    P(tie).  Bucketed: each positive in bucket b beats every negative in
    buckets < b and half-ties the negatives in bucket b.  Exact for scores
    quantized to the bucket grid; O(1/n_bins) bias otherwise — identical to
    TF's thresholded streaming AUC.  Degenerate sets (no positives or no
    negatives) return 0.5.
    """
    import numpy as np

    pos = np.asarray(pos, np.float64)
    neg = np.asarray(neg, np.float64)
    p, n = pos.sum(), neg.sum()
    if p <= 0 or n <= 0:
        return 0.5
    neg_below = np.concatenate([[0.0], np.cumsum(neg)[:-1]])
    wins = float(np.sum(pos * (neg_below + 0.5 * neg)))
    # Plain python float: np.float64 leaks would crash json.dumps on the
    # gRPC JobStatus / metrics-report paths.
    return float(wins / (p * n))


def finalize_metrics(metrics: Dict) -> Dict[str, float]:
    """Scalar-ize a metrics dict: plain entries -> float, histogram pairs ->
    their derived scalar ("auc"), raw histogram vectors dropped."""
    out: Dict[str, float] = {}
    for k, v in metrics.items():
        if not k.startswith(HIST_PREFIX):
            out[k] = float(v)
    if AUC_POS in metrics and AUC_NEG in metrics:
        out["auc"] = auc_from_histograms(metrics[AUC_POS], metrics[AUC_NEG])
    return out


class PhaseTimers:
    """Cumulative wall-clock per named worker task-loop phase.

    The job-vs-bench throughput gap (TRAINJOB_r05 53k ex/s/chip vs BENCH_r05
    289k) was guessed at until these timers: the worker decomposes its task
    wall into named phases so the gap is attributable instead of folklore.
    Phase names used by the worker loop:

    - ``prep_wait``   blocked on host ingest (bulk read + decode + stack, or
                      the prep-ahead future when pipelined)
    - ``dispatch``    issuing device work (H2D transfer + step/scan dispatch;
                      includes the first task's XLA compile)
    - ``step_wait``   draining device execution at the deferred metrics fetch
    - ``metrics``     host-side metric aggregation + the report RPC
    - ``checkpoint``  task-loop boundary cost of periodic checkpoints
                      (snapshot dispatch + in-flight-save joins + final save)
    - ``control``     task-boundary control-plane overhead (heartbeat +
                      membership checks; the lease RPC nests under it and
                      keeps only its own time)
    - ``lease_wait``  the task-lease RPC itself (GetTask/GetGroupTask) —
                      with batched leases (r9) this fires once per batch,
                      so its per-task share is the lease amortization win
    - ``checkpoint_bg``  background checkpoint write + commit-barrier time —
                      OFF the critical path, excluded from wall sums
    - ``decode_parallel``  cumulative ingest-pool thread time in parallel
                      chunk read+decode (r9) — runs CONCURRENTLY with the
                      foreground phases (and with itself, across threads),
                      so it is off the critical path like ``checkpoint_bg``;
                      compare it against ``prep_wait`` to see how much
                      decode the pool hid

    The snapshot rides every ReportTaskResult/ReportCheckpoint, so the
    master's view (JobStatus ``phase_times``) and the train-job artifact get
    the decomposition without a new RPC.  Cost per entry: two
    ``perf_counter`` calls and a locked dict add — noise next to any phase
    worth timing.

    Thread-safe: the background checkpoint thread records under its own key
    while the task loop records the foreground phases.

    Nested phases record SELF-time: a phase entered inside another phase
    (e.g. a membership change inside the ``control`` heartbeat draining a
    pipelined task through its dispatch/metrics/checkpoint phases)
    subtracts its wall from the enclosing phase, so each second of the
    task loop lands in exactly one bucket and the decomposition stays a
    partition of (bounded by) wall time.  The nesting stack is per-thread
    — a background phase never subtracts from a foreground one.
    """

    def __init__(self, gauges=None):
        self._lock = locksan.lock("PhaseTimers._lock", leaf=True)  # lock-order: leaf
        self._seconds: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._local = threading.local()
        # graftgauge (r14): with a registry wired, every phase ENTRY also
        # observes into a per-phase duration histogram (shared log grid),
        # so a live scrape shows the phase tail SHAPE — the cumulative
        # seconds alone cannot tell "one 2 s stall" from "2000 stalls of
        # 1 ms".  Histogram handles are cached per phase name: the add()
        # path pays one dict lookup + an O(1) observe, not a registry
        # walk.
        self._gauges = gauges
        self._phase_hists: Dict[str, object] = {}  # guarded-by: _lock

    @contextlib.contextmanager
    def phase(self, name: str):
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        child_wall = [0.0]
        stack.append(child_wall)
        # Every phase doubles as a trace span (category "phase") when the
        # process recorder is on: the cross-process trace view decomposes
        # by the SAME names as the cumulative timers, and the span's
        # independent self-time arithmetic is pinned against ours by tests.
        # Disabled, span() is a shared no-op — one attribute check.
        sp = trace.span(name, cat="phase")
        sp.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - t0
            sp.__exit__(None, None, None)
            stack.pop()
            if stack:
                # Report the full wall to the enclosing phase so IT can
                # subtract; this phase keeps only its self-time.
                stack[-1][0] += elapsed
            self.add(name, elapsed - child_wall[0])

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self._seconds[name] = self._seconds.get(name, 0.0) + seconds
            self._counts[name] = self._counts.get(name, 0) + 1
            hist = self._phase_hists.get(name)
        if self._gauges is None:
            return
        if hist is None:
            # Created OUTSIDE our leaf lock (the registry lookup takes
            # the registry's own leaf; nesting the two would break both
            # declarations).  Registry.histogram is idempotent, so a
            # racing creation converges on the same series.
            hist = self._gauges.histogram(
                "edl_phase_ms",
                "per-entry wall of each task-loop phase (self-time)",
                labels={"phase": name},
            )
            with self._lock:
                self._phase_hists[name] = hist
        hist.observe(seconds * 1e3)

    def snapshot(self) -> Dict[str, float]:
        """Cumulative seconds per phase (plain floats — JSON/RPC-safe)."""
        with self._lock:
            return {k: round(v, 6) for k, v in self._seconds.items()}

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


#: Phases that consume task-loop wall-clock (everything but the background
#: checkpoint write and the ingest pool's parallel decode time, which run
#: concurrently with the foreground phases).  Consumers summing a
#: decomposition against wall time must restrict to these.
CRITICAL_PATH_PHASES = (
    "prep_wait", "dispatch", "step_wait", "metrics", "checkpoint", "control",
    "lease_wait", "collective_gate",
)


def critical_path_seconds(phase_times: Dict[str, float]) -> float:
    """Sum of the wall-consuming phases of one worker's snapshot."""
    return float(
        sum(v for k, v in phase_times.items() if k in CRITICAL_PATH_PHASES)
    )


class MetricsWriter:
    """Append-only JSONL scalar stream + optional TensorBoard mirror.

    One append handle for the stream's whole life (closed in ``close()``):
    the old open-per-record idiom paid an open/close syscall pair per
    report AND left a window where a crash mid-write tore the final line
    with no reader-side tolerance.  Crash-safe append now means what it
    says: each record is one ``write`` of a full line followed by a flush
    (the OS appends atomically for these sizes), and ``read_metrics``
    drops a torn FINAL line instead of raising.
    """

    def __init__(self, directory: str, tensorboard: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._path = os.path.join(self.directory, METRICS_FILENAME)
        self._lock = locksan.lock("MetricsWriter._lock", leaf=True)  # lock-order: leaf
        # graftlint: allow[durable-write-discipline] metrics are advisory: buffered flush-only appends by contract (fsync per scalar would serialize report handlers on the disk); reader is torn-tolerant
        self._f = open(self._path, "a")  # guarded-by: _lock
        self._tb = None
        if tensorboard:
            try:
                from tensorboardX import SummaryWriter  # type: ignore

                self._tb = SummaryWriter(
                    logdir=os.path.join(self.directory, "tensorboard")
                )
            except Exception:  # pragma: no cover - tensorboardX optional
                logger.info("tensorboardX unavailable; JSONL metrics only")

    def write(self, kind: str, step: int, metrics: Dict[str, float]) -> None:
        """Record one scalar group: kind is "train" | "eval" | custom."""
        record = {
            "ts": time.time(),
            "kind": kind,
            "step": int(step),
            **{k: float(v) for k, v in metrics.items()},
        }
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            if self._f is None:
                # A report racing close() (gRPC pool thread vs master
                # teardown) must not crash the handler: reopen for the
                # straggler record — append keeps the stream consistent.
                # graftlint: allow[durable-write-discipline] same advisory-append contract as the primary handle above
                self._f = open(self._path, "a")
            self._f.write(line + "\n")
            self._f.flush()
            if self._tb is not None:
                for key, value in metrics.items():
                    self._tb.add_scalar(f"{kind}/{key}", float(value), int(step))

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
            if self._tb is not None:
                self._tb.close()
                self._tb = None


# recovery-path
def read_metrics(directory: str) -> list:
    """All records of a job's metrics.jsonl (tests, CLI inspection).

    Tolerates a torn FINAL line — the one legal artifact of a crash mid-
    append — by dropping it; garbage anywhere earlier still raises (that is
    corruption, not a crash tail, and silently skipping it would hide it).
    The r12 stance, generalized: durable.read_wal is the one definition.
    """
    from elasticdl_tpu.common import durable

    path = os.path.join(os.path.abspath(directory), METRICS_FILENAME)
    if not os.path.exists(path):
        return []
    records, _torn = durable.read_wal(path)
    return records
