"""Metrics writer — the master's structured observability sink.

Reference parity (SURVEY.md §5 "Metrics/logging/observability" [U — mount
empty at survey time]): the reference surfaces eval metrics via gRPC to the
master and optionally TensorBoard through Keras callbacks.  Here the master
appends every training/eval metric report to a JSONL stream (one
machine-parseable record per event, crash-safe append) and mirrors scalars
to TensorBoard when ``tensorboardX`` is importable.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("metrics")


class MetricsWriter:
    """Append-only JSONL scalar stream + optional TensorBoard mirror."""

    def __init__(self, directory: str, tensorboard: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._path = os.path.join(self.directory, "metrics.jsonl")
        self._lock = threading.Lock()
        self._tb = None
        if tensorboard:
            try:
                from tensorboardX import SummaryWriter  # type: ignore

                self._tb = SummaryWriter(
                    logdir=os.path.join(self.directory, "tensorboard")
                )
            except Exception:  # pragma: no cover - tensorboardX optional
                logger.info("tensorboardX unavailable; JSONL metrics only")

    def write(self, kind: str, step: int, metrics: Dict[str, float]) -> None:
        """Record one scalar group: kind is "train" | "eval" | custom."""
        record = {
            "ts": time.time(),
            "kind": kind,
            "step": int(step),
            **{k: float(v) for k, v in metrics.items()},
        }
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            with open(self._path, "a") as f:
                f.write(line + "\n")
            if self._tb is not None:
                for key, value in metrics.items():
                    self._tb.add_scalar(f"{kind}/{key}", float(value), int(step))

    def close(self) -> None:
        with self._lock:
            if self._tb is not None:
                self._tb.close()
                self._tb = None


def read_metrics(directory: str) -> list:
    """All records of a job's metrics.jsonl (tests, CLI inspection)."""
    path = os.path.join(os.path.abspath(directory), "metrics.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
