"""Metrics writer — the master's structured observability sink.

Reference parity (SURVEY.md §5 "Metrics/logging/observability" [U — mount
empty at survey time]): the reference surfaces eval metrics via gRPC to the
master and optionally TensorBoard through Keras callbacks.  Here the master
appends every training/eval metric report to a JSONL stream (one
machine-parseable record per event, crash-safe append) and mirrors scalars
to TensorBoard when ``tensorboardX`` is importable.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("metrics")

#: Metric keys with this prefix carry HISTOGRAM vectors, not scalars.  They
#: flow through every aggregation layer (device psum, worker minibatch sums,
#: master cross-worker weighted means) unchanged in meaning — histograms are
#: linear, and the scalars derived from them (AUC) are scale-invariant, so
#: weighted MEANS aggregate as exactly as sums would.  ``finalize_metrics``
#: converts them to their scalar at the last step of each pipeline.
HIST_PREFIX = "__hist__"

#: The one histogram-derived metric so far: ROC AUC from score histograms
#: (the reference evaluates Criteo/DeepFM on AUC via TF's bucketed streaming
#: AUC — same construction).
AUC_POS = HIST_PREFIX + "auc_pos"
AUC_NEG = HIST_PREFIX + "auc_neg"


def auc_from_histograms(pos, neg) -> float:
    """ROC AUC from per-score-bucket positive/negative counts.

    Rank-statistic identity: AUC = P(score_pos > score_neg) + 0.5 *
    P(tie).  Bucketed: each positive in bucket b beats every negative in
    buckets < b and half-ties the negatives in bucket b.  Exact for scores
    quantized to the bucket grid; O(1/n_bins) bias otherwise — identical to
    TF's thresholded streaming AUC.  Degenerate sets (no positives or no
    negatives) return 0.5.
    """
    import numpy as np

    pos = np.asarray(pos, np.float64)
    neg = np.asarray(neg, np.float64)
    p, n = pos.sum(), neg.sum()
    if p <= 0 or n <= 0:
        return 0.5
    neg_below = np.concatenate([[0.0], np.cumsum(neg)[:-1]])
    wins = float(np.sum(pos * (neg_below + 0.5 * neg)))
    # Plain python float: np.float64 leaks would crash json.dumps on the
    # gRPC JobStatus / metrics-report paths.
    return float(wins / (p * n))


def finalize_metrics(metrics: Dict) -> Dict[str, float]:
    """Scalar-ize a metrics dict: plain entries -> float, histogram pairs ->
    their derived scalar ("auc"), raw histogram vectors dropped."""
    out: Dict[str, float] = {}
    for k, v in metrics.items():
        if not k.startswith(HIST_PREFIX):
            out[k] = float(v)
    if AUC_POS in metrics and AUC_NEG in metrics:
        out["auc"] = auc_from_histograms(metrics[AUC_POS], metrics[AUC_NEG])
    return out


class MetricsWriter:
    """Append-only JSONL scalar stream + optional TensorBoard mirror."""

    def __init__(self, directory: str, tensorboard: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._path = os.path.join(self.directory, "metrics.jsonl")
        self._lock = threading.Lock()
        self._tb = None
        if tensorboard:
            try:
                from tensorboardX import SummaryWriter  # type: ignore

                self._tb = SummaryWriter(
                    logdir=os.path.join(self.directory, "tensorboard")
                )
            except Exception:  # pragma: no cover - tensorboardX optional
                logger.info("tensorboardX unavailable; JSONL metrics only")

    def write(self, kind: str, step: int, metrics: Dict[str, float]) -> None:
        """Record one scalar group: kind is "train" | "eval" | custom."""
        record = {
            "ts": time.time(),
            "kind": kind,
            "step": int(step),
            **{k: float(v) for k, v in metrics.items()},
        }
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            with open(self._path, "a") as f:
                f.write(line + "\n")
            if self._tb is not None:
                for key, value in metrics.items():
                    self._tb.add_scalar(f"{kind}/{key}", float(value), int(step))

    def close(self) -> None:
        with self._lock:
            if self._tb is not None:
                self._tb.close()
                self._tb = None


def read_metrics(directory: str) -> list:
    """All records of a job's metrics.jsonl (tests, CLI inspection)."""
    path = os.path.join(os.path.abspath(directory), "metrics.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
