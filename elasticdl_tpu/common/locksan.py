"""Runtime lock-order sanitizer — the dynamic twin of graftlint's
``lock-order`` pass.

The static pass (analysis/lock_order.py) proves the LEXICAL acquisition
graph acyclic, but it is blind to locks reached through object attributes
(``self.dispatcher.get_task()`` crossing into another class's lock) and to
orders established only at runtime.  This wrapper closes that half:

- ``locksan.lock(name, leaf=..., before=...)`` returns a plain
  ``threading.Lock`` when ``GRAFT_LOCKSAN`` != ``1`` (zero overhead in
  production) and a sanitized wrapper when it is set — tests/conftest.py
  turns it on for the whole tier-1 suite, so every threaded test (worker,
  servicer, PS, pod manager) runs with runtime order checking.
- Each thread keeps its held-lock stack; each acquisition records the
  edges ``held -> acquired`` (by lock NAME, so the order is a class-level
  contract, instance-agnostic) together with the acquiring stack site.
- An acquisition raises :class:`LockOrderViolation` when it
  (a) re-acquires a non-reentrant lock this thread already holds,
  (b) acquires anything while holding a lock declared ``leaf=True``,
  (c) acquires a lock declared ``before=(<other>,)`` while ``<other>`` is
      held (the declared order, inverted), or
  (d) inverts an order previously OBSERVED anywhere in the process — the
      classic two-thread A->B / B->A deadlock, caught deterministically on
      the second acquisition order without needing the timing to collide.

The ``leaf``/``before`` declarations mirror the ``# lock-order:``
annotations on the declaring line; graftlint's lock-order pass verifies
the two agree, so the static model and the runtime assertions gate each
other.  Same-name locks of DIFFERENT instances (two workers in one test
process) are exempt from pairwise order checks — the name-level order is a
class contract, and peer instances have no defined order.

Pure stdlib: imported by master-process modules, which must stay jax-free
(graftlint import-hygiene).
"""

from __future__ import annotations

import bisect
import os
import threading
import time
import traceback
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "LockOrderViolation", "enabled", "lock", "rlock", "observed_edges",
    "reset", "held_names", "enable_contention_stats", "contention_snapshot",
]


class LockOrderViolation(AssertionError):
    """A runtime lock acquisition contradicted the declared or previously
    observed order.  Raised BEFORE the offending acquire, so the process
    fails loudly instead of deadlocking quietly later."""


def enabled() -> bool:
    return os.environ.get("GRAFT_LOCKSAN", "") == "1"


#: (held_name, acquired_name) -> "file:line in func" of the first
#: observation.  Process-global: the order contract spans threads and
#: instances, which is the whole point.
_edges: Dict[Tuple[str, str], str] = {}
_edges_lock = threading.Lock()
_tls = threading.local()


def _held() -> List["_SanLock"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _site() -> str:
    """The acquiring frame, skipping locksan internals."""
    for frame in reversed(traceback.extract_stack()[:-2]):
        if os.path.basename(frame.filename) != "locksan.py":
            return f"{frame.filename}:{frame.lineno} in {frame.name}"
    return "<unknown>"


def reset() -> None:
    """Forget observed edges and contention aggregates (test isolation;
    the per-thread held stacks empty themselves when locks release)."""
    with _edges_lock:
        _edges.clear()
    with _stats_lock:
        _stats.clear()


def observed_edges() -> Dict[Tuple[str, str], str]:
    """Snapshot of the observed acquisition orders with their first
    witness site (debugging / tests)."""
    with _edges_lock:
        return dict(_edges)


def held_names() -> Tuple[str, ...]:
    """Names of the sanitized locks the CURRENT thread holds — the lock
    context common/racesan.py records per shared-state observation."""
    return tuple(h.name for h in _held())


# -- contention stats (r16): per-lock-name acquire count + wait histogram.
#
# Recording is OFF until a scrape-side consumer installs it
# (gauge.install_lock_collector); un-installed, each acquire pays one
# module-global check.  Aggregates are raw (count/sum/bucket counts on a
# caller-supplied edge grid) because this module must stay import-light:
# common/gauge.py imports locksan, so the bridge lives THERE and mirrors
# these aggregates into edl_lock_acquire_total / edl_lock_wait_ms at
# scrape time.

_stats_lock = threading.Lock()
_stats_enabled = False
_stats_edges: Tuple[float, ...] = ()
#: name -> [acquire_count, wait_sum_ms, per-bucket counts (len(edges)+1)]
_stats: Dict[str, list] = {}


def enable_contention_stats(edges_ms: Iterable[float]) -> None:
    """Start aggregating per-lock-name wait times on ``edges_ms`` (the
    shared gauge grid).  Idempotent; existing aggregates are kept when
    the grid is unchanged, reset when it differs."""
    global _stats_enabled, _stats_edges
    edges = tuple(float(e) for e in edges_ms)
    with _stats_lock:
        if edges != _stats_edges:
            _stats.clear()
            _stats_edges = edges
        _stats_enabled = True


def contention_snapshot() -> Dict[str, dict]:
    """Per-lock-name ``{"acquires", "wait_ms": {edges, counts, sum,
    count}}`` — the collector's input; empty until stats are enabled and
    a sanitized lock has been acquired."""
    with _stats_lock:
        edges = list(_stats_edges)
        return {
            name: {
                "acquires": rec[0],
                "wait_ms": {
                    "edges": edges, "counts": list(rec[2]),
                    "sum": rec[1], "count": rec[0],
                },
            }
            for name, rec in sorted(_stats.items())
        }


def _record_wait(name: str, wait_ms: float) -> None:
    idx = bisect.bisect_left(_stats_edges, wait_ms)
    with _stats_lock:
        rec = _stats.get(name)
        if rec is None:
            rec = _stats[name] = [0, 0.0, [0] * (len(_stats_edges) + 1)]
        rec[0] += 1
        rec[1] += wait_ms
        rec[2][min(idx, len(rec[2]) - 1)] += 1


class _SanLock:
    """Order-checking wrapper around ``threading.Lock``/``RLock``."""

    def __init__(
        self,
        name: str,
        leaf: bool,
        before: Tuple[str, ...],
        reentrant: bool,
    ):
        self.name = name
        self.leaf = leaf
        self.reentrant = reentrant
        # ``before=("_lock",)`` names sibling attributes; resolve them to
        # full "<Class>.<attr>" names against our own prefix so runtime
        # comparisons match the static lock ids.
        prefix = name.rsplit(".", 1)[0] + "." if "." in name else ""
        self.before = tuple(
            b if "." in b else prefix + b for b in before
        )
        self._lock = threading.RLock() if reentrant else threading.Lock()

    # -- the check --

    def _check_order(self) -> None:
        held = _held()
        if not held:
            return  # fast path: first lock of this thread, nothing to order
        names_to_record = []
        for h in held:
            if h is self:
                if self.reentrant:
                    continue  # RLock re-entry is legal, and orders nothing
                raise LockOrderViolation(
                    f"locksan: {self.name} re-acquired by the thread that "
                    f"already holds it (non-reentrant: self-deadlock) at "
                    f"{_site()}"
                )
            if h.name == self.name:
                # A PEER instance (two workers in one process): the
                # name-level order is a class contract; peers have no
                # defined mutual order — skip pairwise checks.
                continue
            if h.leaf:
                raise LockOrderViolation(
                    f"locksan: {h.name} is declared leaf but {self.name} "
                    f"is being acquired while it is held, at {_site()}"
                )
            if h.name in self.before:
                raise LockOrderViolation(
                    f"locksan: {self.name} is declared before({h.name}) "
                    f"but is being acquired while {h.name} is held, at "
                    f"{_site()}"
                )
            names_to_record.append(h.name)
        if not names_to_record:
            return
        with _edges_lock:
            for hname in names_to_record:
                first = _edges.get((self.name, hname))
                if first is not None:
                    raise LockOrderViolation(
                        f"locksan: lock order inversion — acquiring "
                        f"{self.name} while holding {hname} at {_site()}, "
                        f"but the opposite order ({self.name} before "
                        f"{hname}) was observed at {first}; one of the two "
                        "paths can deadlock against the other"
                    )
            site = _site()
            for hname in names_to_record:
                _edges.setdefault((hname, self.name), site)

    # -- threading.Lock surface --

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check_order()
        if not _stats_enabled:
            got = self._lock.acquire(blocking, timeout)
        else:
            t0 = time.monotonic()
            got = self._lock.acquire(blocking, timeout)
            if got:
                _record_wait(self.name, (time.monotonic() - t0) * 1000.0)
        if got:
            _held().append(self)
        return got

    def release(self) -> None:
        self._lock.release()
        stack = _held()
        # Remove the NEWEST entry for this lock (RLock re-entries release
        # LIFO; non-LIFO release of distinct locks is legal for Lock).
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def _is_owned(self) -> bool:
        """``threading.Condition`` compatibility: Condition(lock) prefers the
        lock's own ``_is_owned`` when present.  Without this, Condition's
        fallback probes ownership via a non-blocking re-``acquire`` — which
        the sanitizer (correctly) rejects as a self-deadlock before the probe
        can return False.  Answer from the per-thread held stack instead."""
        return any(h is self for h in _held())

    def locked(self) -> bool:
        # RLock grew .locked() only in 3.12; absent there, report via the
        # held bookkeeping (callers in this repo only probe plain Locks).
        fn = getattr(self._lock, "locked", None)
        if fn is not None:
            return fn()
        return any(h is self for h in _held())

    def __repr__(self) -> str:
        return f"<locksan {self.name} wrapping {self._lock!r}>"


def lock(
    name: str,
    leaf: bool = False,
    before: Iterable[str] = (),
) -> "threading.Lock | _SanLock":
    """A ``threading.Lock`` (sanitized when ``GRAFT_LOCKSAN=1``).

    ``name`` must be ``"<Class>.<attr>"`` (or ``"<attr>"`` for module-level
    locks) — graftlint's lock-order pass checks it against the assignment.
    ``leaf=True``: no other lock may be acquired while this one is held.
    ``before=("_other",)``: this lock orders before the sibling attribute
    ``self._other`` whenever the two nest.
    """
    if not enabled():
        return threading.Lock()
    return _SanLock(name, leaf=leaf, before=tuple(before), reentrant=False)


def rlock(
    name: str,
    leaf: bool = False,
    before: Iterable[str] = (),
) -> "threading.RLock | _SanLock":
    """``threading.RLock`` twin of :func:`lock`."""
    if not enabled():
        return threading.RLock()
    return _SanLock(name, leaf=leaf, before=tuple(before), reentrant=True)
