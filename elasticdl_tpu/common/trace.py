"""grafttrace — hot-path-safe structured tracing, one recorder per process.

The repo could decompose a worker's wall time (``PhaseTimers``) but not
show one training step ACROSS processes: a slow gang step was attributable
to "control grew" and nothing finer.  This module is the recording half of
the fix — a stdlib-only span recorder cheap enough to live inside
``# hot-path`` functions — and ``tools/trace_dump.py`` is the reading half
(merge every process's buffer into one Chrome-trace/Perfetto JSON).

Design constraints, in order:

- **Hot-path safe.**  Emission never blocks and never allocates beyond the
  event record itself: the buffer is a bounded ``collections.deque`` whose
  ``append`` is GIL-atomic (no lock), overwriting the OLDEST event when
  full — a tracing stall or an unbounded buffer must never be the thing
  that makes the traced job slow.  Disabled (the default), ``span()``
  returns a shared no-op context manager: one attribute read per call.
- **Stdlib only.**  The master control plane and the lint/bench tools are
  jax-free by contract (graftlint import-hygiene); the recorder rides in
  all of them.
- **Mergeable.**  Events carry wall-anchored microsecond timestamps
  (``time.time`` anchor + ``perf_counter`` offsets, so resolution is
  perf_counter's while the epoch is comparable across processes) and the
  worker ships its buffer with a measured clock offset (RPC RTT midpoint,
  see ``Worker._check_membership``), so the dump tool can align per-process
  clocks onto the master's.

API split the ``trace-discipline`` lint rule enforces:

- non-blocking ring API (legal anywhere, including ``# hot-path``):
  ``span(...)`` / ``instant(...)`` / ``TraceRecorder.add_complete``;
- export API (forbidden in ``# hot-path`` functions): ``drain_slice`` /
  ``export`` / ``chrome_events`` — draining belongs on control-plane
  boundaries (heartbeats, checkpoint reports, dump tools).

Per-thread nesting: spans stack per thread; each records its parent's id
and its SELF time (wall minus directly nested spans' wall) in
``args.self_us`` — the trace-side twin of ``PhaseTimers``' nested-phase
self-time arithmetic, and the tests pin that the two agree on the same
block.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: Default per-process ring capacity (events).  At the worker's steady
#: state (~10 spans/task) this holds hours; the serving tier's per-request
#: spans wrap sooner, which is the point of overwrite-oldest: the buffer
#: always holds the most RECENT window.
DEFAULT_CAPACITY = 65536

#: How many events one heartbeat/report ships (bounded so a control-plane
#: RPC can never balloon because tracing is on).
SHIP_BATCH = 512


class _NullSpan:
    """Shared no-op span for the disabled recorder: enter/exit do nothing,
    so a disabled hot path pays one attribute check per ``span()`` call."""

    __slots__ = ()
    span_id = 0

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: a context manager pushed on the per-thread stack."""

    __slots__ = ("_rec", "name", "cat", "attrs", "span_id", "parent_id",
                 "_t0", "_child")

    def __init__(self, rec: "TraceRecorder", name: str, cat: str,
                 attrs: Dict[str, Any]):
        self._rec = rec
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.span_id = 0
        self.parent_id = 0
        self._child = 0.0

    def __enter__(self) -> "_Span":
        rec = self._rec
        stack = rec._stack()
        self.span_id = next(rec._ids)
        self.parent_id = stack[-1].span_id if stack else 0
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        rec = self._rec
        elapsed = t1 - self._t0
        stack = rec._stack()
        if stack and stack[-1] is self:
            stack.pop()
        if stack:
            # Hand the full wall to the enclosing span so IT can subtract;
            # this span keeps only its self-time (PhaseTimers' arithmetic).
            stack[-1]._child += elapsed
        args = dict(self.attrs) if self.attrs else {}
        args["self_us"] = round(max(elapsed - self._child, 0.0) * 1e6, 1)
        args["span_id"] = self.span_id
        if self.parent_id:
            args["parent"] = self.parent_id
        rec.add_complete(
            self.name, self.cat,
            rec._to_us(self._t0), elapsed * 1e6, args,
        )
        return False


class TraceRecorder:
    """Bounded ring of trace events with non-blocking append.

    Thread-safety without a lock: ``deque(maxlen=N).append`` and
    ``popleft`` are GIL-atomic in CPython, so concurrent writers interleave
    safely and a full ring drops the oldest event (each writer's retained
    events form a suffix of its own appends — pinned by tests).
    ``dropped`` is an APPROXIMATE monotonic counter (unsynchronized
    increments may lose a race); it exists to say "the window wrapped",
    not to account every event.
    """

    def __init__(self, enabled: bool = False,
                 capacity: int = DEFAULT_CAPACITY):
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        self._buf: collections.deque = collections.deque(maxlen=self.capacity)
        self._local = threading.local()
        self._ids = itertools.count(1)
        self.dropped = 0
        # Wall anchor + perf_counter origin: timestamps get perf_counter's
        # resolution/monotonicity on a wall-clock epoch, so buffers from
        # different processes are alignable (after the RTT-midpoint offset).
        self._wall0 = time.time()
        self._pc0 = time.perf_counter()

    # -- clock --

    def _to_us(self, pc: float) -> float:
        return (self._wall0 + (pc - self._pc0)) * 1e6

    def now_us(self) -> float:
        """Wall-anchored monotonic timestamp in microseconds."""
        return self._to_us(time.perf_counter())

    # -- non-blocking ring API (hot-path legal) --

    def _stack(self) -> List[_Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_span_id(self) -> int:
        stack = getattr(self._local, "stack", None)
        return stack[-1].span_id if stack else 0

    def span(self, name: str, cat: str = "span", **attrs):
        """Context manager recording one complete ("X") event on exit."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, attrs)

    def instant(self, name: str, cat: str = "event", **attrs) -> None:
        """One instant ("i") event — elastic control transitions live here."""
        if not self.enabled:
            return
        ev = {
            "ph": "i", "name": name, "cat": cat,
            "ts": round(self.now_us(), 1),
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "s": "t",
        }
        if attrs:
            ev["args"] = attrs
        self._append(ev)

    def add_complete(self, name: str, cat: str, ts_us: float, dur_us: float,
                     args: Optional[Dict[str, Any]] = None) -> None:
        """Append one complete event (the span exit path; also usable
        directly by instrumentation that already timed itself)."""
        if not self.enabled:
            return
        ev = {
            "ph": "X", "name": name, "cat": cat,
            "ts": round(ts_us, 1), "dur": round(dur_us, 1),
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def _append(self, ev: dict) -> None:
        if len(self._buf) >= self.capacity:
            self.dropped += 1  # approximate: see class docstring
        self._buf.append(ev)

    # -- export API (forbidden in # hot-path functions: trace-discipline) --

    def drain_slice(self, max_events: int = SHIP_BATCH) -> List[dict]:
        """Pop up to ``max_events`` OLDEST events (the shipping path:
        bounded slices ride the heartbeat/report channel).  Safe against
        concurrent appenders; never blocks."""
        out: List[dict] = []
        for _ in range(max_events):
            try:
                out.append(self._buf.popleft())
            except IndexError:
                break
        return out

    def export(self) -> List[dict]:
        """Snapshot of the current window, oldest first (non-draining)."""
        return list(self._buf)

    def clear(self) -> None:
        self._buf.clear()
        self.dropped = 0


# -- the process-global recorder ------------------------------------------

#: One recorder per process.  GRAFT_TRACE=1 enables at import (subprocess
#: workers/benches inherit the env); ``configure()`` flips it
#: programmatically (the --trace job flag, tests, tools).
_REC = TraceRecorder(
    enabled=os.environ.get("GRAFT_TRACE", "") not in ("", "0")
)


def default() -> TraceRecorder:
    return _REC


def configure(enabled: Optional[bool] = None,
              capacity: Optional[int] = None) -> TraceRecorder:
    """Reconfigure the process recorder IN PLACE (module users hold no
    reference; they call the module helpers, which read the global)."""
    if capacity is not None and capacity != _REC.capacity:
        _REC.capacity = int(capacity)
        _REC._buf = collections.deque(_REC._buf, maxlen=_REC.capacity)
    if enabled is not None:
        _REC.enabled = bool(enabled)
    return _REC


def enabled() -> bool:
    return _REC.enabled


def span(name: str, cat: str = "span", **attrs):
    return _REC.span(name, cat, **attrs)


def instant(name: str, cat: str = "event", **attrs) -> None:
    _REC.instant(name, cat, **attrs)


def now_us() -> float:
    return _REC.now_us()
