"""Logging helpers (reference: elasticdl/python/common/log_utils.py [U])."""

from __future__ import annotations

import logging
import sys

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"

_default_level = "INFO"
_loggers: dict = {}


def get_logger(name: str, level: str = "") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        logger.propagate = False
    logger.setLevel((level or _default_level).upper())
    _loggers[name] = logger
    return logger


def set_level(level: str) -> None:
    """Apply --log_level to every framework logger, existing and future
    (master/worker mains call this right after parsing the job config)."""
    global _default_level
    _default_level = level
    for logger in _loggers.values():
        logger.setLevel(level.upper())
