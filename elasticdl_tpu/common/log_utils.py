"""Logging helpers (reference: elasticdl/python/common/log_utils.py [U])."""

from __future__ import annotations

import logging
import sys

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


def get_logger(name: str, level: str = "INFO") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        logger.propagate = False
    logger.setLevel(level.upper())
    return logger
