"""Version-compat shims over the moving jax API surface.

The framework targets current jax spellings (``jax.shard_map`` with
``check_vma``, ``lax.axis_size``); deployment images lag — this container
ships 0.4.37, where shard_map lives under ``jax.experimental`` with the
``check_rep`` kwarg and ``lax.axis_size`` does not exist yet.  One module
owns the translation so call sites write the modern API exactly once and a
jax upgrade deletes shims instead of re-touching every kernel.

Import-time feature detection (not version parsing): the probe is the
behavior we need, and vendor backports would defeat a version check.
"""

from __future__ import annotations

import inspect

import jax
from jax import lax

from elasticdl_tpu.common import jitsan

try:  # jax >= 0.6 exports shard_map at top level
    _shard_map = jax.shard_map  # type: ignore[attr-defined]
except AttributeError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore


if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    def shard_map(*args, **kwargs):
        # Older jax spells the replication-check kwarg ``check_rep``
        # (renamed ~0.6).  Positional args pass through untouched.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)


def jit_compiled(fun, name=None, expected_variants=1, **jit_kwargs):
    """``jax.jit`` through the shim, with a compile-stability declaration.

    ``name`` keys the jitsan registry (graftlint's jit-shim pass requires
    it at call sites — the gauge label ``edl_jit_compiles_total{fn=}``
    and the LINT artifact's budget table are only as good as the names);
    ``expected_variants`` declares how many times THIS returned callable
    may lower (distinct shapes/dtypes/static args).  With ``GRAFT_JITSAN``
    unset the declaration costs nothing: the plain jitted function comes
    back untouched.  Armed (tier-1-wide via tests/conftest.py), every
    lowering is counted and a lowering past the budget raises
    ``jitsan.JitSanViolation`` deterministically at the drifting call
    (common/jitsan.py).
    """
    if not jitsan.enabled():
        return jax.jit(fun, **jit_kwargs)
    return jitsan.wrap(
        jax.jit, fun, name=name, expected_variants=expected_variants,
        jit_kwargs=jit_kwargs,
    )


def jit_donating(fun, donate_argnums=(0,), name=None, expected_variants=1):
    """``jax.jit`` with input-buffer donation — the train-step spelling.

    One shim owns the donation kwarg so every donating step (train, scan)
    writes it identically and a jax API migration (``donate_argnums`` ->
    the ``donate_argnames`` world) lands here once instead of per call
    site.  Donation lets XLA alias the input state's buffers into the
    output state — without it every step holds two full copies of
    params + optimizer state resident (measurable on CPU as peak-RSS
    delta; tools/optshard_bench.py records the A/B).

    ``name=``/``expected_variants=`` declare the jitsan compile budget,
    exactly as in :func:`jit_compiled` — donation makes stable jit
    identity MORE load-bearing, not less (a retrace on a donating step
    re-lowers against already-consumed buffers' layouts).
    """
    if not jitsan.enabled():
        return jax.jit(fun, donate_argnums=donate_argnums)
    return jitsan.wrap(
        jax.jit, fun, name=name, expected_variants=expected_variants,
        jit_kwargs={"donate_argnums": donate_argnums},
    )


def enable_cpu_multiprocess_collectives() -> None:
    """Give multi-process XLA:CPU a cross-process collectives backend.

    Newer jax defaults ``jax_cpu_collectives_implementation`` to gloo;
    0.4.x defaults to "none", and a multi-process CPU world then fails its
    first cross-process psum with "Multiprocess computations aren't
    implemented on the CPU backend".  Must run before the CPU client forms
    (callers run it next to ``jax.distributed.initialize``, which has the
    same constraint).  No-op wherever the flag is gone or already right.
    """
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # pragma: no cover - newer jax
        pass


_DIST_INIT_PARAMS = frozenset(
    inspect.signature(jax.distributed.initialize).parameters
)


def distributed_initialize(**kwargs) -> None:
    """``jax.distributed.initialize`` minus the kwargs this jax lacks.

    ``heartbeat_timeout_seconds`` (peer-death detection tuning) landed
    after 0.4.x; on an older runtime the coordination service keeps its
    built-in default rather than failing initialization — losing a faster
    elastic re-rendezvous is strictly better than losing the whole
    distributed world to a TypeError.
    """
    dropped = [k for k in kwargs if k not in _DIST_INIT_PARAMS]
    for k in dropped:
        kwargs.pop(k)
    if dropped:  # pragma: no cover - depends on installed jax
        import logging

        logging.getLogger("jax_compat").warning(
            "jax.distributed.initialize does not accept %s on jax %s; "
            "proceeding with runtime defaults for those knobs",
            dropped, jax.__version__,
        )
    jax.distributed.initialize(**kwargs)


if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:
    def axis_size(axis_name) -> int:
        """Static size of a mapped axis from inside shard_map.  psum of the
        unit python constant is special-cased to a concrete int on every
        jax we support, so shapes derived from it stay static."""
        return lax.psum(1, axis_name)
