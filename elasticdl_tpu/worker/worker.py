"""The worker main loop.

Reference parity (SURVEY.md §3.3-3.5 [U/D]): pull task -> build input from
the shard -> jitted step per minibatch -> report; on membership change,
re-form the mesh and resume from the latest checkpoint.  The reference's
trainer split (AllReduceTrainer vs PS path) collapses into one Trainer whose
partition specs differ by strategy (parallel/trainer.py).

Deployment note: in a real multi-host TPU job each worker is one host of a
``jax.distributed``-initialized slice and the mesh spans all hosts' devices;
in-process tests emulate elasticity by resizing the mesh over a fixed pool of
fake CPU devices (SURVEY.md §4 pattern).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterable, List, NamedTuple, Optional, Sequence

import grpc
import jax
import numpy as np

from elasticdl_tpu import chaos
from elasticdl_tpu.common import gauge as gaugelib
from elasticdl_tpu.common import jitsan, locksan, trace
from elasticdl_tpu.common.checkpoint import CheckpointManager
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.metrics import PhaseTimers, finalize_metrics
from elasticdl_tpu.common.rpc import (
    PROTOCOL_VERSION,
    BackoffPolicy,
    JsonRpcClient,
    call_with_backoff,
)
from elasticdl_tpu.data.ingest_pool import IngestPool, plan_chunks
from elasticdl_tpu.data.prefetch import prefetch
from elasticdl_tpu.data.reader import AbstractDataReader, Shard
from elasticdl_tpu.master.task_dispatcher import (
    TASK_EVALUATION,
    TASK_PREDICTION,
    TASK_TRAINING,
    Task,
)
from elasticdl_tpu.models.spec import ModelSpec, load_model_spec_for_job
from elasticdl_tpu.parallel.mesh import create_mesh, mesh_shape, resolve_2d_shape
from elasticdl_tpu.parallel.trainer import Trainer, TrainLoopError

logger = get_logger("worker")


class DirectMasterProxy:
    """In-process master (the reference's no-cluster test pattern).  Applies
    the same wire schemas as the gRPC path so in-process tests catch
    contract drift."""

    def __init__(self, servicer):
        self._s = servicer

    def call(self, method: str, request: dict) -> dict:
        from elasticdl_tpu.common.rpc import MASTER_SCHEMAS, validate_message

        validate_message(method, request, MASTER_SCHEMAS)
        return self._s.method_table()[method](request)


class RpcMasterProxy:
    """The worker's wire boundary to the master: every ``master.call`` in
    this file funnels here, so the per-call deadline lives here (graftlint
    rpc-discipline treats ``master``-terminal receivers as owned by this
    proxy).  A master RPC that outlives the deadline surfaces as an error
    at the call site instead of wedging the task loop forever on a
    half-dead master.

    Master-outage ride-through (r18): a transport-level failure
    (UNAVAILABLE — the master process is down or restarting) does NOT
    surface to the call site while ``outage_tolerance_s`` lasts; the call
    retries under the shared exponential-backoff-with-jitter helper
    (common/rpc.call_with_backoff), which parks the calling thread — the
    task loop blocks at whatever safe boundary it was crossing, holding
    its buffered leases and in-flight prep, while already-dispatched
    device work keeps streaming.  The first call that succeeds after
    failures marks the proxy RECONNECTED (``take_reconnected``): the
    worker then re-registers with its held-lease inventory so the
    restarted master reconciles against its replayed journal.  Report
    retries across the outage are exactly-once by the report-seq dedup
    (common/rpc MASTER_SCHEMAS), never by hope.  Chaos drop_rpc faults
    raise ``ChaosRpcDropped`` — not a grpc error, deliberately NOT
    retried (r13's blackout fleets depend on drops dying client-side)."""

    #: Transport-level codes worth riding out: the server is not there.
    #: DEADLINE_EXCEEDED is deliberately absent — the call may have
    #: EXECUTED (only reports are dedup-protected), and a deadline on a
    #: live master is a latency pathology the caller should see.
    _TRANSIENT_CODES = (grpc.StatusCode.UNAVAILABLE,)

    def __init__(
        self,
        address: str,
        timeout_s: float = 30.0,
        call_timeout_s: float = 60.0,
        outage_tolerance_s: float = 120.0,
        gauges: Optional[gaugelib.Registry] = None,
    ):
        self._address = address
        self._client = JsonRpcClient(address)
        # Startup vs a slow master: short readiness probes under the
        # shared backoff (a master still binding its port is routine at
        # job start — the old one-shot wait_ready(30) hard-failed a
        # healthy worker), with a clear terminal error naming the flag.
        call_with_backoff(
            lambda: self._client.wait_ready(5.0),
            service="master",
            is_transient=lambda e: isinstance(
                e, (grpc.FutureTimeoutError, grpc.RpcError)
            ),
            policy=BackoffPolicy(
                base_s=0.5, max_s=4.0, budget_s=max(timeout_s, 1.0)
            ),
            terminal=lambda e, n, t: RuntimeError(
                f"master at {address} not reachable after {t:.0f}s "
                f"({n} attempt(s)) — check --master_addr / the master pod"
            ),
        )
        self._call_timeout_s = call_timeout_s
        self._tolerance_s = outage_tolerance_s
        # Reconnect flag, read-then-cleared by the task loop's membership
        # check; sets/reads are single ops (benign race with the beat
        # thread: worst case one extra reconcile handshake).
        self._reconnected = False  # gil-atomic
        self._g_outage = (gauges or gaugelib.default()).counter(
            "edl_master_outage_seconds_total",
            "seconds this worker spent riding out master outages "
            "(proxy reconnect backoff)",
        )

    def call(self, method: str, request: dict) -> dict:
        if self._tolerance_s <= 0:
            return self._client.call(
                method, request, timeout_s=self._call_timeout_s
            )
        state = {"t0": None}

        def _on_retry(e, attempt, delay):
            if state["t0"] is None:
                state["t0"] = time.monotonic()
                logger.warning(
                    "master at %s unreachable (%s on %s); riding out up "
                    "to %.0fs", self._address, type(e).__name__, method,
                    self._tolerance_s,
                )
            self._g_outage.inc(delay)

        def _attempt():
            if state["t0"] is not None:
                # Post-failure attempts force a re-dial first: after a few
                # fail-fast RPCs against a down server, the gRPC channel
                # parks in TRANSIENT_FAILURE and further fail-fast calls
                # do NOT trigger a fresh connection — a restarted master
                # on the same port stays "UNAVAILABLE" forever (observed
                # on grpcio 1.68).  A readiness probe is what re-dials;
                # its own timeout while the master is still down is just
                # the next transient failure.
                self._client.wait_ready(5.0)
            return self._client.call(
                method, request, timeout_s=self._call_timeout_s
            )

        resp = call_with_backoff(
            _attempt,
            service="master",
            is_transient=self._is_transient,
            policy=BackoffPolicy(
                base_s=0.5, multiplier=2.0, max_s=8.0, jitter=0.25,
            ),
            # Dynamic, not captured: limit_outage_tolerance (the
            # preemption path) must cut a ride-through that is ALREADY
            # parked in this loop short at its next wake, not after the
            # originally captured 120 s.
            budget_s_fn=lambda: self._tolerance_s,
            on_retry=_on_retry,
            terminal=lambda e, n, t: RuntimeError(
                f"master outage outlived --master_outage_tolerance_s: "
                f"{self._address} unreachable for {t:.0f}s across {n} "
                f"attempt(s) of {method}"
            ),
        )
        if state["t0"] is not None:
            outage_s = time.monotonic() - state["t0"]
            self._reconnected = True
            trace.instant(
                "worker:reconnect", cat="elastic", method=method,
                outage_s=round(outage_s, 3),
            )
            logger.warning(
                "master back after %.1fs outage (%s); reconcile pending",
                outage_s, method,
            )
        return resp

    @classmethod
    def _is_transient(cls, e: BaseException) -> bool:
        if isinstance(e, grpc.FutureTimeoutError):
            # The post-failure readiness probe timed out: still down.
            return True
        return (
            isinstance(e, grpc.RpcError)
            and getattr(e, "code", lambda: None)() in cls._TRANSIENT_CODES
        )

    def take_reconnected(self) -> bool:
        """True once per ridden-out outage: the caller owes the master a
        re-register + lease-reconcile handshake."""
        if not self._reconnected:
            return False
        self._reconnected = False
        return True

    def limit_outage_tolerance(self, budget_s: float) -> None:
        """Shrink (never grow) the ride-through budget — the preemption
        path calls this with a couple of seconds: a process that must be
        GONE inside PREEMPTION_EXIT_S cannot park two minutes in the
        outage backoff waiting for a master that may be restarting (the
        snapshot it still owes matters more than the report, whose loss
        the master's task timeout already covers).  Single float store,
        read per call; affects every thread of this proxy, which is the
        point — the whole process is exiting."""
        self._tolerance_s = min(self._tolerance_s, max(0.0, budget_s))


def _minibatches(
    records: List[bytes], batch_size: int, train: bool
) -> Iterable[tuple]:
    """Split shard records into fixed-size minibatches (static shapes for
    XLA).  The tail is wrap-padded to full size; yields (records, true_count)
    so eval weighting can use the real example count."""
    for start in range(0, len(records), batch_size):
        chunk = records[start : start + batch_size]
        true_count = len(chunk)
        if true_count < batch_size:
            # The tail de-packs to a plain list for the wrap; it is at most
            # one minibatch per task, off the hot path.
            chunk = list(chunk)
            reps = (batch_size + true_count - 1) // true_count
            chunk = (chunk * reps)[:batch_size]
        yield chunk, true_count


class WorkerRestartRequired(RuntimeError):
    """Raised when an elastic membership change needs a process restart
    (multihost mode: the jax.distributed world is fixed per process).  The
    worker main exits with RESTART_EXIT_CODE; the pod manager relaunches
    without consuming the failure budget."""


RESTART_EXIT_CODE = 3


class HostPrep(NamedTuple):
    """Result of a training task's host half (read + decode + stack).

    ``stacked`` is the ``[T, mb, ...]`` host batch over the ``n_full`` full
    minibatches (None when the task has none); ``tail`` is the plain record
    list past the last full minibatch (at most one minibatch — it trains as
    a wrap-padded masked step); ``total`` is the task's true record count.
    The parallel ingest path (data/ingest_pool.py) produces this from
    per-chunk decodes reassembled in record order, so it is bit-identical
    to the serial read — the contract tests pin."""

    total: int
    n_full: int
    stacked: Optional[dict]
    tail: List[bytes]


class Worker:
    def __init__(
        self,
        config: JobConfig,
        master,
        reader: AbstractDataReader,
        worker_id: str = "worker-0",
        spec: Optional[ModelSpec] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        devices_per_worker: int = 0,
        poll_interval_s: float = 0.05,
        gauges: Optional[gaugelib.Registry] = None,
        incarnation: Optional[str] = None,
    ):
        self.config = config
        self.master = master
        self.reader = reader
        self.worker_id = worker_id
        self.spec = spec or load_model_spec_for_job(config)
        self._pool = list(devices) if devices is not None else list(jax.devices())
        # Devices contributed per worker: in a real multi-host world each
        # worker is one host, so its share is its LOCAL device count (the
        # pool is global after jax.distributed.initialize); tests passing an
        # explicit pool emulate elasticity over that pool instead.
        if devices_per_worker:
            self._dpw = devices_per_worker
        elif devices is not None:
            self._dpw = len(self._pool)
        else:
            self._dpw = len(jax.local_devices())
        self._poll = poll_interval_s

        # The trainer/state pair is REPLACED only by the task loop
        # (membership reform, restore); checkpoint/prep threads read
        # the reference they were spawned with (happens-before via
        # thread start / _join_ckpt).
        self.trainer: Optional[Trainer] = None  # single-writer: main
        self.state = None  # single-writer: main
        self._membership_version = -1  # single-writer: main (the beat reads one int)
        self._rank = 0  # single-writer: main (reform happens on the task loop)
        # Replaced wholesale (fresh dicts) on reform; beat-thread readers
        # see either the old or the new reference, never a mid-mutation.
        self._ranks: Dict[str, int] = {}  # single-writer: main
        self._addresses: Dict[str, str] = {}  # single-writer: main
        # Multi-host lockstep: all processes of the world walk the master's
        # group task log in the same order (GetGroupTask seq counter); only
        # rank 0 reports results.
        self._group_mode = False  # single-writer: main
        self._task_seq = 0
        # Gang-boundary ARRIVAL counter (r13, the deadline-bounded gang
        # boundary's per-rank progress signal): group-log entries whose
        # device dispatch this rank has BEGUN.  Incremented immediately
        # before the first (blocking, collective-bearing) device call of
        # each group task, so a rank blocked INSIDE a wedged collective
        # has counted the entry while the straggler that never arrived at
        # it has not — consumption counters (_task_seq, boundary ask seq)
        # cannot make that distinction: lease batching and prep-ahead
        # freeze every rank's consumption at the same value the moment
        # the gang wedges.  Read cross-thread by the liveness beat (int
        # read under the GIL), which is the only RPC still leaving this
        # process while the task loop is blocked in the collective.
        # _gang_last_task guards the count against the in-place transient
        # collective retry (_retry_transient_collective re-dispatches the
        # SAME entry): a retried rank must not drift ahead of its peers,
        # or the deadline would read every HEALTHY rank as the laggard.
        self._gang_dispatched = 0  # single-writer: main (beat reads a recent value)
        self._gang_last_task = -1
        self._ckpt: Optional[CheckpointManager] = None
        # Checkpoint watermark + background-save thread handle: touched by
        # the task loop, the background save thread (failure rollback), and
        # the preemption thread.  The leaf lock makes the hand-off explicit
        # (graftlint lock-discipline); nothing blocking ever runs under it.
        self._ckpt_lock = locksan.lock("Worker._ckpt_lock", leaf=True)  # lock-order: leaf
        self._last_ckpt_step = 0  # guarded-by: _ckpt_lock
        self.reforms = 0  # elastic mesh re-formations (observability/tests)
        self._training_tasks_done = 0  # gates the one-task profiler trace
        # Task-level pipeline: the previous training task's (report, device
        # metrics), fetched + reported only after the NEXT task's steps are
        # dispatched (see _dispatch_training_task for why).
        self._pending: Optional[tuple] = None
        # Prep-ahead pipeline (fused + pipelined mode): a bounded k-deep
        # queue of (task, report, host-prep future) for leased tasks whose
        # host half (bulk read + C++ decode + stacking) is in flight on the
        # prep pool while earlier tasks' transfers stream and metrics
        # settle (see run()).  Depth = config.prep_depth; 1 reproduces the
        # r6 one-slot behavior.  Each prep fans its chunk decodes out to
        # the shared IngestPool (config.ingest_threads).
        self._prep_queue: deque = deque()
        self._prep_pool = None
        # Built eagerly (ThreadPoolExecutor spawns its threads lazily on
        # first submit, so an eval/predict-only job still pays nothing):
        # prep_depth > 1 means _prep_fused_host runs concurrently on prep
        # threads, and a lazy check-then-create there would race into two
        # pools of decode threads competing for the same cores.
        self._ingest = IngestPool(config.ingest_threads)
        # Locally buffered task leases (batched GetTask/GetGroupTask, r9):
        # tasks the master leased in one RPC beyond the one being started.
        # Unstarted leases are returned to the master on preemption or
        # membership change (_abandon_leases) so elasticity semantics stay
        # requeue-on-loss/at-least-once.
        self._leased: deque = deque()
        self._tasks_done = 0
        # Report sequence numbers (r18): every ReportTaskResult carries a
        # per-worker monotone seq so the master can DEDUPE a retried
        # report across its own restart (the proxy's outage ride-through
        # re-sends the in-flight call; the old master may have applied +
        # journaled it before dying).  See MASTER_SCHEMAS.
        self._report_seq = 0
        # Process-incarnation nonce for the reconcile handshake: the
        # master resets a worker's report-seq dedup ledger when the
        # incarnation CHANGES (a fresh process restarts its seq counter
        # at 1).  worker.main passes the one it already registered with;
        # standalone Workers mint their own.
        self._incarnation = (
            incarnation or f"{os.getpid()}-{int(time.time() * 1e3)}"
        )
        # Python-side step counter mirroring state.step: reading the device
        # scalar would drain the dispatch pipeline at every task boundary.
        self._steps_dispatched = 0  # single-writer: main (prep/ckpt threads read a recent value)
        # Set by preemption_snapshot (SIGTERM thread): the task loop parks
        # at its next boundary instead of dispatching more work, so the
        # live state leaves the donated-in-flight window and can be saved.
        # _parked acknowledges the park — once True, the loop only sleeps,
        # so self.state can no longer be donated or reassigned.
        self._preempting = False  # single-writer: thread:preemption
        self._parked = False  # single-writer: main (the preemption thread spin-reads it)
        # Background periodic-checkpoint machinery (_save_snapshot_background
        # / _save_group_snapshot_background)
        self._ckpt_thread = None  # guarded-by: _ckpt_lock
        # graftgauge (r14): the live metrics registry this worker updates
        # from its hot path — counters for examples/steps/tasks, depth
        # gauges and the per-phase families collected at scrape time.
        # An INSTANCE per worker (in-process test fleets run several
        # workers in one process and each must keep its own families);
        # worker.main passes the process-default registry so the one
        # scrape endpoint also serves cross-cutting client-side families
        # (the PS retry counter).  Snapshots ride the Heartbeat/Report
        # ``gauge`` envelope (gauge_payload) so the master's endpoint can
        # aggregate the fleet.
        self.gauges = gauges if gauges is not None else gaugelib.Registry()
        self._g_examples = self.gauges.counter(
            gaugelib.EXAMPLES_TRAINED, "examples trained (records dispatched)"
        )
        self._g_steps = self.gauges.counter(
            gaugelib.STEPS_DISPATCHED, "device steps dispatched"
        )
        self._g_tasks = self.gauges.counter(
            gaugelib.TASKS_DONE, "training/eval/predict tasks completed"
        )
        self.gauges.add_collector(self._collect_gauges)
        # Envelope throttle: the loop heartbeat fires every task-loop
        # iteration (up to 1/poll_interval per second), and a full
        # registry snapshot per beat would be the dominant new
        # per-iteration cost — the fleet view needs ~1 Hz freshness, the
        # same cadence an external scraper would poll at.  Reports
        # (bounded frequency) bypass the throttle so the JSONL mirror
        # never starves.  Benign race between the loop beat and the
        # background liveness beat: worst case one extra snapshot.
        self._gauge_ship_interval_s = 1.0
        # Ship throttle: a cross-thread TOCTOU double-ship is harmless
        # (the fleet view banks the newest snapshot), so single-op
        # atomicity is the whole consistency story.
        self._last_gauge_ship = 0.0  # gil-atomic
        # Per-phase wall decomposition of the task loop (common/metrics.py
        # PhaseTimers); snapshots ride every report so the master and the
        # train-job artifact can attribute the job-vs-bench gap to named
        # phases.  The registry hook adds a per-entry duration histogram
        # per phase (edl_phase_ms) to the live scrape.
        self.phases = PhaseTimers(gauges=self.gauges)
        # grafttrace: --trace turns the per-process span recorder on (every
        # phase above doubles as a span; RPC boundaries, gang waits and
        # elastic transitions add their own).  Bounded slices ship to the
        # master on the heartbeat/report channel; the RTT-midpoint clock
        # offset below is measured against the Heartbeat server stamp so
        # tools/trace_dump.py can align this process onto the master clock.
        if config.trace:
            trace.configure(
                enabled=True, capacity=config.trace_buffer_events
            )
        self._trace_clock_offset_us: Optional[float] = None  # single-writer: main (beat readers tolerate one stale estimate)
        # graftchaos (chaos/inject.py): the --chaos fault plan rides the
        # config bus exactly like --trace; faults address this process by
        # worker id or rank (set_context keeps the rank current across
        # reforms — see _apply_membership).
        if config.chaos:
            chaos.configure(config.chaos)
        chaos.set_context(worker_id=worker_id, rank=self._rank)
        # graftreduce in-step deadline gate (r15, _collective_gate): each
        # dp shard's host-side contribution crosses the gate before a
        # training task dispatches; one that stalls past
        # --collective_deadline_ms is EXCLUDED from the task's
        # collectives (subgroup mask -> trainer.set_active_contributors)
        # instead of holding every other shard.  All state below is
        # task-loop-thread-only (the daemon crossing threads run nothing
        # but the chaos hook crossing and an Event.set); the counters
        # are plain ints read by the heartbeat on the same thread.
        self._collective_pending: Dict[int, Any] = {}  # shard -> stalled crossing
        self._collective_consec: Dict[int, int] = {}  # consecutive exclusions
        self._collective_skips = 0  # cumulative (task, shard) exclusions
        self._g_coll_skips = self.gauges.counter(
            "edl_collective_skip_total",
            "in-collective straggler exclusions (task x shard) charged by "
            "the r15 in-step deadline gate",
        )
        self._g_coll_subgroup = self.gauges.gauge(
            "edl_collective_subgroup_size",
            "contributors the current training collectives reduce over "
            "(world size minus in-step exclusions)",
        )
        self._g_coll_bytes = self.gauges.counter(
            "edl_collective_interhost_bytes_total",
            "analytic per-replica inter-host bytes of the dense-grad "
            "all-reduce (collectives.interhost_bytes_per_step's model)",
        )
        # Analytic inter-host bytes per step under the resolved topology;
        # computed lazily at the first dispatch (needs the placed params)
        # and invalidated per mesh re-formation.
        self._collective_step_bytes: Optional[int] = None

        if config.checkpoint_dir:
            self._ckpt = CheckpointManager(
                config.checkpoint_dir, keep_max=config.keep_checkpoint_max
            )

    # ---- membership / elasticity ----

    def _mesh_size(self, world_size: int) -> int:
        return max(1, min(world_size * self._dpw, len(self._pool)))

    def _advertised_address(self) -> str:
        if not self.config.multihost:
            return ""
        from elasticdl_tpu.parallel.distributed import advertised_address

        return advertised_address()

    def _apply_membership(self, membership: dict, initial: bool = False) -> None:
        version = membership["version"]
        if version == self._membership_version:
            return
        if not initial and dict(membership["ranks"]) == self._ranks and (
            dict(membership.get("addresses") or {}) == self._addresses
        ):
            # Version churn with IDENTICAL topology: a peer's restart cycle
            # bumps the version twice (stale-incarnation eviction, then
            # re-registration) and can net out to exactly the membership
            # this worker already runs.  Restarting on the NUMBER alone made
            # two workers ping-pong restarts forever (each restart causing
            # the next bump); the world is defined by ranks+addresses, so
            # adopt the version and keep the world.
            #
            # Accepted hazard: ranks+addresses cannot distinguish a
            # RELAUNCHED peer on the same host from the incarnation this
            # worker's jax world actually spans, so adoption can briefly
            # keep a world whose peer process is new.  That wedge is
            # BOUNDED: the next collective aborts on the coordination
            # heartbeat (--distributed_heartbeat_timeout_s) and the restart
            # path re-forms.  Comparing per-worker incarnation nonces
            # instead would close the wedge but re-open the ping-pong (a
            # restart always bumps its own nonce, forcing the peer to
            # restart, which bumps again...), which does NOT self-heal —
            # the bounded wedge is the better failure mode.
            logger.info(
                "membership v%d has identical topology; adopting without "
                "re-forming", version,
            )
            self._membership_version = version
            return
        world = max(membership["world_size"], 1)
        prev_ranks = self._ranks
        self._ranks = dict(membership["ranks"])
        self._addresses = dict(membership.get("addresses") or {})
        self._rank = self._ranks.get(self.worker_id, 0)
        # Rank-addressed chaos faults must follow the rank across reforms.
        chaos.set_context(rank=self._rank)
        self._group_mode = self.config.multihost and len(self._ranks) > 1
        if self.config.multihost and not initial:
            # The jax.distributed world is fixed per process (PJRT can't be
            # re-formed in-process): snapshot, then restart.  The pod
            # manager relaunches RESTART exits without burning the relaunch
            # budget; the fresh process joins the new world at startup and
            # resumes from the checkpoint (the reference's elastic-Horovod
            # re-rendezvous, done the process way).
            #
            # The snapshot must come from a SURVIVOR of the previous
            # membership — a newly joined worker can take new-rank 0 with no
            # state, and gating on new rank would then silently lose all
            # progress since the last periodic checkpoint.  The lowest
            # previous-rank worker still present in the new membership saves.
            #
            # Only when the OLD world was single-process, though: in a
            # multi-process world every Orbax save is a COLLECTIVE (all
            # processes barrier; the primary writes), and the very reason the
            # membership changed is usually that a peer died — a lone
            # snapshot would deadlock in the barrier.  Multi-process worlds
            # rely on their periodic checkpoints (which are collective).
            was_group = self.config.multihost and len(prev_ranks) > 1
            survivors = set(prev_ranks) & set(self._ranks)
            saver = (
                min(survivors, key=lambda w: prev_ranks[w]) if survivors else None
            )
            if (
                not was_group
                and self._ckpt is not None
                and self.worker_id == saver
                and self.state is not None
            ):
                try:
                    # A background periodic save may be mid-flight on the
                    # same manager; interleaving two saves tears both.
                    self._join_ckpt()
                    step = int(self.state.step)
                    # host_state: the CANONICAL layout — a dp-sharded
                    # optimizer state must land on disk topology-agnostic,
                    # the relaunch may join a different world size.
                    self._ckpt.save(
                        step, self.trainer.host_state(self.state), wait=True
                    )
                    # Relaunched processes restore from the LOCAL checkpoint
                    # directory at startup (run()'s newest-restorable walk);
                    # this snapshot makes the resume point the pre-restart
                    # step instead of the last PERIODIC checkpoint.  The
                    # report is observability (JobStatus / metrics stream).
                    self.master.call(
                        "ReportCheckpoint",
                        {"path": self._ckpt.directory, "step": step},
                    )
                except Exception:
                    # A broken runtime must not block the restart itself —
                    # the periodic checkpoint covers the resume.
                    logger.exception("pre-restart snapshot failed; restarting anyway")
            trace.instant(
                "elastic:restart_required", cat="elastic",
                version=version, world=world,
            )
            raise WorkerRestartRequired(
                f"membership v{version}: world changed to {world} hosts"
            )
        n_dev = self._mesh_size(world)
        dcn = self.config.dcn_data_parallelism
        if dcn > 1 and n_dev % dcn != 0:
            # Training availability beats layout: an elastic resize can land
            # on a device count the configured hierarchy no longer divides
            # (dcn=2 after shrinking to 3 hosts) — fall back to the flat
            # mesh instead of crash-looping the relaunch budget away.
            # Checked HERE (not via exception) so a genuine too-few-devices
            # ValueError below keeps its own story.
            logger.warning(
                "dcn_data_parallelism=%d does not divide %d devices; "
                "falling back to a flat 1-D mesh",
                dcn, n_dev,
            )
            dcn = 1
        tp_conf = int(getattr(self.config, "tensor_parallelism", 1))
        if tp_conf > 1:
            # Hybrid-parallel (r20): reform picks a LEGAL 2D shape for the
            # live device count — tp preserved (the weight shards must keep
            # fitting one device), dp shrinks first; tp only degrades along
            # its divisor chain when fewer than tp devices remain
            # (mesh.resolve_2d_shape).  The r13/r15 deadline layers sit
            # ABOVE this choice unchanged: gang membership decides n_dev,
            # this just decides its factorization.
            dp, tp = resolve_2d_shape(n_dev, tp_conf)
            if dp * tp != n_dev:
                logger.warning(
                    "tensor_parallelism=%d: %d devices factor to dp=%d x "
                    "tp=%d; %d device(s) idle until the next reform",
                    tp_conf, n_dev, dp, tp, n_dev - dp * tp,
                )
            mesh = create_mesh(
                self._pool, num_devices=dp * tp, tensor_parallelism=tp
            )
        else:
            mesh = create_mesh(
                self._pool, num_devices=n_dev, dcn_parallelism=dcn
            )
        if initial or self.trainer is None:
            self.trainer = Trainer(self.spec, self.config, mesh)
        elif (
            list(self.trainer.mesh.devices.flat) == list(mesh.devices.flat)
            and self.trainer.mesh.shape == mesh.shape
        ):
            # Identical mesh: a non-multihost pool worker sees peers join
            # and leave without its LOCAL device set ever changing
            # (n_dev = min(world*dpw, len(pool)) saturates), and
            # re-sharding state onto the same devices is pure churn — a
            # dropped dispatch pipeline at best, and on the 1-real-cpu-
            # device harness an XLA:CPU crash at worst (the chaos bench's
            # pool fleets segfaulted HERE on every peer churn before this
            # guard).  Adopt the version; keep the trainer.
            logger.info(
                "membership v%d keeps this worker's mesh (%d devices); "
                "adopting without re-forming", version, mesh.devices.size,
            )
        else:
            self.reforms += 1
            old_dp, old_tp = mesh_shape(self.trainer.mesh)
            new_dp, new_tp = mesh_shape(mesh)
            logger.info(
                "membership v%d -> re-forming mesh to %d devices "
                "(dp%dxtp%d -> dp%dxtp%d)",
                version, mesh.devices.size, old_dp, old_tp, new_dp, new_tp,
            )
            trace.instant(
                "elastic:reform", cat="elastic",
                version=version, devices=int(mesh.devices.size),
                old_shape=f"{old_dp}x{old_tp}",
                new_shape=f"{new_dp}x{new_tp}",
            )
            self.trainer.set_mesh(mesh)
            self._replace_state()
        self._membership_version = version

    def _replace_state(self) -> None:
        """Re-place state on the re-formed mesh: restore the latest checkpoint
        if one exists (the reference's recover-from-snapshot path), else
        re-shard the live state (pure in-process resize).

        Both paths bridge through the trainer's CANONICAL host layout
        (``host_state``), so a dp-sharded optimizer state is
        REDISTRIBUTED across the new world size — a 4->8->4 resize moves
        the existing Adam moments, it never re-initializes them."""
        assert self.trainer is not None
        restored = None
        # Settle any in-flight BACKGROUND save first: latest_step() must not
        # see a step whose host-store half is still being written (the
        # bg thread runs the whole trio outside Orbax's own wait scope).
        self._join_ckpt()
        if self._ckpt is not None and self._ckpt.latest_step() is not None:
            self._ckpt.wait()
            template = self.trainer.shard_state(
                self.trainer.host_state(self.state)
            )
            restored = self._restore_checkpoint(template)
            try:
                self.trainer.restore_host_stores(
                    self._ckpt.directory, int(restored.step)
                )
            except FileNotFoundError:
                # In-process resize: the LIVE host stores survive in this
                # trainer, so a missing snapshot is tolerable (slightly newer
                # rows than the restored dense step) — log, don't die.
                logger.warning(
                    "no host-store snapshot for step %d; keeping live rows",
                    int(restored.step),
                )
            logger.info("restored checkpoint step %d", int(restored.step))
        if restored is None:
            restored = self.trainer.shard_state(
                self.trainer.host_state(self.state)
            )
        self.state = restored
        # graftreduce (r15): the mesh changed, so the contributor set and
        # the analytic inter-host bytes/step change with it.  Stalled
        # contributions of the OLD mesh are dropped (their futures run
        # out harmlessly on the gate pool) and the mask is all-active
        # again (trainer._adopt_mesh_axes already reset it).
        self._collective_pending.clear()
        self._collective_consec.clear()
        self._collective_step_bytes = None

    def _restore_checkpoint(self, state_like, step: Optional[int] = None):
        """Restore a checkpoint step into the live mesh AND optimizer
        layout.  Checkpoints always hold the canonical (unsharded)
        optimizer leaves; restore_template aims the read at param-shaped
        replicated targets when the live layout is dp-sharded, and
        adopt_restored lays the result back out flat over the shard axis.
        Replicated mode degenerates to the old direct restore-into-mesh
        path."""
        restored = self._ckpt.restore(
            self.trainer.restore_template(state_like), step=step
        )
        return self.trainer.adopt_restored(restored)

    # thread-role: thread:heartbeat — the beat thread (worker.main _beat)
    # reaches this through the worker holder dict, a hand-off the static
    # resolver cannot see.
    def death_watch_tick(
        self, state: dict, now: float, master_version=None
    ) -> bool:
        """One death-push decision (called from the liveness-heartbeat
        thread, worker.main): return True when this process must force-exit
        RESTART because a gang peer DIED while the main thread is wedged in
        a blocked collective.

        The main thread only notices membership changes at task boundaries
        (``_check_membership``); a survivor blocked in a collective on a
        dead peer otherwise waits out the jax.distributed coordination
        heartbeat (``--distributed_heartbeat_timeout_s``, default 30 s —
        VERDICT r4 Weak #3 measured this as the avoidable middle of the
        25.7 s re-rendezvous).  The master's reaper already knows within
        ~3 s; this push closes the gap: poll the master's version, and when
        a previous member has DEPARTED and the main thread still hasn't
        applied the change after ``death_push_grace_s``, exit now.

        Deliberately narrow:
        - pure JOINS never force-exit (the running task completes; the main
          loop restarts gracefully at the boundary — aborting would waste
          its work);
        - identical-topology churn never force-exits (the adoption path,
          see ``_apply_membership``);
        - the grace window lets an unblocked main thread win the race and
          do the snapshot-then-restart path;
        - only group mode (world > 1): a lone worker has no collective to
          be stuck in.

        ``state`` carries ``pending_since`` between ticks; it must be reset
        by the caller if the worker restarts in place.
        """
        if not self._group_mode or self.config.death_push_grace_s <= 0:
            state["pending_since"] = None
            return False
        if (
            master_version is not None
            and master_version == self._membership_version
        ):
            # The caller's own Heartbeat response already proves nothing
            # changed — skip the GetMembership RPC (the steady-state path,
            # so the push costs zero extra control-plane load).
            state["pending_since"] = None
            return False
        try:
            membership = self.master.call("GetMembership", {})
        except Exception:
            return False  # master briefly unreachable: retry next beat
        if membership["version"] == self._membership_version:
            state["pending_since"] = None
            return False
        same_topology = dict(membership["ranks"]) == self._ranks and dict(
            membership.get("addresses") or {}
        ) == self._addresses
        departed = set(self._ranks) - set(membership["ranks"])
        if same_topology or not departed:
            state["pending_since"] = None
            return False
        since = state.get("pending_since")
        if since is None:
            state["pending_since"] = now
            return False
        if now - since < self.config.death_push_grace_s:
            return False
        logger.warning(
            "death push: peer(s) %s departed (membership v%s vs applied "
            "v%s) and the main thread has not re-formed within %.1fs — "
            "assuming a blocked collective; forcing RESTART now",
            sorted(departed), membership["version"],
            self._membership_version, self.config.death_push_grace_s,
        )
        return True

    # thread-role: thread:heartbeat — ditto: invoked from the beat thread
    # via the worker holder.
    def gang_beat_fields(self) -> dict:
        """Fields the background liveness beat (worker.main ``_beat``)
        adds to its Heartbeat so the deadline-bounded gang boundary keeps
        seeing per-rank arrival progress while the task loop is blocked
        inside a wedged collective — the loop's own heartbeat (the other
        carrier) is silent exactly then.  Plain int/None reads under the
        GIL; safe from the beat thread."""
        if not self._group_mode:
            return {}
        return {
            "gang_seq": self._gang_dispatched,
            "version": self._membership_version,
        }

    def _collect_gauges(self) -> None:
        """Scrape-time collector (never the task loop): pull-model
        families that are cheap to READ — depths are GIL-safe ``len``s,
        the phase families re-publish ``PhaseTimers`` cumulative state —
        refreshed per scrape/snapshot instead of being pushed per
        update."""
        g = self.gauges
        g.gauge("edl_membership_version", "applied membership version").set(
            float(self._membership_version)
        )
        g.gauge("edl_rank", "rank in the current membership").set(
            float(self._rank)
        )
        g.gauge("edl_reforms_total", "elastic mesh re-formations").set(
            float(self.reforms)
        )
        g.gauge(
            gaugelib.LEASE_DEPTH, "locally buffered task leases"
        ).set(float(len(self._leased)))
        g.gauge(
            gaugelib.PREP_QUEUE_DEPTH, "prep-ahead tasks in flight"
        ).set(float(len(self._prep_queue)))
        if self._group_mode:
            g.gauge(
                "edl_gang_dispatched",
                "gang-boundary arrivals (lockstep entries begun)",
            ).set(float(self._gang_dispatched))
        if self.trainer is not None:
            # Current subgroup size from the trainer's live mask (reads
            # correctly even when the gate never armed: all-active).
            self._g_coll_subgroup.set(
                float(self.trainer.active_contributors().sum())
            )
            # The live mesh's (dp, tp) shape (mesh.mesh_shape — a 1-D mesh
            # reads dp=n, tp=1), one sample per axis; watch_job renders
            # the pair as its "mesh: dpNxtpM" line.
            dp, tp = mesh_shape(self.trainer.mesh)
            for ax, val in (("dp", dp), ("tp", tp)):
                g.gauge(
                    "edl_mesh_shape",
                    "current mesh extent per axis (dp=data, tp=model)",
                    labels={"axis": ax},
                ).set(float(val))
        for name, secs in self.phases.snapshot().items():
            g.gauge(
                "edl_phase_seconds_total",
                "cumulative seconds per task-loop phase",
                labels={"phase": name},
            ).set(secs)
        for name, n in self.phases.counts().items():
            g.gauge(
                "edl_phase_entries_total",
                "entries per task-loop phase",
                labels={"phase": name},
            ).set(float(n))

    # thread-role: thread:heartbeat — also shipped by the beat thread
    # (besides the loop heartbeat and checkpoint reports).
    def gauge_payload(self, force: bool = False) -> Optional[dict]:
        """The Heartbeat/Report ``gauge`` envelope: this worker's full
        registry snapshot (collectors run, so depths and phase families
        are fresh).  None when the registry is disabled, or — unless
        ``force`` — when one shipped within the last
        ``_gauge_ship_interval_s`` (the loop heartbeat fires every
        iteration; the fleet view needs ~1 Hz).  Called from
        control-plane boundaries only — the heartbeat in
        ``_check_membership``, the background liveness beat, checkpoint
        reports (forced: the JSONL mirror rides them) — never a
        ``# hot-path`` function (gauge-discipline)."""
        if not self.gauges.enabled:
            return None
        now = time.monotonic()
        if not force and now - self._last_gauge_ship < self._gauge_ship_interval_s:
            return None
        self._last_gauge_ship = now
        return {"families": self.gauges.snapshot()}

    def _trace_payload(self) -> Optional[dict]:
        """One bounded slice of this process's trace ring for the
        heartbeat/report channel, with the latest clock-offset estimate —
        or None when tracing is off or the buffer is empty.  Draining here
        (a control-plane boundary, NOT a ``# hot-path`` function) is
        exactly the split the trace-discipline lint rule enforces."""
        rec = trace.default()
        if not rec.enabled:
            return None
        events = rec.drain_slice(trace.SHIP_BATCH)
        if not events:
            return None
        payload: dict = {"events": events, "dropped": rec.dropped}
        if self._trace_clock_offset_us is not None:
            payload["clock_offset_us"] = self._trace_clock_offset_us
        return payload

    def _held_task_ids(self) -> List[int]:
        """Every training-task id this worker still HOLDS: buffered
        leases, queued preps, and the pipelined pending slot — the
        reconcile handshake's inventory.  Task-loop thread only."""
        held: List[int] = []
        for entry in self._leased:
            t = entry.get("task")
            if t:
                held.append(int(t["task_id"]))
        held.extend(task.task_id for task, _r, _f in self._prep_queue)
        if self._pending is not None:
            held.append(int(self._pending[0]["task_id"]))
        return held

    def _reconcile_with_master(self) -> None:
        """Post-outage handshake (r18): the proxy just rode out a master
        restart — re-register (the rendezvous is fresh) declaring the
        leases this worker holds, so the restarted master requeues its
        journal-replayed ``doing`` entries we DON'T hold and tells us
        which held ones IT no longer attributes to us (``stale_tasks`` —
        dropped unstarted here; training them would double-train records
        the master already re-leased).  Group mode declares nothing: the
        lockstep log owns gang leases, and its version-keyed
        invalidation requeues them master-side."""
        held = [] if self._group_mode else self._held_task_ids()
        resp = self.master.call(
            "RegisterWorker",
            {
                "worker_id": self.worker_id,
                "address": self._advertised_address(),
                "proto": PROTOCOL_VERSION,
                "incarnation": self._incarnation,
                "held_tasks": held,
            },
        )
        stale = {int(t) for t in resp.get("stale_tasks") or []}
        dropped = 0
        if stale and not self._group_mode:
            kept = deque()
            for entry in self._leased:
                t = entry.get("task")
                if t and int(t["task_id"]) in stale:
                    dropped += 1
                    continue
                kept.append(entry)
            self._leased = kept
            kept_prep: deque = deque()
            for task, report, fut in self._prep_queue:
                if task.task_id in stale:
                    fut.cancel()
                    dropped += 1
                    continue
                kept_prep.append((task, report, fut))
            self._prep_queue = kept_prep
        trace.instant(
            "worker:reconcile", cat="elastic",
            held=len(held), stale=len(stale), dropped=dropped,
            version=resp.get("version"),
        )
        logger.info(
            "reconciled with restarted master: declared %d held lease(s), "
            "dropped %d stale", len(held), dropped,
        )

    def _check_membership(self) -> None:
        # Post-outage reconcile FIRST (r18): the proxy flags the first
        # successful call after a ridden-out master outage, and the lease
        # inventory must reach the restarted master before this loop
        # leases anything new against its replayed queues.
        take = getattr(self.master, "take_reconnected", None)
        if take is not None and take():
            self._reconcile_with_master()
        # The heartbeat carries the version this worker has APPLIED: the
        # master's lockstep task log withholds collective tasks until every
        # member confirms the current topology (see RendezvousServer).
        hb = {"worker_id": self.worker_id, "version": self._membership_version}
        if self._collective_skips:
            # Cumulative in-collective exclusions (r15 gate): the master
            # banks the newest value per worker — the same bounded-skip
            # ledger the r13 boundary deadline charges (JobStatus
            # ``collective_skips``).
            hb["collective_skips"] = self._collective_skips
        if self._group_mode:
            # Gang-boundary arrival for the deadline-bounded boundary
            # (r13): entries whose dispatch this rank has BEGUN (see
            # _gang_dispatched in __init__).  Also carried by the
            # background liveness beat (gang_beat_fields) — this loop
            # heartbeat stops the moment the loop blocks inside a wedged
            # collective, which is exactly when the signal matters.
            hb["gang_seq"] = self._gang_dispatched
        if self._group_mode and self._rank != 0:
            # Non-rank-0 members never send task reports (rank-0-gated in
            # _flush), so the heartbeat carries their phase snapshot —
            # without it the master's per-worker decomposition only ever
            # held rank 0, and a straggler rank (prep is per-process-local
            # and CAN diverge) was invisible to the very instrument built
            # to see it.
            hb["phase_times"] = self.phases.snapshot()
            hb["phase_counts"] = self.phases.counts()
        tp = self._trace_payload()
        if tp is not None:
            hb["trace"] = tp
        gp = self.gauge_payload()
        if gp is not None:
            hb["gauge"] = gp
        t0_us = trace.now_us()
        resp = self.master.call("Heartbeat", hb)
        t1_us = trace.now_us()
        server_ts = resp.get("server_ts_us")
        if server_ts is not None:
            # RTT-midpoint clock alignment: assume the server stamped its
            # clock halfway through the round trip, so (master - worker) ~=
            # server_ts - (t0+t1)/2.  Error is bounded by RTT asymmetry —
            # microseconds in-cluster, and the next beat refreshes it.
            self._trace_clock_offset_us = server_ts - (t0_us + t1_us) / 2.0
        if not self._group_mode and resp.get("draining"):
            # Max-steps drain: buffered leases AND undispatched prepped
            # tasks carry no device work yet — return them all (requeue-
            # flagged; the STOPPED dispatcher drops them, so nothing
            # trains past the limit).  Overshoot shrinks to the tasks
            # already dispatched, the pre-lease pipeline bound.
            self._abandon_prep()
            self._abandon_leases()
        elif (
            resp.get("eval_pending")
            and self._leased
            and not self._group_mode
        ):
            # A pending eval round preempts training tasks; buffered
            # leases would delay it by up to lease_batch-1 tasks of
            # version skew.  Return them (immediate requeue) so the next
            # lease RPC pulls the eval task first — prepped tasks keep
            # their decode investment and still train, exactly the
            # pre-r9 preemption granularity.  Group mode is exempt from
            # both hints: the lockstep log already fixes the global
            # order.
            self._abandon_leases()
        if resp["version"] != self._membership_version:
            # Settle the in-flight pipelined tasks before re-forming: a
            # multihost change raises WorkerRestartRequired out of
            # _apply_membership, and an unflushed report would leave the
            # master waiting out the task timeout to requeue.  The prepped
            # tasks (if any) dispatch on the OLD mesh first — their state
            # is settled before the re-form.  Locally buffered leases, by
            # contrast, have no work invested: return them to the master
            # NOW (immediate requeue) rather than carrying them across a
            # membership whose lease the master may already have
            # invalidated.
            self._drain_prep()
            self._abandon_leases()
            membership = self.master.call("GetMembership", {})
            self._apply_membership(membership)

    # ---- checkpointing ----

    # hot-path: runs at every task boundary; the step mirror below exists
    # precisely so this never reads the device
    def _maybe_checkpoint(self) -> None:
        if self._ckpt is None or self.config.checkpoint_steps <= 0:
            return
        # The python-side step mirror, NOT int(self.state.step): reading the
        # device scalar drains the whole dispatch pipeline at the boundary —
        # exactly the stall the background save exists to remove.  The
        # mirror equals the step the live state settles to (every dispatched
        # step applies to it), which is the step the snapshot will carry.
        step = self._steps_dispatched
        with self._ckpt_lock:
            behind = step - self._last_ckpt_step
        if behind < self.config.checkpoint_steps:
            return
        with self.phases.phase("checkpoint"):
            if self._group_mode:
                self._save_group_snapshot_background(step)
            elif self._rank == 0:
                self._save_snapshot_background(step)

    def _save_snapshot(self, step: int, wait: bool = False, state=None) -> None:
        """The non-group save trio: Orbax dense state + host-store shards +
        master report.  One definition so the periodic checkpoint and the
        preemption snapshot cannot drift apart.  ``state`` lets the
        preemption path save its single captured reference."""
        state = self.state if state is None else state
        # Canonical layout on disk (trainer.host_state): restores must work
        # into a DIFFERENT world size / optimizer_sharding mode.
        self._ckpt.save(step, self.trainer.host_state(state), wait=wait)
        self.trainer.save_host_stores(self._ckpt.directory, step)
        if wait:
            # Publish LAST: the manifest is the serving watcher's only
            # trigger, so it must name a step whose Orbax commit AND
            # host-store snapshot are both complete (publish drains any
            # in-flight async save before writing).  The wait=False caller
            # (none today) would publish at its own completion point.
            self._ckpt.publish(step)
        with self._ckpt_lock:
            self._last_ckpt_step = step
        self.master.call(
            "ReportCheckpoint", self._checkpoint_report(step)
        )

    def _checkpoint_report(self, step: int) -> dict:
        """The ReportCheckpoint payload: path/step plus the phase snapshot
        AND a trace slice — checkpoint reports are the "Report" half of the
        heartbeat/report trace-shipping channel (the last word a finishing
        worker sends, so the tail of its buffer rides out here)."""
        report = {
            "path": self._ckpt.directory,
            "step": step,
            "worker_id": self.worker_id,
            "phase_times": self.phases.snapshot(),
            "phase_counts": self.phases.counts(),
        }
        tp = self._trace_payload()
        if tp is not None:
            report["trace"] = tp
        # Forced past the ship throttle: checkpoint reports are the JSONL
        # gauge mirror's carrier (bounded frequency by construction).
        gp = self.gauge_payload(force=True)
        if gp is not None:
            report["gauge"] = gp
        return report

    def _join_ckpt(self, timeout: float = None) -> None:
        with self._ckpt_lock:
            t = self._ckpt_thread
        if t is not None and t.is_alive():
            t.join(timeout)  # outside the lock: the join itself may block

    # hot-path: dispatch-only by design — the whole point is that the
    # boundary pays a dispatch RTT, never a drain
    def _snapshot_state(self):
        """ONE jitted device-side copy of the live state in the CANONICAL
        optimizer layout (trainer.snapshot_state): fresh buffers no later
        step can donate (copy_to_host_async on the live state would race
        donation), and group-mode collective Orbax saves — which stream
        the device arrays straight to disk — therefore write the
        topology-agnostic checkpoint format even when the live optimizer
        state is dp-sharded.  Dispatch-only, so the caller pays ~a
        dispatch RTT, not a pipeline drain."""
        return self.trainer.snapshot_state(self.state)

    def _save_snapshot_background(self, step: int) -> None:
        """Periodic checkpoint OFF the task loop's critical path.

        The synchronous trio stalls training for the whole state D2H —
        ~165 MB for the flagship table+moments, 15-60 s over the tunneled
        chip's bimodal link (measured: the r5 train-job timeline showed a
        58 s gap at every checkpoint boundary).  Instead: ONE jitted
        device-side copy of the state (``_snapshot_state``), then the
        device_get + save trio runs on a background thread while training
        continues.  Saves are serialized (join before starting the next); a
        failed background save logs loudly and rolls the watermark back so
        the next boundary retries."""
        self._join_ckpt()
        snap = self._snapshot_state()
        with self._ckpt_lock:
            prev_watermark, self._last_ckpt_step = self._last_ckpt_step, step

        def _bg():
            try:
                with self.phases.phase("checkpoint_bg"):
                    self._save_snapshot(step, wait=True, state=snap)
            except Exception:
                logger.exception(
                    "background checkpoint at step %d failed; next "
                    "boundary retries", step,
                )
                with self._ckpt_lock:
                    self._last_ckpt_step = prev_watermark

        t = threading.Thread(target=_bg, name="edl-ckpt", daemon=True)
        with self._ckpt_lock:
            self._ckpt_thread = t
        t.start()

    def _save_group_snapshot_background(self, step: int) -> None:
        """Group-mode periodic checkpoint OFF the lockstep task loop.

        r5 ran the collective Orbax save synchronously at the boundary:
        every rank stalled for the full shard D2H + write + cross-process
        commit barrier — the gang-mode twin of the 58 s single-process gap
        that motivated ``_save_snapshot_background`` (VERDICT r5 Missing
        #1).  Now the boundary pays only the jitted device-side copy (plus
        the join of a still-in-flight PREVIOUS save), and the shard D2H +
        write + commit-barrier join run on a background thread.  Orbax
        saves stay COLLECTIVE — every process must participate — and they
        still do: all ranks walk the same lockstep seq with the same step
        watermark, so every rank starts its background save at the same
        boundary and the collective forms in the background symmetrically.

        Failure policy DIFFERS from the single-process path deliberately:
        the watermark is NOT rolled back.  A per-rank rollback would
        diverge the gang's save schedule — the failed rank would retry a
        collective save its peers never join, wedging it in the commit
        barrier.  A failed group save logs loudly and the NEXT boundary
        (same watermark arithmetic on every rank) writes a fresh step; a
        torn step is skipped by the restore walk.
        """
        self._join_ckpt()
        snap = self._snapshot_state()
        with self._ckpt_lock:
            self._last_ckpt_step = step

        def _bg():
            try:
                with self.phases.phase("checkpoint_bg"):
                    self._ckpt.save(step, snap, wait=True)
                    if self._rank == 0:
                        # Host-tier PS snapshot: ONE process fans the Save
                        # out to the PS shards (each dumps its own slice);
                        # plain RPC — not collective — so the rank gate
                        # cannot deadlock the group.
                        self.trainer.save_host_stores(
                            self._ckpt.directory, step
                        )
                        # Collective save committed (wait=True above) and
                        # host shards dumped: rank 0 publishes for serving.
                        self._ckpt.publish(step)
                        self.master.call(
                            "ReportCheckpoint", self._checkpoint_report(step)
                        )
            except Exception:
                logger.exception(
                    "group background checkpoint at step %d failed; the "
                    "next boundary saves (watermark kept — a per-rank "
                    "rollback would desync the gang's collective saves)",
                    step,
                )

        t = threading.Thread(target=_bg, name="edl-ckpt", daemon=True)
        with self._ckpt_lock:
            self._ckpt_thread = t
        t.start()

    # thread-role: thread:preemption — runs on the SIGTERM handler's
    # graceful-exit thread (worker.main), reached via the worker holder.
    def preemption_snapshot(self) -> bool:
        """Best-effort state save on SIGTERM (k8s preemption grace window).

        Returns True when a snapshot was written.  Deliberately narrow:
        - group mode never solo-saves (Orbax saves are COLLECTIVE in a
          multi-process world — see ``_maybe_checkpoint`` — and the gang
          is being preempted precisely when peers may already be gone);
          the fleet relies on its periodic collective checkpoints, and
          the fast RESTART exit is itself the win (peers re-form without
          waiting out heartbeats).
        - non-rank-0 workers never solo-save either (same shared-dir gate
          as ``_maybe_checkpoint``: a node drain preempting several
          workers at once must not race Orbax commits in one directory).
        - a state still donated-in-flight after the park window is
          skipped: the periodic checkpoint covers the resume rather than
          risking a read of consumed buffers.
        Runs on the preemption thread, not in the signal handler frame.
        """
        self._preempting = True  # parks the task loop at its next boundary
        # FIRST, before anything can block: a preempting process has
        # PREEMPTION_EXIT_S to live, so every remaining master RPC (this
        # thread's pending flush, the parked loop's abandons) must fail
        # fast-ish instead of parking in the r18 outage backoff — a
        # snapshot forfeited to a 120 s reconnect wait would be the exact
        # pre-r18 behavior regression.
        limit = getattr(self.master, "limit_outage_tolerance", None)
        if limit is not None:
            limit(2.0)
        trace.instant("elastic:preempt", cat="elastic", rank=self._rank)
        if (
            self._group_mode
            or self._rank != 0
            or self._ckpt is None
            or self.state is None
        ):
            if self._group_mode:
                # The fleet's resume point IS the periodic collective
                # checkpoint; an in-flight background group save must not be
                # torn by os._exit if it can finish inside the grace window.
                # Bounded: a save wedged on already-dead peers will never
                # complete, and the hard PREEMPTION_EXIT_S timer still owns
                # the exit.
                self._join_ckpt(timeout=5.0)
            logger.info(
                "preemption snapshot skipped (group=%s rank=%d ckpt=%s "
                "state=%s)",
                self._group_mode, self._rank, self._ckpt is not None,
                self.state is not None,
            )
            return False
        from elasticdl_tpu.parallel.trainer import _state_alive

        # Wait for the task loop to ACKNOWLEDGE the park: once _parked is
        # set the loop only sleeps, so self.state can no longer be donated
        # or reassigned under us.  Under continuous dispatch the state
        # spends most wall-clock donated into the in-flight step, so this
        # is the common path, bounded well inside the grace window.
        deadline = time.time() + 5.0
        while not self._parked and time.time() < deadline:
            time.sleep(0.05)
        if not self._parked:
            # The park is REQUIRED, not best-effort: a main thread merely
            # blocked in a master RPC (mass preemption is exactly when the
            # master is slow) resumes its iteration after we give up —
            # donating a state we captured as live and racing our
            # _flush_pending on the self._pending slot (duplicate or torn
            # report).  No snapshot then; the RESTART exit still happens
            # and the relaunch resumes from the last periodic checkpoint.
            logger.warning(
                "preemption snapshot skipped (task loop never parked "
                "within 5s — likely blocked in a master RPC)",
            )
            return False
        # Single capture: the parked loop only sleeps, so this reference
        # cannot be donated or reassigned under us.
        state = self.state
        if state is None or not _state_alive(state):
            logger.info("preemption snapshot skipped (state in flight)")
            return False
        # The pipelined previous task's report is already reflected in
        # this state; report it now or the master waits out the task
        # timeout and REQUEUES work the snapshot already contains
        # (double-applied examples on resume).
        try:
            self._flush_pending()
        except Exception:
            logger.exception("preemption flush of pending report failed")
        step = int(state.step)  # settles the in-flight dispatch
        try:
            # A background periodic save may be mid-flight; settle it first
            # (bounded inside the grace window) — both the same-step
            # collision check and a fresh save need it durable.
            self._join_ckpt(timeout=10.0)
            with self._ckpt_lock:
                bg = self._ckpt_thread
            if bg is not None and bg.is_alive():
                # Still saving after the bounded join: a fresh save here
                # would interleave with it on the same manager/step dirs
                # (tearing both), and waiting longer blows the grace
                # window.  Report no durable snapshot; os._exit tears the
                # in-flight write, whose step the torn-pair restore walk
                # skips — resume falls back to the last durable step.
                logger.warning(
                    "preemption: background checkpoint still in flight "
                    "after 10s join; exiting without a fresh snapshot",
                )
                return False
            with self._ckpt_lock:
                saved_this_step = self._last_ckpt_step == step
            if saved_this_step:
                # The flush above crossed the periodic-checkpoint threshold
                # and already saved THIS step (async): saving again would
                # collide on the step dir, and exiting now would tear the
                # in-flight write — settle it instead.
                self._ckpt.wait()
            else:
                self._save_snapshot(step, wait=True, state=state)
        except Exception:
            # Dense may have landed while host stores/report failed; the
            # torn-pair walk at restore refuses a dense-only step, so a
            # partial write degrades to the previous checkpoint.
            logger.exception("preemption snapshot incomplete")
            return False
        logger.info("preemption snapshot at step %d", step)
        return True

    # ---- profiling ----

    def _maybe_start_profile(self):
        """Trace the SECOND training task (the first pays compilation) into
        ``config.profile_dir`` with ``jax.profiler`` — the reference's
        TF-profiler-hook role (SURVEY.md §5 "Tracing/profiling").  Counts
        training tasks only, so interleaved eval/predict tasks neither skip
        the trace nor shift it onto a compiling step."""
        if not self.config.profile_dir or self._training_tasks_done != 1:
            return False
        try:
            jax.profiler.start_trace(self.config.profile_dir)
            logger.info("profiling this task into %s", self.config.profile_dir)
            return True
        except Exception:
            logger.exception("profiler start failed")
            return False

    # ---- task execution ----

    def _read_records(self, shard):
        """Shard records, packed (one bulk C++ read — data/packed.py) when
        the reader offers it, else a plain list."""
        fast = getattr(self.reader, "read_records_packed", None)
        if fast is not None:
            records = fast(shard)
            if records is not None:
                return records
        return list(self.reader.read_records(shard))

    def _stack_full_minibatches(self, records, mb: int, n_full: int):
        """Feed + stack every full minibatch into ONE [T, mb, ...] host
        batch (the fused-scan wire format); shared by the training prep and
        the fused eval path."""
        big = self.spec.feed(records[: n_full * mb])
        return jax.tree.map(
            lambda v: np.ascontiguousarray(v).reshape(
                (n_full, mb) + v.shape[1:]
            ),
            dict(big),
        )

    def _prep_fused_host(self, task: Task) -> HostPrep:
        """Host half of a fused training task: bulk read + C++ decode +
        [T, mb, ...] stacking.  Touches neither ``self.state`` nor the
        device, so the prep-ahead pipeline in ``run`` executes it on a
        background thread (the C++ codec and numpy copies release the GIL)
        while the previous task's wire transfer and metrics settle.

        With ``ingest_threads`` > 1 (and a reader declaring
        ``thread_safe_ranges``) the task's record range splits into
        minibatch-aligned sub-chunks read+decoded concurrently on the
        IngestPool, reassembled in chunk order — record order, ragged-tail
        records, and therefore the ``__mask__``/gradient-weighting
        semantics are bit-identical to the serial path (the feed decodes
        each record independently, so a chunked feed concatenates to
        exactly the serial feed's bytes)."""
        # graftchaos: stall(point=prep) — the host-side straggler the
        # deadline-bounded gang boundary exists to cut short.
        chaos.hook(
            "worker:prep", rank=self._rank, step=self._steps_dispatched
        )
        mb = self.config.minibatch_size
        shard = task.shard
        pool = self._ingest
        chunks = (
            plan_chunks(shard.start, shard.end, mb, pool.threads)
            if pool.parallel
            and getattr(self.reader, "thread_safe_ranges", False)
            else None
        )
        if not chunks or len(chunks) < 2:
            records = self._read_records(shard)
            total = len(records)
            n_full = total // mb
            stacked = (
                self._stack_full_minibatches(records, mb, n_full)
                if n_full >= 1
                else None
            )
            return HostPrep(total, n_full, stacked, list(records[n_full * mb:]))

        def _decode_chunk(span):
            # Runs on an ingest-pool thread; its cumulative time lands in
            # the off-critical-path ``decode_parallel`` phase (the phase
            # stack is per-thread, so this never subtracts from the
            # foreground phases).
            with self.phases.phase("decode_parallel"):
                recs = self._read_records(Shard(shard.name, span[0], span[1]))
                t = len(recs) // mb
                stacked = (
                    self._stack_full_minibatches(recs, mb, t)
                    if t >= 1
                    else None
                )
                return len(recs), t, stacked, list(recs[t * mb:])

        parts = pool.map_ordered(_decode_chunk, chunks)
        total = sum(p[0] for p in parts)
        n_full = sum(p[1] for p in parts)
        stacks = [p[2] for p in parts if p[2] is not None]
        if not stacks:
            stacked = None
        elif len(stacks) == 1:
            stacked = stacks[0]
        else:
            # Ordered concat along the step axis: chunk i's [t_i, mb, ...]
            # rows precede chunk i+1's, exactly the serial reshape's layout.
            stacked = {
                k: np.concatenate([s[k] for s in stacks], axis=0)
                for k in stacks[0]
            }
        # plan_chunks puts the ragged tail on the LAST chunk, so only it
        # can have leftover records.
        return HostPrep(total, n_full, stacked, parts[-1][3])

    def _gather_contribution(self, shard: int) -> None:
        """One dp shard's contribution crossing the collective gate.  On
        this harness the crossing is the graftchaos hook (the r13 stance:
        the injector is the supply side of stragglers the gate is the
        demand side for); a real fleet would await the shard's host-side
        inputs here (its PS row pull, its ingest chunk).  Runs on a gate
        thread when the in-step deadline is armed — a stalled crossing
        must stall ONE shard, never the dispatch."""
        chaos.hook(
            "worker:collective",
            rank=self._rank,
            step=self._steps_dispatched,
            shard=shard,
        )

    def _start_crossing(self, shard: int) -> threading.Event:
        """Run one shard's gate crossing on a DAEMON thread, signalling
        the returned event on completion.  Daemon deliberately (not an
        executor): a crossing wedged in a long stall must never block
        interpreter exit at job end — the severed straggler dies with
        the process, exactly the r13 teardown stance."""
        done = threading.Event()

        def _cross():
            try:
                self._gather_contribution(shard)
            finally:
                done.set()

        threading.Thread(
            target=_cross, name=f"edl-collgate-{shard}", daemon=True
        ).start()
        return done

    # hot-path: the gate's wait is the in-step deadline itself, accounted
    # under the collective_gate phase boundary
    def _collective_gate(self, task: Task) -> None:
        """graftreduce in-step straggler deadline (r15).

        Every dp shard's host-side contribution must cross the gate
        before the task's steps dispatch.  Deadline off (the default):
        the crossings run inline — a stalled contributor blocks the
        dispatch, the pre-r15 behavior (and the baseline the collective
        bench measures against).  Deadline on: crossings run on the gate
        pool, and a shard that misses ``--collective_deadline_ms`` is
        EXCLUDED — its weight in the subgroup mask drops to 0, the
        task's collectives renormalize over the survivors
        (``sum/|G'|``; trainer.set_active_contributors, a traced input,
        so no recompile), ``edl_collective_skip_total`` and a
        ``collective:exclude`` instant record the skip, and the
        cumulative count rides the heartbeat into the master's
        accounting.  A still-stalled shard stays excluded on later tasks
        WITHOUT re-submitting (its crossing is still in flight); when
        the crossing completes the shard re-joins (``collective:restore``).

        Bounded skip accounting (the r13 stance, same budget knob): a
        shard excluded more than ``--gang_skip_budget`` CONSECUTIVE
        tasks is waited out instead — a permanently dead contributor
        must surface as a visible stall, never as silently untrained
        data forever.

        Single-process meshes only: the mask is a replicated input, and
        every participant of a multi-process collective must dispatch
        the same mask — coordinating that across a gang needs a master
        round-trip per entry, so multi-process stragglers stay with the
        r13 task-boundary deadline (docs/robustness.md lays out the two
        layers)."""
        n = self.trainer.num_contributors()
        deadline_s = self.config.collective_deadline_ms / 1e3
        if deadline_s <= 0 or n <= 1 or self._group_mode:
            if chaos.enabled():
                # Inline crossings BLOCK the dispatch (the pre-r15
                # behavior the deadline exists to cut) — run them inside
                # the same phase the armed gate accounts to, so a
                # blocking stall and a bounded deadline wait decompose
                # under ONE name and the bench can compare them on phase
                # clocks instead of noisy whole-fleet walls.
                with self.phases.phase("collective_gate"):
                    for shard in range(n):
                        self._gather_contribution(shard)
            return
        if not chaos.enabled() and not self._collective_pending:
            # On this harness the chaos hook is the only crossing body
            # (_gather_contribution's docstring) — unarmed, nothing can
            # stall, so skip the per-shard thread spawn entirely.  The
            # mask invariant (exclusions == pending keys, rebuilt every
            # armed pass) means empty pending implies all-active already.
            self._g_coll_subgroup.set(float(n))
            return
        # Re-admit contributors whose stalled crossing finally finished.
        for shard, done in list(self._collective_pending.items()):
            if done.is_set():
                self._collective_pending.pop(shard)
                self._collective_consec.pop(shard, None)
                trace.instant(
                    "collective:restore", cat="collective",
                    shard=shard, task=task.task_id,
                )
        crossings = {
            shard: self._start_crossing(shard)
            for shard in range(n)
            if shard not in self._collective_pending
        }
        end = time.monotonic() + deadline_s
        with self.phases.phase("collective_gate"):
            for shard, done in crossings.items():
                if not done.wait(timeout=max(0.0, end - time.monotonic())):
                    self._collective_pending[shard] = done
            # Budget escalation: a shard past its consecutive-skip budget
            # is waited out (the stall becomes visible dispatch time in
            # this phase, exactly where a pre-r15 stall would land).
            budget = max(0, self.config.gang_skip_budget)
            for shard, done in list(self._collective_pending.items()):
                if self._collective_consec.get(shard, 0) < budget and (
                    len(self._collective_pending) < n
                ):
                    continue
                logger.warning(
                    "collective gate: shard %d exceeded %d consecutive "
                    "in-step skips (or no quorum remains); waiting it out",
                    shard, budget,
                )
                done.wait()  # accounted: inside the collective_gate phase
                self._collective_pending.pop(shard)
                self._collective_consec.pop(shard, None)
                trace.instant(
                    "collective:restore", cat="collective",
                    shard=shard, task=task.task_id, waited=True,
                )
        excluded = sorted(self._collective_pending)
        mask = np.ones(n, np.float32)
        for shard in excluded:
            mask[shard] = 0.0
            self._collective_consec[shard] = (
                self._collective_consec.get(shard, 0) + 1
            )
            self._collective_skips += 1
            self._g_coll_skips.inc()
            trace.instant(
                "collective:exclude", cat="collective",
                shard=shard, task=task.task_id,
                deadline_ms=self.config.collective_deadline_ms,
                consecutive=self._collective_consec[shard],
            )
        self.trainer.set_active_contributors(mask)
        self._g_coll_subgroup.set(float(n - len(excluded)))
        if excluded:
            logger.warning(
                "collective gate: task %d trains on subgroup %d/%d "
                "(excluded shard(s) %s past %.0f ms in-step deadline)",
                task.task_id, n - len(excluded), n, excluded,
                self.config.collective_deadline_ms,
            )

    # hot-path: THE dispatch function — every blocking transfer here shows
    # up as device idle on the remote-attached chip
    def _dispatch_training_task(
        self, task: Task, prep: Optional[HostPrep] = None
    ) -> tuple:
        """Dispatch every device step of a training task WITHOUT blocking on
        results.  Returns (per-batch device metrics, n_steps).

        Two overlap levels hide host and transfer latency behind the device
        (on a tunneled/remote chip every synchronous transfer costs a full
        RTT — measured ~60 ms against a ~10 ms step):
        - the prefetch thread decodes AND device-places (``shard_batch``)
          upcoming batches while steps are in flight (mesh-tier specs only;
          host-tier tables need the host batch for the row pull);
        - the caller defers the metrics fetch (``_finalize_training_metrics``)
          until after the NEXT task's steps are dispatched (task-level
          pipelining in ``run``).

        ``prep`` is an already-computed ``_prep_fused_host`` result (the
        prep-ahead pipeline); when None the host work runs inline here.
        Prep is only ever produced on the fused pre-shard path
        (``_prep_ahead_eligible``), so a prepped task either takes the
        fused branch (``n_full >= 1``) or is a pure-tail task whose records
        are exactly ``prep.tail``.
        """
        if self._group_mode and task.task_id != self._gang_last_task:
            # Gang-boundary arrival (r13): this entry's dispatch BEGINS
            # now — counted before the first device call so a rank that
            # blocks inside the collective below has still arrived at it,
            # and at most once per entry so the in-place collective retry
            # cannot inflate it (see _gang_dispatched in __init__).
            self._gang_last_task = task.task_id
            self._gang_dispatched += 1
        # graftchaos: stall(point=step) — a device-dispatch-side straggler.
        chaos.hook(
            "worker:step", rank=self._rank, step=self._steps_dispatched
        )
        # graftreduce (r15): every shard's contribution crosses the
        # in-step deadline gate before the steps dispatch; a straggler
        # past --collective_deadline_ms is excluded-and-renormalized
        # instead of holding the collective.
        self._collective_gate(task)
        mb = self.config.minibatch_size
        if prep is not None:
            records = None
            total, n_full, stacked_host, tail = prep
        else:
            with self.phases.phase("prep_wait"):
                records = self._read_records(task.shard)
            total = len(records)
            n_full = total // mb
            stacked_host = None
            tail = records[n_full * mb:]
        n_steps = (total + mb - 1) // mb
        pre_shard = not self.spec.host_io

        def _train_feed(chunk, true_count):
            """Feed a train chunk; wrap-padded tails get the eval-style
            ``__mask__`` so duplicated examples carry ZERO gradient (the
            train step weights shards by real count — build_train_step)."""
            batch = self.spec.feed(chunk)
            if true_count < mb:
                batch = dict(batch)
                batch["__mask__"] = (np.arange(mb) < true_count).astype(
                    np.float32
                )
            return batch

        try:
            if pre_shard and self.config.fused_task_scan and n_full >= 1:
                # Whole-task fused path: ONE feed call over every full
                # minibatch, ONE H2D transfer of the stacked [T, mb, ...]
                # batch, and ONE jitted lax.scan running all T steps — one
                # dispatch per task (per-step dispatch costs ~half the step
                # wall-clock on a remote-attached chip, and a single big
                # decode also sidesteps the GIL fight a per-batch producer
                # thread loses on 1-core hosts; docs/perf.md).  The
                # task-level pipeline in ``run`` overlaps this host work
                # with the PREVIOUS task's scan.  A ragged tail trains as
                # one extra masked step.
                if stacked_host is not None:
                    stacked = stacked_host
                else:
                    with self.phases.phase("prep_wait"):
                        stacked = self._stack_full_minibatches(
                            records, mb, n_full
                        )
                # jitsan (v6): the optional transfer guard makes any
                # IMPLICIT device->host materialization inside the
                # dispatch window a loud failure (explicit device_put /
                # device_get spellings stay legal) — the runtime half of
                # graftlint's transfer-discipline rule.
                with self.phases.phase("dispatch"), jitsan.transfer_guard():
                    self.state, scan_metrics = self.trainer.train_scan(
                        self.state, self.trainer.shard_stacked_batch(stacked)
                    )
                    metrics_list = [scan_metrics]  # [T]-stacked dict
                    for chunk, true_count in _minibatches(tail, mb, True):
                        self.state, m = self.trainer.train_step(
                            self.state,
                            self.trainer.shard_batch(
                                _train_feed(chunk, true_count)
                            ),
                        )
                        metrics_list.append(m)
            else:
                # Inline: the full record list.  Prepped: only reachable as
                # a pure-tail task (n_full == 0), whose records ARE the tail.
                gen_records = records if records is not None else tail

                def _gen():
                    for chunk, true_count in _minibatches(
                        gen_records, mb, True
                    ):
                        batch = _train_feed(chunk, true_count)
                        yield (
                            self.trainer.shard_batch(batch)
                            if pre_shard
                            else batch
                        )

                # run_train_steps = (host-tier pull ->) shard -> jitted step
                # (-> sparse push) per batch; plain shard+step when no host
                # tables.  --use_async pipelines the host-tier pulls against
                # the device step (the reference's async-PS mode).  The
                # per-step feed runs inside the same consumer loop, so this
                # path's decode time lands under "dispatch" — honest for a
                # mode whose decode and dispatch genuinely interleave.
                # when=: host-tier models materialize sparse cotangents
                # (np.asarray in _push_host_grads) INSIDE this window by
                # design — the documented sync point — so the guard arms
                # only for the dense paths where any implicit transfer is
                # a genuine leak.
                with self.phases.phase("dispatch"), jitsan.transfer_guard(
                    when=not self.spec.host_io
                ):
                    self.state, metrics_list = self.trainer.run_train_steps(
                        self.state,
                        prefetch(
                            _gen(),
                            self.config.prefetch_depth,
                            name=f"prefetch:{task.task_id}",
                        ),
                        use_async=self.config.use_async,
                        pre_sharded=pre_shard,
                    )
        except TrainLoopError as e:
            # The failed step may have consumed (donated) the state this
            # worker still references; adopt the newest live state — or
            # rebuild from the checkpoint — so the requeued task retries
            # against real buffers instead of wedging every later task.
            if e.state is not None:
                self.state = e.state
            else:
                self._recover_state()
            # Resync the python-side step mirror: recovery may have landed
            # on an older step, and later pipelined reports derive
            # model_version from this counter.
            self._steps_dispatched = int(self.state.step)
            raise
        except Exception:
            from elasticdl_tpu.parallel.trainer import _state_alive

            # Same donated-state hazard for the fused path's direct calls.
            if not _state_alive(self.state):
                self._recover_state()
            self._steps_dispatched = int(self.state.step)
            raise
        # Live throughput counters (r14): O(1) adds under a leaf lock —
        # the only gauge API legal on the hot path (gauge-discipline).
        self._g_examples.inc(total)
        self._g_steps.inc(n_steps)
        if self._collective_step_bytes is None:
            self._collective_step_bytes = (
                self.trainer.collective_bytes_per_step(self.state)["resolved"]
            )
        self._g_coll_bytes.inc(n_steps * self._collective_step_bytes)
        # Start the D2H copy of the task's metrics NOW, in the background:
        # the runtime moves each value to the host as soon as its step
        # completes, so the deferred fetch in _finalize_training_metrics
        # finds them resident instead of paying a blocking transfer RTT
        # while the device queue sits idle.
        for leaf in jax.tree.leaves(metrics_list):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        return metrics_list, n_steps

    def _recover_state(self) -> None:
        """Rebuild training state after a failed step consumed the live
        buffers: newest restorable checkpoint if any, else fresh init
        (loudly — a training job loses at most the work since the last
        checkpoint; the failed task is requeued either way)."""
        logger.error(
            "training state lost to a failed step; rebuilding from checkpoint"
        )
        self._join_ckpt()  # a mid-flight background save should land first:
        # its step is the newest restorable state this recovery can adopt
        self.state = self.trainer.init_state(jax.random.key(0))
        steps = self._ckpt.all_steps() if self._ckpt is not None else []
        for step in steps:
            try:
                restored = self._restore_checkpoint(self.state, step=step)
                self.trainer.restore_host_stores(self._ckpt.directory, step)
                self.state = restored
                logger.info("recovered from checkpoint step %d", step)
                return
            except FileNotFoundError:
                continue
        logger.error(
            "no restorable checkpoint; training state re-initialized fresh"
        )

    # hot-path: the one deliberate drain per task — both blocking halves
    # sit inside their named phase boundaries
    def _finalize_training_metrics(self, metrics_list) -> Dict[str, float]:
        """ONE device_get of the whole task's per-batch metrics, then host
        aggregation — per-batch device adds or per-scalar fetches would cost
        a dispatch/RTT each.  Entries are per-step scalar dicts OR
        [T]-stacked dicts (the fused lax.scan path); both weigh each step
        equally."""
        # The fetch is where the in-flight device steps drain: its wall is
        # the task's device-execution tail plus the transfer ("step_wait"),
        # distinct from the microseconds of host math after it ("metrics").
        with self.phases.phase("step_wait"):
            host = jax.device_get(metrics_list)
        with self.phases.phase("metrics"):
            sums: Dict[str, Any] = {}
            n = 0
            for metrics in host:
                steps = 1
                for k, v in metrics.items():
                    a = np.asarray(v, np.float64)
                    if a.ndim >= 1:  # [T]-stacked scan metrics
                        steps = a.shape[0]
                        a = a.sum(axis=0)
                    sums[k] = sums.get(k, 0.0) + a
                n += steps
            # finalize: scalars -> float, histogram pairs -> scalar (AUC).
            return finalize_metrics(
                {k: s / max(n, 1) for k, s in sums.items()}
            )

    def _run_training_task(self, task: Task) -> Dict[str, float]:
        """Synchronous task execution (profiled tasks, group/lockstep mode)."""
        metrics_list, _ = self._dispatch_training_task(task)
        return self._finalize_training_metrics(metrics_list)

    #: Collective-formation failures worth retrying in place: a gang member
    #: still COMPILING while its peer already executes trips the runtime's
    #: hard context-init deadline (XLA:CPU Gloo: 30 s).  The peer just needs
    #: time, not a group teardown — by the retry it has usually reached its
    #: side of the collective.  Anything else stays fatal (desync -> the
    #: deregister/restart path).
    #:
    #: Exactly the runtime's message prefix (ADVICE r4 #3 found the broad
    #: "context initialization failed" fallback could over-match; jaxlib
    #: emits only this one Gloo-prefixed form).  Retrying here cannot desync
    #: the gang's collective order: context init precedes any data exchange,
    #: so a member that failed it never participated — no peer's collective
    #: can have COMPLETED one-sided (it is blocked waiting), and every
    #: member classifies this same message the same way, so re-dispatch
    #: replays the identical collective sequence on all sides.
    _TRANSIENT_COLLECTIVE_MARKERS = (
        # Deliberately suffixless: a jaxlib upgrade rewording what follows
        # the phrase must not silently kill the retry path (each formerly
        # ~1s in-place retry would become a full gang restart cycle).  The
        # "Gloo" prefix keeps the r4 tightening — generic "context
        # initialization failed" strings still do NOT match.
        "Gloo context initialization failed",
    )
    _GROUP_TASK_ATTEMPTS = 3

    # hot-path: wraps every dispatch; the retry sleep lives on the
    # exception path only
    def _retry_transient_collective(self, fn, task_id: int):
        """Run a task's device work; in group mode, retry the transient
        collective-formation failures above in place.  _dispatch_training_task
        settles self.state on every failure (adopts the last live state or
        recovers from the checkpoint), so an immediate re-dispatch is safe
        and keeps the collective ORDER identical across the gang.  Outside
        group mode there is no collective to re-form: one plain call, so
        every dispatch site routes through here without branching on
        mode.  The schedule runs on the shared backoff helper (r18): a
        fixed 1 s, jitter-free cadence — every gang member classifies the
        same failure the same way, and identical re-dispatch timing is
        what keeps the retried collective aligned across ranks."""
        if not self._group_mode:
            return fn()

        def _transient(e: BaseException) -> bool:
            msg = str(e)
            return any(m in msg for m in self._TRANSIENT_COLLECTIVE_MARKERS)

        def _on_retry(e: BaseException, attempt: int, _delay: float) -> None:
            logger.warning(
                "transient collective-formation failure on task %d "
                "(attempt %d/%d): %s — retrying",
                task_id, attempt, self._GROUP_TASK_ATTEMPTS,
                str(e)[:200],
            )

        return call_with_backoff(
            fn,
            service="collective",
            is_transient=_transient,
            policy=BackoffPolicy(
                base_s=1.0, multiplier=1.0, max_s=1.0, jitter=0.0,
                max_attempts=self._GROUP_TASK_ATTEMPTS,
            ),
            on_retry=_on_retry,
        )

    def _run_group_training_task(self, task: Task) -> Dict[str, float]:
        return self._retry_transient_collective(
            lambda: self._run_training_task(task), task.task_id
        )

    def _group_resync(self, report: dict, context: str) -> None:
        """A lockstep member that failed a task is DESYNCHRONIZED: its
        peers' next collective (step or checkpoint barrier) would wedge
        waiting for it.  Requeue the task (failure report), actively leave
        the membership (the version bump resyncs the peers), and restart.
        One definition serving the synchronous path and every pipelined
        failure site, so the resync contract cannot drift."""
        report["success"] = False
        report.pop("metrics", None)
        report["seq"] = self._next_report_seq()
        for call, payload in (
            ("ReportTaskResult", report),
            ("DeregisterWorker", {"worker_id": self.worker_id}),
        ):
            try:
                self.master.call(call, payload)
            except Exception:  # master unreachable: peers will
                pass           # still reap us via heartbeats
        raise WorkerRestartRequired(
            f"task {report['task_id']} failed in lockstep mode "
            f"({context}); deregistered for group resync"
        )

    def _next_report_seq(self) -> int:
        # graftlint: allow[shared-state] the _parked spin-wait handshake serializes the preemption thread's _flush_pending (the only off-loop report path) against the loop (see preemption_snapshot)
        self._report_seq += 1
        return self._report_seq

    # hot-path: the report RPC is accounted under the metrics phase
    def _report_result(self, report: dict) -> None:
        """ReportTaskResult with the cumulative phase decomposition riding
        along (the master's JobStatus and the train-job artifact read it).
        ``phase_counts`` rides beside the seconds so per-phase AVERAGES are
        computable downstream, not just cumulative sums."""
        report["phase_times"] = self.phases.snapshot()
        report["phase_counts"] = self.phases.counts()
        report["seq"] = self._next_report_seq()
        # Gauge envelope on every task report (forced past the ship
        # throttle: reports are bounded frequency by construction) — the
        # carrier of the master's per-report JSONL gauge mirror.
        gp = self.gauge_payload(force=True)
        if gp is not None:
            report["gauge"] = gp
        with self.phases.phase("metrics"):
            self.master.call("ReportTaskResult", report)

    # hot-path: settles the PREVIOUS task while this one's steps run
    def _flush(self, pending: Optional[tuple]) -> None:
        """Settle a pipelined task: fetch its device metrics, report (rank 0
        only in group mode — peers ran the same collectives but exactly one
        report must hit the master's queues), and run the checkpoint hook.

        Failure containment differs by mode.  Single-process: a fetch
        failure fails THAT task's report (requeued by the master), never the
        task whose dispatch triggered the flush.  Group mode: a deferred
        error surfacing at the fetch can be a failed COLLECTIVE — peers may
        already be wedged waiting — so the member resyncs the gang
        (_group_resync) exactly as a synchronous task failure does."""
        if pending is None:
            return
        report, metrics_list = pending
        try:
            report["metrics"] = self._finalize_training_metrics(metrics_list)
        except Exception:
            logger.exception(
                "task %d failed at metrics fetch", report["task_id"]
            )
            if self._group_mode:
                self._group_resync(report, "metrics fetch")  # raises
            report["success"] = False
            report.pop("metrics", None)
        if not self._group_mode or self._rank == 0:
            if self._group_mode:
                # The checkpoint hook below must stay RANK-SYMMETRIC: a
                # rank-0 report-RPC blip that skipped it would leave the
                # peers starting a collective background save rank 0 never
                # joins (wedged commit barrier) and desync the watermark
                # arithmetic.  Swallow the failure — the master's task
                # timeout requeues a lost report, and the requeued task
                # re-enters the lockstep log symmetrically for every rank.
                try:
                    self._report_result(report)
                except Exception:
                    logger.exception(
                        "group report for task %d lost (master task "
                        "timeout requeues it)", report["task_id"]
                    )
            else:
                self._report_result(report)
        if report["success"]:
            # graftlint: allow[shared-state] the _parked spin-wait handshake serializes the preemption thread's _flush_pending against the loop (see preemption_snapshot)
            self._tasks_done += 1
            self._g_tasks.inc()
            self._maybe_checkpoint()

    # ---- prep-ahead pipeline (fused + pipelined mode) ----

    def _pipelining_enabled(self, profiling: bool = False) -> bool:
        """Task-level pipelining: defer the previous task's metrics fetch +
        report behind this task's dispatched steps.

        r6 lifted the single-process (``not self._group_mode``) gate: every
        rank dispatches tasks in the lockstep seq order, so deferring the
        LOCAL metrics fetch reorders no collective — the gang's device
        programs still execute in identical task order on every rank.
        Reports stay rank-0-gated inside ``_flush``, and a pipelined-task
        failure resyncs the gang (``_group_resync``) exactly as a
        synchronous one does.  A profiled task is still traced in
        isolation."""
        return not profiling and self.config.task_pipelining

    def _prep_ahead_eligible(self) -> bool:
        """Prep-ahead runs the NEXT task's host work (read+decode+stack) on
        a background thread while the current task's wire transfer streams
        and the previous task's metrics settle — on a remote-attached chip
        the host<->device link is the e2e bound (~20-40 MB/s measured
        through the tunnel), and without prep-ahead it sits idle during
        every decode and metrics fetch.  Group mode is eligible too (r6):
        the host-side decode/pre-shard prep is per-process-local and touches
        no collective state, and a prepped task's DISPATCH still happens
        only at its own lockstep boundary — prep is submitted at task
        acquisition (GetGroupTask), so the gang's collective order is
        untouched.  Only the fused pre-shard path (host-tier tables need
        the host batch on the main thread), and never in a profiling
        session (a profiled task must be traced in isolation)."""
        return (
            self.config.task_pipelining
            and self.config.fused_task_scan
            and not self.spec.host_io
            and not self.config.profile_dir
        )

    # hot-path: submission only — the prep itself runs on the pool thread
    def _submit_prep(self, task: Task):
        if self._prep_pool is None:
            # One prep thread per pipeline slot: every queued task's host
            # half runs concurrently (each fanning its chunk decodes out to
            # the shared IngestPool), so a slow shard never serializes the
            # preps behind it.  A reader that does NOT declare
            # thread_safe_ranges (shared-connection sources) keeps the
            # pre-r9 one-thread pool: the k-deep queue still buffers k
            # leased tasks, but their reads serialize — concurrent
            # _read_records calls are exactly what such readers forbid.
            width = (
                max(1, self.config.prep_depth)
                if getattr(self.reader, "thread_safe_ranges", False)
                else 1
            )
            self._prep_pool = ThreadPoolExecutor(
                max_workers=width, thread_name_prefix="edl-prep"
            )
        return self._prep_pool.submit(self._prep_fused_host, task)

    # hot-path: the pipelined steady state — prep wait and the previous
    # task's settle are the only (phase-accounted) blocking points
    def _dispatch_prepped(self, prepped: tuple) -> None:
        """Dispatch a prepped task's device work, rotate it into the
        pending (report-deferred) slot, and settle the PREVIOUS pending
        task.  Single-process: a failure (prep or dispatch) fails THIS
        task's report — the master requeues it — exactly as the inline
        dispatch path does, and nothing is raised: the caller has often
        just queued a NEW task into ``_prep_queue`` whose report dict the
        run loop's outer exception handler would wrongly fail — a task the
        master would requeue while this worker still holds (and later
        trains) it, double-training its records.  Lost reports are the
        master's task timeout's job.

        Group mode: a dispatch failure is a gang DESYNC (peers' collectives
        would wedge on this rank), so after the in-place transient
        collective retry is exhausted this raises WorkerRestartRequired via
        ``_group_resync`` — the restart requeues everything this rank held,
        including the freshly prepped task, through the membership bump."""
        task, report, fut = prepped
        try:
            with self.phases.phase("prep_wait"):
                prep = fut.result()
            metrics_list, n_steps = self._retry_transient_collective(
                lambda: self._dispatch_training_task(task, prep=prep),
                task.task_id,
            )
        except Exception:
            logger.exception("task %d failed", task.task_id)
            if self._group_mode:
                self._group_resync(report, "prep/dispatch")  # raises
            report["success"] = False
            try:
                self._report_result(report)
            except Exception:
                logger.exception(
                    "failure report for task %d lost (master task timeout "
                    "will requeue it)", task.task_id,
                )
            return
        self._steps_dispatched += n_steps
        report["model_version"] = self._steps_dispatched
        self._training_tasks_done += 1
        # graftlint: allow[shared-state] the _parked spin-wait handshake serializes the preemption thread's _flush_pending against this swap (see preemption_snapshot)
        prev, self._pending = self._pending, (report, metrics_list)
        try:
            self._flush(prev)
        except WorkerRestartRequired:
            raise  # group resync: the whole process restarts
        except Exception:
            # _flush already contains metric-fetch failures; what escapes is
            # the report RPC itself.  The settled task's work is done and
            # this worker no longer holds it — the master's timeout requeues
            # it if the report truly never landed.
            logger.exception(
                "report of previous pipelined task lost (master task "
                "timeout will requeue it)",
            )

    def _drain_prep(self) -> None:
        """Run the prep-ahead queue to completion (dispatch every prepped
        task, then settle the deferred report slot): called whenever
        something must observe a fully settled task order — eval/predict
        tasks, membership changes, idle polls, job end.  A group resync
        raised mid-drain leaves the remaining entries queued; the restart's
        membership bump requeues them master-side."""
        while self._prep_queue:
            self._dispatch_prepped(self._prep_queue.popleft())
        self._flush_pending()

    def _abandon_prep(self) -> None:
        """Give every undispatched prepped task back to the master (failure
        report -> immediate requeue) — the preemption path must not start
        new device work, and silently dropping a task would make the
        master wait out its timeout.  Each queue entry is reported exactly
        once; tasks already dispatched left the queue and report through
        their pending slot instead (no double-report)."""
        while self._prep_queue:
            task, report, fut = self._prep_queue.popleft()
            fut.cancel()  # not-yet-started prep must not compete with the
            # preemption snapshot for host I/O inside the grace window
            report["success"] = False
            # No device work ran: requeue without charging the retry
            # budget (a genuine failure this is not).
            report["requeue"] = True
            report["seq"] = self._next_report_seq()
            try:
                self.master.call("ReportTaskResult", report)
            except Exception:
                logger.exception(
                    "abandoning prepped task %d failed", task.task_id
                )

    def _abandon_leases(self) -> None:
        """Return locally buffered (never-started) task leases to the
        master: a failure report requeues each immediately, preserving the
        at-least-once contract without waiting out the task timeout.  In
        group mode the buffer is lockstep-log read-ahead attributed to the
        group pseudo worker, and the master's log invalidation on a
        membership change already requeues it — reporting from here would
        double-requeue, so the local buffer is simply dropped."""
        leased, self._leased = self._leased, deque()
        if self._group_mode or not leased:
            return
        for entry in leased:
            t = entry.get("task")
            if not t:
                continue
            report = {
                "worker_id": self.worker_id,
                "task_id": t["task_id"],
                "task_type": t["type"],
                "success": False,
                # Never started: requeue without charging the retry budget.
                "requeue": True,
                "seq": self._next_report_seq(),
            }
            try:
                self.master.call("ReportTaskResult", report)
            except Exception:
                logger.exception(
                    "abandoning leased task %d failed (master task "
                    "timeout will requeue it)", t["task_id"],
                )

    def _flush_pending(self) -> None:
        pending, self._pending = self._pending, None
        self._flush(pending)

    # hot-path: steady-state task acquisition — buffered leases cost no
    # RPC at all; the batched lease RPC is accounted under lease_wait
    def _next_lease(self) -> dict:
        """The next task entry: from the local lease buffer when one is
        held, else one batched GetTask/GetGroupTask RPC (up to
        ``lease_batch`` tasks per round-trip — the r5 loop paid a full
        control-plane RTT per task).  Returns the wire shape
        ``{task?, finished, stale}``; extra leased tasks are buffered and
        consumed on later iterations (and returned to the master by
        ``_abandon_leases`` if preemption or a membership change strikes
        first)."""
        if self._leased:
            return self._leased.popleft()
        n = max(1, self.config.lease_batch)
        if self._group_mode:
            # Lockstep pull: every process of the world executes the same
            # task sequence (the jitted step is a collective over all their
            # devices); the master's group log keys entries by seq, and the
            # lease batches the log walk.
            with self.phases.phase("lease_wait"):
                # The gang-boundary wait, as its own span per rank: in
                # lockstep mode every rank crosses this boundary at the
                # same seq, so per-rank span totals are directly
                # comparable — the straggler report's skew input
                # (tools/straggler_report.py).
                with trace.span(
                    "gang_boundary", cat="gang",
                    seq=self._task_seq, rank=self._rank,
                    version=self._membership_version,
                ):
                    resp = self.master.call(
                        "GetGroupTask",
                        {
                            "worker_id": self.worker_id,
                            "seq": self._task_seq,
                            "version": self._membership_version,
                            "lease": n,
                        },
                    )
            if resp.get("stale"):
                return resp
            entries = resp.get("entries") or [
                {"task": resp.get("task"), "finished": resp["finished"]}
            ]
            self._leased.extend(
                {"task": e["task"], "finished": e["finished"], "stale": False}
                for e in entries[1:]
            )
            return {
                "task": entries[0]["task"],
                "finished": entries[0]["finished"],
                "stale": False,
            }
        with self.phases.phase("lease_wait"):
            resp = self.master.call(
                "GetTask", {"worker_id": self.worker_id, "lease": n}
            )
        tasks = resp.get("tasks")
        if tasks:
            self._leased.extend(
                {"task": t, "finished": False, "stale": False}
                for t in tasks[1:]
            )
            return {"task": tasks[0], "finished": False, "stale": False}
        return {
            "task": resp.get("task"), "finished": resp["finished"],
            "stale": False,
        }

    def _run_evaluation_task(self, task: Task) -> tuple:
        records = self._read_records(task.shard)
        mb = self.config.minibatch_size
        sums: Dict[str, Any] = {}
        total = 0.0

        def _accumulate(metrics, true_count):
            nonlocal total
            for k, v in metrics.items():
                # Histogram metrics (streaming AUC) are vectors; accumulate
                # with the same count weighting as the scalars.
                sums[k] = sums.get(k, 0.0) + np.asarray(v, np.float64) * true_count
            total += true_count

        n_full = len(records) // mb
        if (
            not self.spec.host_io
            and self.config.fused_task_scan
            and n_full >= 1
        ):
            # Fused eval: all full chunks in ONE decode + transfer + scan
            # (the eval twin of the fused training task); only the masked
            # tail runs as a separate step.
            stacked = self._stack_full_minibatches(records, mb, n_full)
            metrics = jax.device_get(
                self.trainer.eval_scan(
                    self.state, self.trainer.shard_stacked_batch(stacked)
                )
            )
            for t in range(n_full):
                _accumulate({k: v[t] for k, v in metrics.items()}, mb)
            tail = records[n_full * mb :]
        else:
            tail = records

        def _batches():
            for chunk, true_count in _minibatches(tail, mb, False):
                batch = dict(self.spec.feed(chunk))
                # Real-vs-padding mask for the wrap-padded tail: metrics
                # count only real rows (see models/metrics.py) — without it
                # the duplicated examples were over-weighted.
                batch["__mask__"] = (np.arange(mb) < true_count).astype(
                    np.float32
                )
                yield batch, true_count

        for batch, true_count in prefetch(
            _batches(), self.config.prefetch_depth,
            name=f"prefetch:{task.task_id}",
        ):
            metrics = self.trainer.run_eval_step(self.state, batch)
            _accumulate(metrics, true_count)
        # Report RAW weighted means — including histogram vectors (as JSON
        # lists) — so the MASTER's cross-worker aggregation stays exact; it
        # derives the AUC scalar at round end (evaluation_service).
        means = {k: s / max(total, 1e-12) for k, s in sums.items()}
        return {
            k: (v.tolist() if v.ndim else float(v)) for k, v in means.items()
        }, total

    def _run_prediction_task(self, task: Task) -> None:
        records = self._read_records(task.shard)
        outs = []
        for batch, true_count in prefetch(
            (
                (self.spec.feed(chunk), count)
                for chunk, count in _minibatches(
                    records, self.config.minibatch_size, False
                )
            ),
            self.config.prefetch_depth,
            name=f"prefetch:{task.task_id}",
        ):
            out = self.trainer.run_predict_step(self.state, batch)
            # graftlint: allow[transfer-discipline] the materialized outputs ARE the prediction task's product; the per-batch fetch is the work
            outs.append(np.asarray(out)[:true_count])
        if self.config.prediction_outputs:
            os.makedirs(self.config.prediction_outputs, exist_ok=True)
            np.save(
                os.path.join(
                    self.config.prediction_outputs, f"task-{task.task_id}.npy"
                ),
                np.concatenate(outs, axis=0),
            )

    def _ship_trace_tail(self, max_beats: int = 8) -> None:
        """Drain the remaining trace buffer to the master over bounded
        extra heartbeats (job end / final settle).  Best-effort: a dead
        master just loses the tail — the job is over either way."""
        rec = trace.default()
        for _ in range(max_beats):
            if not rec.enabled:
                return
            tp = self._trace_payload()
            if tp is None:
                return
            try:
                self.master.call(
                    "Heartbeat",
                    {
                        "worker_id": self.worker_id,
                        "version": self._membership_version,
                        "trace": tp,
                    },
                )
            except Exception:
                logger.info("trace tail ship failed; dropping the tail")
                return

    # ---- main loop ----

    # hot-path: the task loop itself — every deliberate blocking point is
    # either phase-accounted or individually waived with its reason
    def run(self, membership: Optional[dict] = None) -> Dict[str, Any]:
        """Main loop.  ``membership`` is the view returned by an EARLIER
        RegisterWorker call (worker.main registers once, derives the
        jax.distributed spec from that view, and passes it here) — a second
        registration would race a concurrent join and silently absorb a
        membership this process's fixed distributed world does not match.
        Without it (single-process tests, in-process workers) we register
        here."""
        if membership is None:
            # graftlint: allow[hot-path-sync] one-time registration before the loop starts
            membership = self.master.call(
                "RegisterWorker",
                {
                    "worker_id": self.worker_id,
                    "address": self._advertised_address(),
                    "proto": PROTOCOL_VERSION,
                    "incarnation": self._incarnation,
                    # A fresh registration holds nothing: stale leases of
                    # a previous incarnation requeue now (r18 reconcile).
                    "held_tasks": [],
                },
            )
        # graftlint: allow[blocking-propagation] one-time initial membership application before the loop starts
        self._apply_membership(membership, initial=True)
        if self.state is None:
            self.state = self.trainer.init_state(jax.random.key(0))
            # Adopt the newest restorable snapshot from the LOCAL checkpoint
            # directory.  Deliberately NOT gated on the master's
            # GetCheckpoint: a fresh master (standalone evaluation/
            # prediction job over a trained checkpoint, or a master restart)
            # has no reported checkpoint yet, and gating on it made such
            # jobs silently score freshly-initialized weights.
            #
            # Walk retained steps newest-first; adopt a step only when BOTH
            # halves restore (a torn pair — dense committed but the host
            # snapshot missing/truncated after a crash — would silently pair
            # trained dense layers with re-initialized embeddings).  An
            # older intact step beats starting over.
            steps = self._ckpt.all_steps() if self._ckpt is not None else []
            restored_step = None
            for step in steps:
                try:
                    restored = self._restore_checkpoint(self.state, step=step)
                    self.trainer.restore_host_stores(
                        self._ckpt.directory, step
                    )
                    self.state = restored
                    restored_step = step
                    logger.info("joined from checkpoint step %d", step)
                    break
                except FileNotFoundError as e:
                    logger.warning(
                        "checkpoint step %d torn (%s); trying older", step, e
                    )
            if restored_step is None:
                if self.config.job_type in ("evaluation", "prediction"):
                    if self._ckpt is not None:
                        # Fail-loud: scoring random weights is silent garbage.
                        raise RuntimeError(
                            f"{self.config.job_type} job found no restorable "
                            f"checkpoint under {self._ckpt.directory} "
                            f"(steps seen: {steps}); refusing to score "
                            "freshly initialized weights"
                        )
                    # No --checkpoint_dir at all: legitimate for smoke tests,
                    # a misconfiguration in production — say so loudly.
                    logger.warning(
                        "%s job has no --checkpoint_dir: scoring FRESHLY "
                        "INITIALIZED weights", self.config.job_type,
                    )
                if steps:
                    logger.error(
                        "every retained checkpoint step %s was torn; "
                        "training from freshly initialized state", steps,
                    )

        self._tasks_done = 0
        # graftlint: allow[hot-path-sync] one-time mirror seed before the loop; the restore above already settled the state
        self._steps_dispatched = int(self.state.step)
        while True:
            if self._preempting:
                # SIGTERM arrived: the preemption thread owns the exit
                # (snapshot + os._exit); dispatching more work would keep
                # the state donated-in-flight and unsaveable.  Acknowledge
                # the park FIRST — the abandon report below is a blocking
                # RPC against a master that is slow exactly when a mass
                # preemption is in flight, and paying it before _parked
                # could consume the preemption thread's 5 s park deadline
                # and forfeit the snapshot (ADVICE r5).  Safe: from here
                # this loop only abandons and sleeps, so self.state can no
                # longer be donated or reassigned.
                self._parked = True
                # Give undispatched prepped tasks and unstarted leases
                # straight back to the master (they must not start device
                # work now), then park.
                # graftlint: allow[blocking-propagation] parked for preemption: the abandon report is the last useful work
                self._abandon_prep()
                # graftlint: allow[blocking-propagation] parked for preemption: returning unstarted leases is the last useful work
                self._abandon_leases()
                # graftlint: allow[hot-path-sync] parked for preemption: the loop must only idle here
                time.sleep(self._poll)
                continue
            with self.phases.phase("control"):
                self._check_membership()
                # Buffered lease or one batched GetTask/GetGroupTask RPC
                # (the lease RPC's wall lands in the nested lease_wait
                # phase; control keeps only the heartbeat + loop overhead).
                resp = self._next_lease()
            if self._group_mode and resp.get("stale"):
                # World changed under us: the next membership check
                # raises WorkerRestartRequired.
                # graftlint: allow[hot-path-sync] stale lockstep world: no work to overlap until the re-form
                time.sleep(self._poll)
                continue
            if resp["task"] is None:
                if resp["finished"]:
                    break
                # No new task to overlap with: settle the pipelined ones NOW
                # — the dispatcher cannot finish (or hand out follow-up
                # work, e.g. an eval round gated on this report's
                # model_version) until they land, and idling on unreported
                # tasks would eventually look like a timeout/requeue.
                self._drain_prep()
                # graftlint: allow[hot-path-sync] dispatcher idle: nothing to dispatch, the poll IS the work
                time.sleep(self._poll)
                continue
            task = Task.from_dict(resp["task"])
            # graftchaos: kill / stall(point=task) faults fire at the task
            # boundary — after the lease, before any device work, so a
            # killed rank's task requeues through the ordinary loss path.
            # BEFORE the seq increment: a rank wedged in this hook has not
            # begun the entry, and its lockstep progress mirror (gang_seq,
            # the deadline-bounded boundary's per-rank signal) must not
            # count it — on a harness without dispatch lookahead the
            # healthy peers sit at the SAME consumed seq, and an
            # already-incremented straggler would be indistinguishable
            # from them, invisible to the very deadline built to cut it.
            chaos.hook(
                "worker:task", rank=self._rank,
                step=self._steps_dispatched, task_id=task.task_id,
            )
            self._task_seq += 1
            report = {
                "worker_id": self.worker_id,
                "task_id": task.task_id,
                "task_type": task.type,
                "success": True,
            }
            try:
                if task.type == TASK_TRAINING:
                    profiling = self._maybe_start_profile()
                    # Task-level pipelining: dispatch this task's steps,
                    # then settle the PREVIOUS task's metrics fetch +
                    # report while these steps run — the fetch is the one
                    # per-task blocking transfer, and overlapping it keeps
                    # the device queue full across task boundaries.  Group
                    # mode pipelines too since r6 (_pipelining_enabled):
                    # dispatch order is the lockstep seq order on every
                    # rank, so no collective is reordered; only a profiled
                    # task keeps the synchronous shape (traced in
                    # isolation).
                    pipelined = self._pipelining_enabled(profiling)
                    try:
                        if pipelined and self._prep_ahead_eligible():
                            # Prep-ahead: submit THIS task's host work to
                            # the prep pool, then dispatch + settle the
                            # OLDEST prepped task once the queue exceeds
                            # its depth.  At depth k the wire transfer of
                            # task N streams while tasks N+1..N+k decode
                            # and task N-1's metrics settle — k+2 tasks in
                            # flight, link busy end to end.  In group mode
                            # the submission rides the gang
                            # task-acquisition path (this task was just
                            # pulled at its seq), so every prepped
                            # dispatch below stays inside the lockstep
                            # boundary of the task it belongs to.
                            fut = self._submit_prep(task)
                            self._prep_queue.append((task, report, fut))
                            while (
                                len(self._prep_queue)
                                > max(1, self.config.prep_depth)
                            ):
                                self._dispatch_prepped(
                                    self._prep_queue.popleft()
                                )
                            continue
                        if pipelined:
                            metrics_list, n_steps = (
                                self._retry_transient_collective(
                                    lambda: self._dispatch_training_task(
                                        task
                                    ),
                                    task.task_id,
                                )
                            )
                            self._steps_dispatched += n_steps
                            report["model_version"] = self._steps_dispatched
                            self._training_tasks_done += 1
                            prev, self._pending = (
                                self._pending, (report, metrics_list),
                            )
                            try:
                                self._flush(prev)
                            except WorkerRestartRequired:
                                raise  # group resync: process restarts
                            except Exception:
                                # Same containment as _dispatch_prepped: a
                                # report-RPC failure here must not fail THIS
                                # task's report (its steps are already in
                                # self.state; a master requeue would train
                                # its records twice).  The lost report is
                                # the master task timeout's to requeue.
                                logger.exception(
                                    "report of previous pipelined task "
                                    "lost (master task timeout requeues)",
                                )
                            continue
                        metrics = (
                            self._run_group_training_task(task)
                            if self._group_mode
                            else self._run_training_task(task)
                        )
                    finally:
                        if profiling:
                            # graftlint: allow[hot-path-sync] a profiled task is traced in isolation; the trace must capture the drain
                            jax.block_until_ready(self.state)
                            jax.profiler.stop_trace()
                    self._training_tasks_done += 1
                    report["metrics"] = metrics
                    # graftlint: allow[hot-path-sync] synchronous (non-pipelined) mode settles every task by design
                    report["model_version"] = int(self.state.step)
                    # graftlint: allow[hot-path-sync] synchronous-mode mirror resync, same settle as the line above
                    self._steps_dispatched = int(self.state.step)
                elif task.type == TASK_EVALUATION:
                    # Settle the pipelined train tasks first: their reports
                    # must not interleave behind this round's eval
                    # aggregation, and the eval scores the settled state.
                    self._drain_prep()
                    # graftlint: allow[blocking-propagation] eval settles synchronously by design: it scores a settled state
                    metrics, weight = self._run_evaluation_task(task)
                    report["metrics"] = metrics
                    report["weight"] = weight
                elif task.type == TASK_PREDICTION:
                    self._drain_prep()
                    self._run_prediction_task(task)
                else:
                    raise ValueError(f"unknown task type {task.type}")
            except WorkerRestartRequired:
                # A pipelined group failure already reported + deregistered
                # (_group_resync); the restart must not be demoted to a
                # failed report for the task that merely triggered the
                # flush.
                raise
            except Exception:
                logger.exception("task %d failed", task.task_id)
                report["success"] = False
            if self._group_mode and not report["success"]:
                # graftlint: allow[blocking-propagation] failure exit protocol: the member is leaving the world
                self._group_resync(report, "synchronous task")  # raises
            if not self._group_mode or self._rank == 0:
                # In lockstep mode every process ran the task's collectives,
                # but exactly one report must hit the master's queues.
                self._report_result(report)
            if report["success"]:
                self._tasks_done += 1
                self._g_tasks.inc()
                self._maybe_checkpoint()

        # Settle the last pipelined tasks before the final checkpoint.
        self._drain_prep()
        # Final checkpoint so a completed job is resumable/servable.  In
        # group mode the save is collective (see _maybe_checkpoint); all
        # processes reach this point together because the finished marker is
        # a logged lockstep entry.
        if self._ckpt is not None and self.state is not None and (
            self._group_mode or self._rank == 0
        ):
            with self.phases.phase("checkpoint"):
                # Settle any in-flight background periodic save first: the
                # final save below must not interleave with it.  In group
                # mode this is also the shutdown settle point for the
                # background COLLECTIVE save — every rank joins its own
                # thread here before entering the final collective save.
                self._join_ckpt()
                step = int(self.state.step)
                # Canonical layout either way: group mode canonicalizes on
                # device (collective saves stream device arrays), the
                # single-process path on host.
                payload = (
                    self.trainer.snapshot_state(self.state)
                    if self._group_mode
                    else self.trainer.host_state(self.state)
                )
                self._ckpt.save(step, payload, wait=True)
                if self._rank == 0:
                    # Rank-gated like _maybe_checkpoint: one Save fan-out
                    # per step (plain RPC, not collective — no deadlock
                    # risk).
                    self.trainer.save_host_stores(self._ckpt.directory, step)
                    # Publish for serving: the completed job's final state
                    # is exactly the checkpoint an online tier wants live.
                    self._ckpt.publish(step)
                if self._rank == 0:
                    self.master.call(
                        "ReportCheckpoint", self._checkpoint_report(step)
                    )
        # Ship the trace tail: events recorded since the last heartbeat
        # would otherwise die with this process (the merged view of a
        # COMPLETED job wants its final tasks too).  Inside a control
        # phase boundary: these are deliberate, accounted job-end RPCs.
        with self.phases.phase("control"):
            self._ship_trace_tail()
        return {
            "tasks_done": self._tasks_done,
            # graftlint: allow[hot-path-sync] job-end summary; everything is already settled
            "step": int(self.state.step) if self.state is not None else 0,
            "reforms": self.reforms,
            # The task loop's wall decomposition (common/metrics.PhaseTimers)
            # for in-process callers; out-of-process consumers read the same
            # snapshot off the master's JobStatus.
            "phase_times": self.phases.snapshot(),
        }
