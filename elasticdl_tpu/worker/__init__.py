"""Worker: the task-pulling training/eval/predict loop.

Reference parity (SURVEY.md §2 #7-9, §3.3-3.4 [U/D]): the worker registers
with the master, pulls shard tasks over RPC, runs the jitted mesh step on
each shard's minibatches, reports results/metrics, and on a membership-version
change re-forms its mesh from the latest checkpoint (the reference's elastic
Horovod retry path, §3.5).
"""

from elasticdl_tpu.worker.worker import DirectMasterProxy, RpcMasterProxy, Worker  # noqa: F401
