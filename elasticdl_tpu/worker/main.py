"""Worker pod entry point.

Reference parity (SURVEY.md §2 #7 [U]): the master renders worker pods whose
command is the worker main module and whose args/env carry the job config;
here the config bus is the ``ELASTICDL_JOB_CONFIG`` env var (set by the
PodManager) with CLI flags as a fallback, and the worker id comes from
``ELASTICDL_WORKER_ID`` (the pod name).

Run as ``python -m elasticdl_tpu.worker.main``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import List, Optional

from elasticdl_tpu.common.config import JobConfig, parse_args
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.platform import apply_platform_env
from elasticdl_tpu.common.rpc import PROTOCOL_VERSION

apply_platform_env()
from elasticdl_tpu.data.reader import (
    AbstractDataReader,
    CompositeDataReader,
    create_data_reader,
)
from elasticdl_tpu.worker.worker import (
    RESTART_EXIT_CODE,
    RpcMasterProxy,
    Worker,
    WorkerRestartRequired,
)

logger = get_logger("worker.main")

# Multihost join settle window: after registering, wait for the rendezvous
# version to hold still this long (bounded by the max) before fixing the
# jax.distributed world.  Workers of one gang start near-simultaneously; the
# first to register would otherwise derive a world of 1 and pay a full
# process restart the moment the second joins.  Sampled at SETTLE_POLL_S so
# the wait costs ~the stability window itself, not a fixed sleep — the
# settle is on the relaunch critical path (docs/perf.md re-rendezvous), and
# a missed race now costs one CHEAP restart (warm standby + death push)
# rather than a cold boot.
SETTLE_STABLE_S = 1.0
SETTLE_POLL_S = 0.25
SETTLE_MAX_S = 15.0


def build_job_reader(config: JobConfig) -> AbstractDataReader:
    """One reader serving every dataset the job's tasks may name."""
    params = config.parsed_data_reader_params()
    paths = [
        p
        for p in (
            config.training_data,
            config.validation_data,
            config.prediction_data,
        )
        if p
    ]
    if not paths:
        raise ValueError("job config names no data paths")
    readers = [create_data_reader(p, params) for p in dict.fromkeys(paths)]
    return readers[0] if len(readers) == 1 else CompositeDataReader(readers)


def _park_as_standby(go_file: str) -> str:
    """Warm-standby mode (ELASTICDL_STANDBY_GO_FILE): pre-pay the boot tail
    — python + jax + framework imports, ~13 s of the r4 re-rendezvous
    (docs/perf.md) — then park until the pod manager writes the go file
    naming the worker id this process should become.  Nothing here may
    touch a jax *backend* (devices/compile): in multihost mode the backend
    must first bind to the jax.distributed world formed AFTER registration.
    Returns the assigned worker id."""
    import importlib

    for mod in (
        "jax", "jax.numpy", "flax", "optax", "orbax.checkpoint",
        "elasticdl_tpu.parallel.trainer", "elasticdl_tpu.parallel.mesh",
        "elasticdl_tpu.models.spec", "elasticdl_tpu.data.reader",
        "elasticdl_tpu.worker.worker",
    ):
        importlib.import_module(mod)
    logger.info("standby warmed (pid %d); parking on %s", os.getpid(), go_file)
    # Readiness marker (atomic publish, like the go file itself): only a
    # WARMED spare is worth adopting — the pod manager skips spares whose
    # marker is absent and cold-spawns instead (ProcessPodBackend
    # _adopt_standby), so a burst of failures never queues behind a spare
    # that is still paying its imports.
    from elasticdl_tpu.common import durable

    ready = go_file + ".ready"
    durable.atomic_publish(ready, str(os.getpid()))
    parent0 = os.getppid()
    while not os.path.exists(go_file):
        if os.getppid() != parent0:
            # The master died without close() (kill -9/OOM): nothing will
            # ever write the go file — exit instead of parking a jax-loaded
            # interpreter forever (review r5).
            logger.info("standby orphaned (parent gone); exiting")
            raise SystemExit(0)
        time.sleep(0.05)
    import json

    # JSON payload: the worker id plus per-pod identity env the backend
    # withheld at spawn time so one spare serves any slot (ProcessPodBackend
    # _IDENTITY_KEYS) — e.g. ELASTICDL_WORKER_SLOT, which
    # parallel/distributed.py reads for coordinator selection.
    payload = json.loads(open(go_file).read())
    for k, v in payload.get("env", {}).items():
        os.environ[k] = v
    worker_id = payload["worker_id"]
    logger.info("standby adopted as %s", worker_id)
    return worker_id


def settle_membership(
    master,
    worker_id: str,
    membership: dict,
    *,
    stable_s: Optional[float] = None,
    poll_s: Optional[float] = None,
    max_s: Optional[float] = None,
    clock=time.time,
    sleep=time.sleep,
) -> dict:
    """The gang-formation wait: return the membership view to fix the
    jax.distributed world on.

    When the master publishes the fleet's DESIRED size (``expected``),
    form the world once the full gang is registered AND every member has
    CONFIRMED the current version (registration or the versioned
    heartbeat this loop sends).  Both halves matter: without the size
    gate, staggered relaunches form worlds one member at a time; without
    the confirmation gate, a fresh relaunch forms a world with a STALE
    incarnation that is about to restart — each late restart then
    restarts everyone who already formed (measured 54 s of churn on a
    2-pod peer-death recovery before these gates; docs/perf.md).  Fall
    back to the version-stability heuristic when the master doesn't
    publish a target (hand-spawned workers), and proceed with whoever is
    present at the deadline either way: a crash-looping peer must degrade
    the world, not wedge it.
    """
    stable_s = SETTLE_STABLE_S if stable_s is None else stable_s
    poll_s = SETTLE_POLL_S if poll_s is None else poll_s
    max_s = SETTLE_MAX_S if max_s is None else max_s
    deadline = clock() + max_s
    stable_since = clock()
    while clock() < deadline:
        expected = membership.get("expected") or 0
        confirmed = membership.get("confirmed") or {}
        version = membership["version"]
        if (
            expected
            and membership["world_size"] == expected
            and all(
                confirmed.get(w) == version for w in membership["workers"]
            )
        ):
            # EXACT size, not >=: during a scale-DOWN the doomed members
            # stay registered (and confirmed) through their terminate
            # grace; forming an oversized world with them guarantees an
            # immediate re-collapse as they exit.  An overshoot that
            # never drains falls back to the deadline path below, which
            # proceeds with whoever is present.
            break
        sleep(poll_s)
        try:
            # The versioned heartbeat IS this worker's confirmation of
            # the view it currently intends to form.
            master.call(
                "Heartbeat", {"worker_id": worker_id, "version": version}
            )
            current = master.call("GetMembership", {})
        except Exception:
            # Master briefly unreachable (mass relaunch is exactly when
            # this loop runs): retry next poll rather than burning
            # relaunch budget on a healthy worker.
            continue
        if current["version"] != membership["version"]:
            stable_since = clock()
        elif not expected and clock() - stable_since >= stable_s:
            membership = current
            break
        # Adopt unconditionally: the confirmed map advances WITHOUT a
        # version bump (peers confirm by heartbeat), so updating only on
        # version change would freeze the formation condition at its
        # registration-time snapshot and ride every settle to the
        # deadline.
        membership = current
    return membership


#: Hard-exit bound after SIGTERM: k8s preemption grants a grace window
#: (default 30 s) before SIGKILL; the snapshot must not gamble on using
#: all of it, and a wedged snapshot must still exit RESTART in time for
#: the relaunch to ride the warm standby.
PREEMPTION_EXIT_S = 15.0


def _install_preemption_handler(worker_holder: dict) -> None:
    """SIGTERM = preemption notice (k8s eviction, spot reclaim, pod
    delete): snapshot if safe, then exit RESTART so the pod manager
    relaunches without burning failure budget and gang peers re-form
    immediately instead of discovering the death by heartbeat.

    The handler only SPAWNS the graceful thread: the signal frame may be
    inside jax/XLA calls, where re-entering jax (device_get in the save)
    is not safe — the work happens on a plain thread while a hard timer
    bounds the whole exit (PS main has used this SIGTERM shape since r3;
    the worker was the gap).
    """
    import signal

    def _graceful() -> None:
        try:
            w = worker_holder.get("worker")
            if w is not None:
                w.preemption_snapshot()
        except Exception:
            logger.exception("preemption snapshot failed; exiting anyway")
        finally:
            sys.stderr.flush()
            sys.stdout.flush()
            os._exit(RESTART_EXIT_CODE)

    def _on_term(signum, frame):
        logger.info("SIGTERM: preemption notice; snapshot + RESTART exit")
        threading.Thread(
            target=_graceful, name="preemption", daemon=True
        ).start()
        t = threading.Timer(
            PREEMPTION_EXIT_S, lambda: os._exit(RESTART_EXIT_CODE)
        )
        t.daemon = True
        t.start()

    signal.signal(signal.SIGTERM, _on_term)


def main(argv: Optional[List[str]] = None) -> int:
    try:
        config = JobConfig.from_env()
    except KeyError:
        config = parse_args(argv)
    if not config.master_addr:
        raise SystemExit("worker needs --master_addr (or config via env)")
    from elasticdl_tpu.common.log_utils import set_level

    set_level(config.log_level)
    go_file = os.environ.get("ELASTICDL_STANDBY_GO_FILE", "")
    if go_file:
        worker_id = _park_as_standby(go_file)
    else:
        worker_id = os.environ.get(
            "ELASTICDL_WORKER_ID", f"worker-{os.getpid()}"
        )
    logger.info("worker %s booting (pid %d)", worker_id, os.getpid())
    # Persistent XLA compile cache: every elastic re-join re-jits the train
    # step for its (program, topology); relaunched incarnations load the
    # executable from disk instead of recompiling (~20-40 s on TPU).  This
    # also bounds COMPILE SKEW between gang members forming a collective:
    # XLA:CPU's Gloo context init times out (hard 30 s) if one process is
    # still compiling while its peer already executes — observed when the
    # fused-scan compile ran under CPU contention.
    from elasticdl_tpu.common.platform import enable_compile_cache

    enable_compile_cache()

    # Call deadline + outage ride-through budget come off the config bus
    # (r18): the proxy owns both — see RpcMasterProxy.
    master = RpcMasterProxy(
        config.master_addr,
        call_timeout_s=config.master_call_timeout_s,
        outage_tolerance_s=config.master_outage_tolerance_s,
    )
    # Register EXACTLY ONCE, before any jax computation.  The membership view
    # from this call both (a) seeds the jax.distributed spec (the PJRT world
    # is fixed once created) and (b) is handed to Worker.run verbatim — a
    # second registration inside run() would race a concurrent join and
    # absorb a membership this process's fixed world does not match
    # (VERDICT r2 Weak #3).  Any later change surfaces as a heartbeat
    # version bump, which in multihost mode restarts the process.
    from elasticdl_tpu.parallel import distributed

    # Incarnation nonce (r18): this boot's identity across every
    # registration this process makes — the master resets the worker's
    # report-seq dedup ledger when it changes (a fresh process restarts
    # its seq counter at 1).
    incarnation = f"{os.getpid()}-{int(time.time() * 1e3)}"
    membership = master.call(
        "RegisterWorker",
        {
            "worker_id": worker_id,
            "address": distributed.advertised_address() if config.multihost else "",
            "proto": PROTOCOL_VERSION,
            "incarnation": incarnation,
            # held_tasks=[] (r18): a fresh boot HOLDS nothing — the master
            # requeues any journal-replayed leases still attributed to a
            # previous incarnation of this id NOW, instead of waiting out
            # task_timeout_s.
            "held_tasks": [],
        },
    )
    # Liveness is a background thread, decoupled from the task loop: the
    # startup window (jax.distributed waiting for peers, first XLA compile)
    # and long steps must not look like death to the master's reaper.  The
    # loop's own Heartbeat calls still drive version-change detection.
    hb_stop = threading.Event()
    # Set once the Worker exists; the beat thread then doubles as the
    # DEATH-PUSH receiver (Worker.death_watch_tick): a survivor blocked in
    # a collective on a dead peer force-exits RESTART within ~grace seconds
    # of the master's eviction instead of waiting out the coordination
    # heartbeat (--distributed_heartbeat_timeout_s).
    worker_holder: dict = {}

    def _beat() -> None:
        dw_state: dict = {"pending_since": None}
        while not hb_stop.wait(0.25 if dw_state["pending_since"] else 1.0):
            master_version = None
            w = worker_holder.get("worker")
            try:
                hb = {"worker_id": worker_id}
                if w is not None:
                    # Gang-boundary arrival progress (r13): the beat is
                    # the only RPC still leaving this process while the
                    # task loop is blocked inside a wedged collective —
                    # without it the deadline-bounded boundary could
                    # never tell the straggler (arrival counter frozen)
                    # from the ranks blocked on it (counter one ahead).
                    hb.update(w.gang_beat_fields())
                    # Gauge envelope (r14) on the SAME beat, for the same
                    # reason: a wedged gang's fleet metrics must keep
                    # flowing while the task loop's own heartbeat is
                    # silent (registry locks are leaves — safe from this
                    # thread).
                    gp = w.gauge_payload()
                    if gp is not None:
                        hb["gauge"] = gp
                resp = master.call("Heartbeat", hb)
                master_version = resp.get("version")
            except Exception:  # master briefly unreachable: retry next beat
                pass
            if w is None:
                continue
            try:
                # The Heartbeat response's version lets the tick skip its
                # own membership RPC in the steady state.
                if w.death_watch_tick(
                    dw_state, time.time(), master_version=master_version
                ):
                    sys.stderr.flush()
                    sys.stdout.flush()
                    os._exit(RESTART_EXIT_CODE)
            except Exception:
                logger.exception("death watch tick failed; will retry")

    threading.Thread(target=_beat, daemon=True, name="heartbeat").start()
    _install_preemption_handler(worker_holder)
    logger.info(
        "worker %s registered (membership v%s, world %s)",
        worker_id, membership.get("version"), membership.get("world_size"),
    )

    if config.multihost:
        membership = settle_membership(master, worker_id, membership)
        spec = distributed.spec_from_membership(
            membership,
            worker_id,
            config.coordinator_port,
            heartbeat_timeout_s=config.distributed_heartbeat_timeout_s,
        )
        distributed.initialize(spec)
    # The process-default registry (r14): the worker's own families plus
    # cross-cutting client-side ones (the PS retry counter records via
    # gauge.default()) all land in ONE registry, so the scrape endpoint
    # below serves everything this process measures.
    from elasticdl_tpu.common import gauge
    from elasticdl_tpu.common.metrics_http import maybe_start

    worker = Worker(
        config, master, build_job_reader(config), worker_id=worker_id,
        gauges=gauge.default(), incarnation=incarnation,
    )
    worker_holder["worker"] = worker
    metrics_server = maybe_start(
        config.gauge_port,
        worker.gauges.render_prometheus,
        health_fn=lambda: {
            "role": "worker",
            "worker_id": worker_id,
            "membership_version": worker._membership_version,
        },
        registry=worker.gauges,
    )
    try:
        result = worker.run(membership=membership)
    except WorkerRestartRequired as e:
        logger.info("worker %s restarting: %s", worker_id, e)
        hb_stop.set()
        # Skip interpreter teardown: atexit hooks (jax.distributed shutdown,
        # gRPC channels) can block for tens of seconds against peers that
        # are mid-collective or already gone.  The relaunch replaces the
        # whole process anyway — exit NOW so the pod manager can.
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(RESTART_EXIT_CODE)
    finally:
        hb_stop.set()
        if metrics_server is not None:
            metrics_server.stop()
    logger.info("worker %s finished: %s", worker_id, result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
