"""Worker pod entry point.

Reference parity (SURVEY.md §2 #7 [U]): the master renders worker pods whose
command is the worker main module and whose args/env carry the job config;
here the config bus is the ``ELASTICDL_JOB_CONFIG`` env var (set by the
PodManager) with CLI flags as a fallback, and the worker id comes from
``ELASTICDL_WORKER_ID`` (the pod name).

Run as ``python -m elasticdl_tpu.worker.main``.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

from elasticdl_tpu.common.config import JobConfig, parse_args
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.platform import apply_platform_env

apply_platform_env()
from elasticdl_tpu.data.reader import (
    AbstractDataReader,
    CompositeDataReader,
    create_data_reader,
)
from elasticdl_tpu.worker.worker import (
    RESTART_EXIT_CODE,
    RpcMasterProxy,
    Worker,
    WorkerRestartRequired,
)

logger = get_logger("worker.main")


def build_job_reader(config: JobConfig) -> AbstractDataReader:
    """One reader serving every dataset the job's tasks may name."""
    params = config.parsed_data_reader_params()
    paths = [
        p
        for p in (
            config.training_data,
            config.validation_data,
            config.prediction_data,
        )
        if p
    ]
    if not paths:
        raise ValueError("job config names no data paths")
    readers = [create_data_reader(p, params) for p in dict.fromkeys(paths)]
    return readers[0] if len(readers) == 1 else CompositeDataReader(readers)


def main(argv: Optional[List[str]] = None) -> int:
    try:
        config = JobConfig.from_env()
    except KeyError:
        config = parse_args(argv)
    if not config.master_addr:
        raise SystemExit("worker needs --master_addr (or config via env)")
    worker_id = os.environ.get("ELASTICDL_WORKER_ID", f"worker-{os.getpid()}")

    master = RpcMasterProxy(config.master_addr)
    if config.multihost:  # pragma: no cover - needs real multi-host
        # Join the jax.distributed world BEFORE any jax computation (the
        # PJRT backend is fixed once created): register over plain gRPC,
        # derive this process's spec from membership, initialize.
        from elasticdl_tpu.parallel import distributed

        membership = master.call(
            "RegisterWorker",
            {"worker_id": worker_id, "address": distributed.advertised_address()},
        )
        spec = distributed.spec_from_membership(
            membership, worker_id, config.coordinator_port
        )
        distributed.initialize(spec)
    worker = Worker(
        config, master, build_job_reader(config), worker_id=worker_id
    )
    try:
        result = worker.run()
    except WorkerRestartRequired as e:
        logger.info("worker %s restarting: %s", worker_id, e)
        return RESTART_EXIT_CODE
    logger.info("worker %s finished: %s", worker_id, result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
