"""lock-discipline: annotated shared attributes only under their lock.

Convention (docs/static_analysis.md): an attribute assignment carrying a
``# guarded-by: <lock>`` comment — normally in ``__init__`` — registers the
attribute as guarded by ``self.<lock>``.  Every other touch (load or store)
of ``self.<attr>`` in that class must then sit lexically inside a
``with self.<lock>:`` block, or inside a method whose ``def`` line carries
the same ``# guarded-by: <lock>`` annotation (the *_locked helper pattern:
the caller holds the lock).

Deliberate scoping, matching the runtime semantics:

- ``__init__`` is exempt: construction happens-before publication.
- A nested ``def``/``lambda`` does NOT inherit the enclosing ``with``:
  closures (background-thread bodies, callbacks) execute after the lock is
  released, which is exactly the race class this pass exists to catch.
- The analysis is lexical, per-class, and intra-procedural — a method that
  takes the lock and then calls a helper is expressed by annotating the
  helper's ``def`` line, not inferred.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile


def _self_attr(node: ast.AST):
    """``self.X`` -> ``"X"``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class LockDisciplinePass(LintPass):
    name = "lock-discipline"
    description = (
        "attributes declared '# guarded-by: <lock>' may only be touched "
        "inside 'with self.<lock>:'"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(src, node))
        return findings

    def _check_class(self, src: SourceFile, cls: ast.ClassDef) -> List[Finding]:
        guarded: Dict[str, str] = {}  # attr -> lock name
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                lock = src.guarded_by(node.lineno)
                if lock is None:
                    continue
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        guarded[attr] = lock
        if not guarded:
            return []
        findings: List[Finding] = []
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            held = set()
            lock = src.guarded_by(stmt.lineno)
            if lock is not None:
                held.add(lock)
            exempt = stmt.name == "__init__"
            self._walk(src, stmt.body, guarded, held, exempt, findings)
        return findings

    def _walk(self, src, body, guarded, held, exempt, findings) -> None:
        for node in body:
            self._visit(src, node, guarded, held, exempt, findings)

    def _visit(self, src, node, guarded, held, exempt, findings) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # Deferred execution: the enclosing with-block's lock is NOT
            # held when a closure runs.  A def-line annotation may re-assert
            # it (a helper documented as called-with-lock-held).
            inner_held = set()
            lock = src.guarded_by(node.lineno)
            if lock is not None:
                inner_held.add(lock)
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                self._visit(src, child, guarded, inner_held, exempt, findings)
            return
        if isinstance(node, ast.With):
            acquired = set()
            for item in node.items:
                ctx = item.context_expr
                attr = _self_attr(ctx)
                if attr is not None and attr in set(guarded.values()):
                    acquired.add(attr)
            new_held = held | acquired
            for item in node.items:
                self._visit(src, item.context_expr, guarded, held, exempt, findings)
            self._walk(src, node.body, guarded, new_held, exempt, findings)
            return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None and attr in guarded and not exempt:
                lock = guarded[attr]
                if lock not in held:
                    # The declaring line itself (re-annotated elsewhere) is
                    # still a touch; only __init__ is exempt by position.
                    findings.append(Finding(
                        self.name, src.path, node.lineno,
                        f"self.{attr} is guarded by self.{lock} but touched "
                        f"outside 'with self.{lock}:' (annotate the method "
                        f"'# guarded-by: {lock}' if the caller holds it)",
                    ))
            # fall through: visit children (e.g. self.a.b -> self.a)
        for child in ast.iter_child_nodes(node):
            self._visit(src, child, guarded, held, exempt, findings)
