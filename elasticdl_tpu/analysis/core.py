"""graftlint core: source model, waivers, pass protocol, runner.

Design constraints:

- **Pure stdlib** (``ast`` + ``tokenize``): the linter gates tier-1 and
  pre-commit; it must never pay — or hang on — a jax/grpc import.
- **Comment conventions are the contract.**  Annotations ride comments
  (``# guarded-by: _lock``, ``# hot-path``) because the invariants they
  declare are about *runtime concurrency*, which the type system cannot
  express, and because a comment on the declaring line keeps the
  declaration next to the thing it protects.
- **Waivers require a reason.**  ``# graftlint: allow[<rule>] <reason>``
  on the finding's line (or a comment-only line directly above it).  A
  waiver with no rule, an unknown rule, or no reason is itself a finding
  (rule ``waiver-syntax``) — the escape hatch cannot silently rot.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence

#: Every rule a waiver may name.  Passes register here at import; the
#: waiver validator rejects anything else (typo'd waivers must fail loud,
#: or they would silently waive nothing).
KNOWN_RULES = {
    "lock-discipline",
    "hot-path-sync",
    "compat-shim",
    "rpc-discipline",
    "thread-hygiene",
    "import-hygiene",
    # r12: hot-path trace emission must use the non-blocking ring API only
    # (common/trace.py's span/instant); export/drain calls are findings.
    "trace-discipline",
    # r13: hot-path fault-injection crossings use the no-op-when-disabled
    # chaos.hook only (chaos/inject.py); setup/injector API is a finding.
    "chaos-discipline",
    # r14: hot-path metric updates use the O(1) counter/gauge/histogram
    # API only (common/gauge.py); scrape/aggregation calls are findings.
    "gauge-discipline",
    # v2 interprocedural passes (analysis/callgraph.py layer).
    "blocking-propagation",
    "lock-order",
    # v5: thread-role inference (analysis/thread_map.py) + cross-role
    # unguarded shared state (analysis/shared_state.py); also covers the
    # '# thread-role:' / '# single-writer:' / '# gil-atomic' annotation
    # grammar, which the pass validates itself.
    "shared-state",
    # v6: compile & transfer discipline (analysis/jit_discipline.py) —
    # raw jax.jit only in the shim (with name= declared at shim call
    # sites), no fresh-compile-cache-per-invocation jit bindings, and no
    # device->host materialization of jit-boundary values reachable from
    # '# hot-path' functions.  Runtime twin: common/jitsan.py.
    "jit-shim",
    "jit-stability",
    "transfer-discipline",
    # v7: durability discipline (analysis/durability.py) — writes to
    # '# durable-file' paths route through common/durable.py (atomic
    # publish / fsync'd append; no raw renames, no hand-rolled '.tmp'
    # names), and '# recovery-path' readers use the shared torn-tolerant
    # readers.  Runtime twin: common/crashsan.py.
    "durable-write-discipline",
    "recovery-read-discipline",
    # v8: wire-schema discipline (analysis/wire_discipline.py) — sender
    # payloads carry only MessageSchema-declared keys, receiver handlers
    # and client response reads never subscript OPTIONAL fields, and
    # breaking schema drift against artifacts/wire_schema.lock.json needs
    # a PROTOCOL_VERSION bump + regenerated lock in the same diff.
    # Runtime twin: common/wiresan.py.
    "wire-discipline",
    "wire-evolution",
    # A waiver that suppresses no finding is itself a finding: the waiver
    # inventory must not rot as code moves (see run_passes).
    "stale-waiver",
    "waiver-syntax",
    # Unreadable / syntactically invalid files: not waivable (a broken file
    # cannot carry a trustworthy waiver), but a distinct rule id so the
    # artifact's per-rule counts don't misattribute them to waiver grammar.
    "parse-error",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Waiver:
    rule: str
    reason: str
    line: int


#: Comments of the shape ``graftlint: <payload>`` (after a hash) mark
#: waivers; the payload grammar is validated separately so malformed
#: payloads become findings instead of silent no-ops.
_WAIVER_MARK = re.compile(r"#\s*graftlint\s*:\s*(?P<payload>.*)$")
_WAIVER_PAYLOAD = re.compile(
    r"^allow\[(?P<rule>[^\]]*)\]\s*(?P<reason>.*)$"
)

_GUARDED_BY = re.compile(r"#\s*guarded-by\s*:\s*(?P<lock>[A-Za-z_][A-Za-z0-9_]*)")
_HOT_PATH = re.compile(r"#\s*hot-path\b")


class SourceFile:
    """One parsed python file: AST + per-line comments + waivers.

    ``path`` is the display path (repo-relative when linting the repo);
    passes that exempt specific files (compat-shim) match on its suffix.
    """

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        #: line number -> full comment text (including the ``#``).  A line
        #: holds at most one comment token.
        self.comments: Dict[int, str] = {}
        #: lines that contain ONLY a comment (a waiver there applies to the
        #: next line down).
        self.comment_only_lines: set = set()
        self._scan_comments()
        self.waivers: Dict[int, Waiver] = {}
        self.waiver_errors: List[Finding] = []
        #: Waiver lines that suppressed at least one finding this run —
        #: populated by ``waived()``; the runner turns the complement into
        #: ``stale-waiver`` findings.
        self.used_waiver_lines: set = set()
        self._parse_waivers()

    def _scan_comments(self) -> None:
        tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
        lines = self.text.splitlines()
        try:
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    row, col = tok.start
                    self.comments[row] = tok.string
                    if lines[row - 1][:col].strip() == "":
                        self.comment_only_lines.add(row)
        except tokenize.TokenError:
            # ast.parse already accepted the file; an incidental tokenizer
            # wobble (rare, e.g. on odd trailing bytes) degrades to "no
            # comments seen", never to a crash of the whole lint run.
            pass

    def _parse_waivers(self) -> None:
        for line, comment in self.comments.items():
            m = _WAIVER_MARK.search(comment)
            if m is None:
                continue
            payload = m.group("payload").strip()
            pm = _WAIVER_PAYLOAD.match(payload)
            if pm is None:
                self.waiver_errors.append(Finding(
                    "waiver-syntax", self.path, line,
                    f"malformed waiver {payload!r}: expected "
                    "'allow[<rule>] <reason>'",
                ))
                continue
            rule = pm.group("rule").strip()
            reason = pm.group("reason").strip()
            if not rule:
                self.waiver_errors.append(Finding(
                    "waiver-syntax", self.path, line,
                    "waiver names no rule: 'allow[]' must name the rule "
                    "it waives",
                ))
                continue
            if rule not in KNOWN_RULES:
                self.waiver_errors.append(Finding(
                    "waiver-syntax", self.path, line,
                    f"waiver names unknown rule {rule!r} "
                    f"(known: {', '.join(sorted(KNOWN_RULES))})",
                ))
                continue
            if not reason:
                self.waiver_errors.append(Finding(
                    "waiver-syntax", self.path, line,
                    f"waiver for {rule!r} carries no reason — every "
                    "waiver must say why the rule does not apply",
                ))
                continue
            self.waivers[line] = Waiver(rule, reason, line)

    # -- annotation lookups --

    def guarded_by(self, line: int) -> Optional[str]:
        """Lock name from a ``# guarded-by: <lock>`` comment on ``line``."""
        comment = self.comments.get(line)
        if comment is None:
            return None
        m = _GUARDED_BY.search(comment)
        return m.group("lock") if m else None

    def is_hot_path(self, line: int) -> bool:
        """``# hot-path`` marker on ``line`` or anywhere in the contiguous
        block of comment-only lines directly above it (markers may wrap
        onto multiple comment lines of prose)."""
        comment = self.comments.get(line)
        if comment is not None and _HOT_PATH.search(comment):
            return True
        cand = line - 1
        while cand in self.comment_only_lines:
            if _HOT_PATH.search(self.comments[cand]):
                return True
            cand -= 1
        return False

    def waived(self, finding: Finding) -> bool:
        """A finding is waived by a matching-rule waiver on its own line or
        on a comment-only line directly above it."""
        for cand in (finding.line, finding.line - 1):
            w = self.waivers.get(cand)
            if w is None:
                continue
            if cand == finding.line - 1 and cand not in self.comment_only_lines:
                continue
            if w.rule == finding.rule:
                self.used_waiver_lines.add(cand)
                return True
        return False


class LintPass:
    """One rule.  Per-file passes implement ``run``; whole-project passes
    (import-hygiene needs the module graph) implement ``run_project``."""

    name: str = ""
    description: str = ""

    def run(self, src: SourceFile) -> Iterable[Finding]:
        return ()

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        return ()


# -- AST helpers shared by passes --

def attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute/name chain (``self.master.call`` ->
    ``"self.master.call"``); ``""`` when the chain bottoms out in a call or
    subscript (dynamic receiver)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def receiver_hinted(func: ast.Attribute, hints: Sequence[str]) -> bool:
    """True when an attribute call's RECEIVER looks like one of ``hints``
    — either the chain's last segment before the method name
    (``trace.export()`` -> ``trace``), or, for a dynamic receiver whose
    chain bottoms out in a call (``trace.default().export()``), any
    segment of the inner call's own chain.  The shared matcher behind the
    trace-/chaos-discipline passes: ambiguous method verbs (``export``,
    ``configure``) only flag on receivers shaped like the guarded API."""
    chain = attr_chain(func)
    if chain:
        recv = chain.rsplit(".", 1)[0].split(".")[-1]
        return recv in hints
    inner = func.value
    if isinstance(inner, ast.Call):
        ichain = attr_chain(inner.func)
        return any(part in hints for part in ichain.split("."))
    return False


class HotPathCallDisciplinePass(LintPass):
    """Shared shape of the trace-/chaos-discipline rules: inside a
    ``# hot-path`` function's steady-state body, calls matching the
    subclass's predicate are findings.  Exemptions — identical across the
    family by design, so a traversal fix lands in both rules at once:

    - nested ``def``/``lambda`` bodies (deferred execution owns its own
      time);
    - ``except`` handler bodies (the error path), while ``try``/``else``/
      ``finally`` bodies stay in scope;
    - NO ``phases.phase(...)`` excuse, unlike hot-path-sync: the guarded
      APIs are control-plane surfaces, not accountable hot-path phases.

    Subclasses set ``name``/``description``/``message`` and implement
    ``is_flagged_call``."""

    #: Finding text appended at each flagged call site.
    message: str = ""

    def is_flagged_call(self, node: ast.Call) -> bool:
        raise NotImplementedError

    def run(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if src.is_hot_path(node.lineno):
                    for stmt in node.body:
                        self._visit(src, stmt, findings)
        return findings

    def _visit(self, src, node, findings) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # deferred execution: not this function's hot path
        if isinstance(node, ast.Try):
            for stmt in node.body + node.orelse + node.finalbody:
                self._visit(src, stmt, findings)
            return  # handlers (error path) skipped
        if isinstance(node, ast.Call) and self.is_flagged_call(node):
            findings.append(Finding(
                self.name, src.path, node.lineno, self.message,
            ))
        for child in ast.iter_child_nodes(node):
            self._visit(src, child, findings)


def iter_file_paths(paths: Sequence[str]) -> List[str]:
    """Every ``.py`` file under ``paths`` (files pass through), skipping
    ``__pycache__`` and hidden directories, sorted for stable output."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(names):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    return sorted(dict.fromkeys(out))


def load_sources(
    file_paths: Sequence[str], rel_to: Optional[str] = None
) -> tuple:
    """Parse files into SourceFiles; unparseable files become findings (a
    syntax error must fail the gate, not crash it).  Returns
    ``(sources, error_findings)``."""
    sources: List[SourceFile] = []
    errors: List[Finding] = []
    for fp in file_paths:
        display = os.path.relpath(fp, rel_to) if rel_to else fp
        try:
            with open(fp, encoding="utf-8") as f:
                text = f.read()
        except (OSError, UnicodeDecodeError) as e:
            errors.append(Finding("parse-error", display, 1, f"unreadable: {e}"))
            continue
        try:
            sources.append(SourceFile(display, text))
        except SyntaxError as e:
            errors.append(Finding(
                "parse-error", display, e.lineno or 1, f"syntax error: {e.msg}"
            ))
    return sources, errors


def run_passes(
    sources: Sequence[SourceFile],
    passes: Sequence[LintPass],
    only_paths: Optional[set] = None,
) -> List[Finding]:
    """All findings across ``sources``, waivers applied.  ``only_paths``
    restricts *reporting* to those display paths (``--changed`` mode) while
    project passes still see the whole file set."""
    findings: List[Finding] = []
    by_path = {s.path: s for s in sources}
    for src in sources:
        if only_paths is not None and src.path not in only_paths:
            continue
        # waiver-syntax findings are never waivable (a broken escape hatch
        # must not be able to excuse itself).
        findings.extend(src.waiver_errors)
        for p in passes:
            for f in p.run(src):
                if not src.waived(f):
                    findings.append(f)
    for p in passes:
        for f in p.run_project(sources):
            src = by_path.get(f.path)
            if src is not None and src.waived(f):
                continue
            if only_paths is not None and f.path not in only_paths:
                continue
            findings.append(f)
    # Stale waivers: a waiver that suppressed nothing is itself a finding —
    # the inventory must shrink as code moves, not fossilize.  Only judged
    # for rules that actually RAN (a subset lint cannot know whether the
    # waiver is live) — except waiver-syntax, which is never waivable, so
    # a waiver naming it is stale by construction.  allow[stale-waiver]
    # waivers are exempt from staleness (they exist to waive THIS rule's
    # findings; recursing would make them un-waivable).
    active_rules = {p.name for p in passes} | {"waiver-syntax"}
    for src in sources:
        if only_paths is not None and src.path not in only_paths:
            continue
        for line, w in sorted(src.waivers.items()):
            if w.rule == "stale-waiver" or w.rule not in active_rules:
                continue
            if line in src.used_waiver_lines:
                continue
            f = Finding(
                "stale-waiver", src.path, line,
                f"waiver for {w.rule!r} suppresses no finding — the code "
                "it excused moved or was fixed; delete the waiver",
            )
            if not src.waived(f):
                findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def collect_waivers(
    sources: Sequence[SourceFile], only_paths: Optional[set] = None
) -> List[dict]:
    """The waiver inventory (file, line, rule, reason) — stamped into the
    LINT artifact and ``--json`` output so waiver count per rule is
    trackable across rounds."""
    out: List[dict] = []
    for src in sources:
        if only_paths is not None and src.path not in only_paths:
            continue
        for line, w in sorted(src.waivers.items()):
            out.append({
                "path": src.path, "line": line,
                "rule": w.rule, "reason": w.reason,
            })
    return out


def run_lint_full(
    paths: Sequence[str],
    passes: Optional[Sequence[LintPass]] = None,
    rel_to: Optional[str] = None,
    only_paths: Optional[set] = None,
    preloaded: Optional[tuple] = None,
) -> tuple:
    """Lint ``paths``; returns ``(findings, sources)`` so callers (CLI
    waiver inventory, --callgraph stats) reuse the parsed files.
    ``preloaded`` is an already-computed ``load_sources`` result for the
    same paths (the --changed dependent scan parses first; re-reading 80+
    files would double the pre-commit cost)."""
    if passes is None:
        from elasticdl_tpu.analysis import all_passes

        passes = all_passes()
    if preloaded is not None:
        sources, errors = preloaded
    else:
        sources, errors = load_sources(iter_file_paths(paths), rel_to=rel_to)
    if only_paths is not None:
        # Changed-only mode scopes REPORTING, parse errors included — an
        # out-of-scope broken file must not fail a scoped run.
        errors = [f for f in errors if f.path in only_paths]
    findings = sorted(
        errors + run_passes(sources, passes, only_paths=only_paths),
        key=lambda f: (f.path, f.line, f.rule),
    )
    return findings, sources


def run_lint(
    paths: Sequence[str],
    passes: Optional[Sequence[LintPass]] = None,
    rel_to: Optional[str] = None,
    only_paths: Optional[set] = None,
) -> List[Finding]:
    """Lint ``paths`` with ``passes`` (default: the full suite)."""
    return run_lint_full(
        paths, passes, rel_to=rel_to, only_paths=only_paths
    )[0]


def lint_text(
    text: str,
    passes: Sequence[LintPass],
    path: str = "fixture.py",
) -> List[Finding]:
    """Lint an in-memory snippet (the test-fixture entry point)."""
    src = SourceFile(path, text)
    return run_passes([src], passes)
