"""collective-shim: every reduction routes through the graftreduce layer.

r15 built ``parallel/collectives.py`` — ONE module owning how gradient
and metric reductions run (flat vs hierarchical topology routing, and
the subgroup-weight renormalization of timeout-bounded participation).
A raw ``lax.psum`` call site bypasses all of it: it is always flat, it
cannot be excluded-and-renormalized, and the next topology change would
have to find it by hand (exactly the r6 shard_map hunt the compat-shim
pass mechanized).  So, outside the two shim modules —
``parallel/collectives.py`` itself and ``common/jax_compat.py`` (whose
``axis_size`` fallback is a psum of the unit constant) — the following
are findings:

- ``lax.psum`` / ``lax.pmean`` / ``lax.psum_scatter`` attribute use
  (and the ``jax.lax.*`` spellings);
- ``from jax.lax import psum`` / ``pmean`` / ``psum_scatter`` — an
  import alias would otherwise smuggle the raw spelling past the
  attribute check.

``lax.all_gather`` / ``lax.ppermute`` stay legal: they move data, they
do not reduce — the renormalization and hierarchy concerns that make
reductions shim-worthy do not apply.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain

#: Modules allowed to spell the raw reductions.
SHIM_MODULE_SUFFIXES = (
    "parallel/collectives.py",
    "common/jax_compat.py",
)

_REDUCTIONS = ("psum", "pmean", "psum_scatter")

_FORBIDDEN_ATTR_CHAINS = {
    f"{prefix}.{name}": name
    for name in _REDUCTIONS
    for prefix in ("lax", "jax.lax")
}

_SHIM_HINT = {
    "psum": "elasticdl_tpu.parallel.collectives.psum",
    "pmean": "elasticdl_tpu.parallel.collectives.pmean",
    "psum_scatter": "elasticdl_tpu.parallel.collectives.psum_scatter",
}


class CollectiveShimPass(LintPass):
    name = "collective-shim"
    description = (
        "raw lax.psum / lax.pmean / lax.psum_scatter only inside "
        "parallel/collectives.py and common/jax_compat.py"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        path = src.path.replace("\\", "/")
        if any(path.endswith(s) for s in SHIM_MODULE_SUFFIXES):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "jax.lax" or mod.startswith("jax.lax."):
                    for alias in node.names:
                        if alias.name in _REDUCTIONS:
                            findings.append(Finding(
                                self.name, src.path, node.lineno,
                                f"raw {alias.name} import bypasses the "
                                "collective layer — use "
                                f"{_SHIM_HINT[alias.name]} (graftreduce)",
                            ))
            elif isinstance(node, ast.Attribute):
                chain = attr_chain(node)
                name = _FORBIDDEN_ATTR_CHAINS.get(chain)
                if name is not None:
                    findings.append(Finding(
                        self.name, src.path, node.lineno,
                        f"raw {chain} bypasses the collective layer — use "
                        f"{_SHIM_HINT[name]} (graftreduce: topology routing "
                        "+ subgroup renormalization live there)",
                    ))
        return findings
