"""shared-state: unannotated state crossing thread roles must be locked.

The v5 race detector.  lock-discipline only judges attributes someone
already annotated ``# guarded-by:``; every check-and-set race the review
rounds hand-found since r6 (``_max_steps_hit``, ``_known_workers``, the
lazy IngestPool creation) lived in UNannotated state shared between the
task loop and a gRPC pool / watcher / timer thread.  This pass closes
that hole on top of the thread map (analysis/thread_map.py):

For every ``self.<attr>`` of a class, collect each access site with its
thread roles (from the map) and the locks lexically held there (the
lock-order held-lock context, plus the ``# guarded-by: <lock>`` def-line
convention for called-with-lock-held helpers).  An attribute is a
finding when

- it is WRITTEN outside ``__init__`` on some role, and
- its access sites span >= 2 distinct roles, and
- the sites share NO common held lock.

Accesses in ``__init__`` are exempt (construction happens-before the
spawn that publishes ``self``), as are sites in functions whose role the
map cannot infer (unknown context must not manufacture findings).

Escape hatches — each itself checked — on the declaring assignment line:

- ``# guarded-by: <lock>``      lock-discipline owns it (out of scope
                                here);
- ``# single-writer: <role>``   only ``<role>`` may write (any write
                                site on another role is a finding; reads
                                ride the GIL's torn-free loads).  The
                                role must exist in the thread map;
- ``# gil-atomic``              single-op loads/plain stores only: an
                                augmented assignment (read-modify-write
                                at one site) under this annotation is a
                                finding;
- ``# graftlint: allow[shared-state] <reason>`` — the reasoned waiver.

Blind spots, by design (the runtime twin ``common/racesan.py`` covers
the dynamic side): instance confinement (a per-thread instance of a
shared class still looks cross-role), same-role concurrency (two
threads of one role), state shared through containers/globals rather
than ``self``, and roles the map cannot reach (see the thread-map blind
spots).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from elasticdl_tpu.analysis.callgraph import shared_graph
from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile
from elasticdl_tpu.analysis.import_hygiene import _module_name
from elasticdl_tpu.analysis.thread_map import MAIN_ROLE, shared_thread_map

_SINGLE_WRITER = re.compile(
    r"#\s*single-writer\s*:\s*(?P<role>[^#]*)"
)
_GIL_ATOMIC = re.compile(r"#\s*gil-atomic\b")


class _Site:
    __slots__ = ("path", "line", "write", "rmw", "held", "roles", "func")

    def __init__(self, path, line, write, rmw, held, roles, func):
        self.path = path
        self.line = line
        self.write = write
        self.rmw = rmw
        self.held = held  # frozenset of lock tokens
        self.roles = roles  # frozenset of role names
        self.func = func  # short function name for the witness text

    def witness(self) -> str:
        kind = "rmw" if self.rmw else ("write" if self.write else "read")
        roles = ",".join(sorted(self.roles)) or "?"
        return f"{kind}@{self.path}:{self.line} [{roles}] in {self.func}"


class SharedStatePass(LintPass):
    name = "shared-state"
    description = (
        "a self.<attr> written on one thread role and touched on another "
        "must share a lock, or carry '# single-writer: <role>' / "
        "'# gil-atomic' / '# guarded-by: <lock>' on its declaring line"
    )

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        graph = shared_graph(files)
        tmap = shared_thread_map(files)
        findings: List[Finding] = list(tmap.errors)
        known_roles = tmap.known_roles() | {MAIN_ROLE}

        guarded, annos = self._scan_annotations(files, findings, known_roles)

        # Group access sites by (module:Class, attr).
        sites: Dict[Tuple[str, str], List[_Site]] = {}
        for q, fn in graph.functions.items():
            if not fn.cls_name or not fn.attr_accesses:
                continue
            mod = q.split(":", 1)[0]
            cls_key = f"{mod}:{fn.cls_name}"
            method = q.split(":", 1)[1]
            if method == f"{fn.cls_name}.__init__":
                continue  # construction happens-before publication
            src = graph.sources.get(fn.path)
            extra_held = ()
            if src is not None:
                lock = src.guarded_by(fn.line)
                if lock is not None:
                    extra_held = (f"{cls_key}.{lock}",)
            roles = tmap.roles_of(q)
            func_short = method
            for acc in fn.attr_accesses:
                sites.setdefault((cls_key, acc.attr), []).append(_Site(
                    fn.path, acc.line, acc.write, acc.rmw,
                    frozenset(acc.held) | frozenset(extra_held),
                    roles, func_short,
                ))

        for (cls_key, attr), accs in sorted(sites.items()):
            if f"{cls_key}.{attr}" in graph.locks:
                continue  # the lock itself, not data
            if attr in guarded.get(cls_key, ()):
                continue  # lock-discipline owns it
            anno = annos.get((cls_key, attr))
            if anno is not None and anno[0] == "gil-atomic":
                for s in accs:
                    if s.rmw:
                        findings.append(Finding(
                            self.name, s.path, s.line,
                            f"self.{attr} is declared '# gil-atomic' but "
                            "this site is a read-modify-write (augmented "
                            "assignment) — gil-atomic is only legal on "
                            "single-op load/store sites; lock it or drop "
                            "the annotation",
                        ))
                continue
            if anno is not None and anno[0] == "single-writer":
                writer = anno[1]
                for s in accs:
                    if s.write and s.roles and not (s.roles <= {writer}):
                        findings.append(Finding(
                            self.name, s.path, s.line,
                            f"self.{attr} is declared '# single-writer: "
                            f"{writer}' but written on role(s) "
                            f"{','.join(sorted(s.roles))} at this site — "
                            "route the write through the declared writer "
                            "role or lock the attribute",
                        ))
                continue
            findings.extend(self._cross_role(cls_key, attr, accs))
        return findings

    # -- annotations --

    def _scan_annotations(
        self, files: Sequence[SourceFile], findings: List[Finding],
        known_roles,
    ):
        """Per class: the '# guarded-by' attr set (lock-discipline's
        contract) and the v5 single-writer/gil-atomic declarations."""
        guarded: Dict[str, set] = {}
        annos: Dict[Tuple[str, str], Tuple[str, str]] = {}
        for src in files:
            mod = _module_name(src.path) or src.path
            for node in src.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                cls_key = f"{mod}:{node.name}"
                for sub in ast.walk(node):
                    if not isinstance(
                        sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)
                    ):
                        continue
                    targets = (
                        sub.targets if isinstance(sub, ast.Assign)
                        else [sub.target]
                    )
                    attrs = [
                        t.attr for t in targets
                        if isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ]
                    if not attrs:
                        continue
                    if src.guarded_by(sub.lineno) is not None:
                        guarded.setdefault(cls_key, set()).update(attrs)
                    comment = src.comments.get(sub.lineno, "")
                    m = _SINGLE_WRITER.search(comment)
                    if m is not None:
                        # First token only: trailing prose is rationale.
                        tokens = m.group("role").split()
                        role = tokens[0] if tokens else ""
                        if role not in known_roles:
                            findings.append(Finding(
                                self.name, src.path, sub.lineno,
                                f"single-writer names unknown role {role!r}"
                                " — the role must be one the thread map "
                                "infers (see tools/graftlint.py "
                                "--threadmap)",
                            ))
                        else:
                            for attr in attrs:
                                annos.setdefault(
                                    (cls_key, attr), ("single-writer", role)
                                )
                    elif _GIL_ATOMIC.search(comment):
                        for attr in attrs:
                            annos.setdefault(
                                (cls_key, attr), ("gil-atomic", "")
                            )
        return guarded, annos

    # -- the core judgement --

    @staticmethod
    def _pair_conflicts(w: _Site, s: _Site) -> bool:
        """A write site and another site can race iff they may run on
        DIFFERENT roles concurrently and share no held lock.  Judged
        pairwise — a global all-site lock intersection would flag a
        writer role's own unlocked read of its attribute, which cannot
        race the writes it is sequenced with."""
        if not w.held.isdisjoint(s.held):
            return False
        # Two distinct roles exist across the pair iff the union spans
        # >= 2 (this also covers w IS s: one multi-role site races
        # itself); a single shared role means the sites are sequenced on
        # one domain and cannot race.
        return len(w.roles | s.roles) >= 2

    def _cross_role(
        self, cls_key: str, attr: str, accs: List[_Site]
    ) -> List[Finding]:
        judged = [s for s in accs if s.roles]
        writes = [s for s in judged if s.write]
        if not writes:
            return []
        if len(frozenset().union(*(s.roles for s in judged))) < 2:
            return []
        # One finding per attribute, anchored at the first conflicting
        # write site so a single reasoned waiver (or fix) covers it.
        for w in sorted(writes, key=lambda s: (s.path, s.line)):
            other = next(
                (s for s in judged if self._pair_conflicts(w, s)), None
            )
            if other is None:
                continue
            pair_roles = sorted(w.roles | other.roles)
            cls_short = cls_key.split(":", 1)[1]
            return [Finding(
                self.name, w.path, w.line,
                f"{cls_short}.{attr} is shared across thread roles "
                f"({', '.join(pair_roles)}) with no common lock: "
                f"{w.witness()} vs {other.witness()} — guard both sites "
                "with one lock, or declare '# single-writer: <role>' / "
                "'# gil-atomic' on the declaring line, or waive with a "
                "reason",
            )]
        return []
