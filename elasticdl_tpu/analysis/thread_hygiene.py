"""thread-hygiene: every thread is daemonized or provably joined.

A non-daemon thread that is never joined keeps the process alive after
main exits — in this codebase that turns a clean worker RESTART exit into
a hang the pod manager must SIGKILL out of (and a leaked prep/checkpoint
thread can pin device buffers).  The rule: every ``threading.Thread(...)``
constructor must either

- pass ``daemon=True`` at construction, or
- have a ``.join(...)`` call in the same lexical scope (function body, or
  module top level for module-level threads) — the bench-tool
  ``threads = [...]; for t in threads: t.start(); ... t.join()`` pattern,
  or a ``<t>.daemon = True`` assignment in that scope.

``threading.Timer(...)`` (v5) is a Thread subclass whose constructor
takes NO ``daemon=`` kwarg, so its proof set is the scope-local
``<t>.daemon = True`` assignment, a ``.join(...)``, or a ``.cancel()``
(a cancelled timer cannot outlive the scope's intent).

``ThreadPoolExecutor(...)`` (v5) owns non-daemon worker threads; a bare
anonymous pool leaks them.  A constructor is accounted when it is
assigned to a ``self.<attr>`` (the owner manages shutdown), passed
directly as an argument to another call (``grpc.server(...)`` owns it),
used as a context manager, or its scope calls ``.shutdown(...)``.

The join-proof is scope-local and name-blind (it accepts any ``x.join()``
in the scope that is not a string/``os.path`` join): a cross-function
hand-off (constructed here, joined elsewhere) is expressed with a waiver
naming the join site.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain


def _ctor_kind(node: ast.Call) -> str:
    """'thread' | 'timer' | 'pool' | '' for a constructor call."""
    chain = attr_chain(node.func)
    tail = chain.split(".")[-1] if chain else (
        node.func.id if isinstance(node.func, ast.Name) else ""
    )
    head_ok = chain in ("", tail) or chain.startswith(
        ("threading.", "futures.", "concurrent.futures.")
    )
    if not head_ok:
        return ""
    if tail == "Thread":
        return "thread"
    if tail == "Timer":
        return "timer"
    if tail == "ThreadPoolExecutor":
        return "pool"
    return ""


def _has_daemon_true(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return kw.value.value is True
    return False


def _scope_has(scope: ast.AST, attrs: tuple, daemon_set: bool) -> bool:
    """A ``.{attr}(...)`` call (excluding string/os.path joins), or — when
    ``daemon_set`` — a ``<t>.daemon = True`` assignment, in ``scope``."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in attrs:
                recv = node.func.value
                # Exclude the two common non-thread joins: "sep".join(...)
                # and os.path.join(...).
                if isinstance(recv, ast.Constant):
                    continue
                if attr_chain(recv).endswith("path"):
                    continue
                return True
        if daemon_set and isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon":
                    return True
    return False


class ThreadHygienePass(LintPass):
    name = "thread-hygiene"
    description = (
        "threading.Thread/Timer must be daemonized, joined (or cancelled) "
        "in scope; a ThreadPoolExecutor must be owned or shut down"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        self._check_scope(src, src.tree, findings)
        return findings

    def _check_scope(self, src, scope, findings) -> None:
        # Per lexical scope: collect this scope's ctors (not those of
        # nested functions), then recurse into nested functions.
        nested = []
        ctors: List[tuple] = []  # (kind, node)
        owned_pools: set = set()  # pool ctor nodes accounted structurally
        stack = list(
            scope.body if isinstance(scope.body, list) else [scope.body]
        )
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.append(node)
                continue
            if isinstance(node, ast.Call):
                kind = _ctor_kind(node)
                if kind:
                    ctors.append((kind, node))
                # A pool handed DIRECTLY to another call is owned by the
                # receiver (grpc.server(ThreadPoolExecutor(...))).
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Call) and _ctor_kind(arg) == "pool":
                        owned_pools.add(id(arg))
            if isinstance(node, ast.Assign):
                # Assigned to self.<attr> (anywhere in the value subtree —
                # conditional construction like ``X() if par else None``
                # included): the owner manages shutdown.
                if any(
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    for t in node.targets
                ):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Call) and _ctor_kind(sub) == "pool":
                            owned_pools.add(id(sub))
            if isinstance(node, ast.With):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Call) and _ctor_kind(ctx) == "pool":
                        owned_pools.add(id(ctx))
            stack.extend(ast.iter_child_nodes(node))

        threads = [
            c for k, c in ctors if k == "thread" and not _has_daemon_true(c)
        ]
        if threads and not _scope_has(scope, ("join",), daemon_set=True):
            for c in threads:
                findings.append(Finding(
                    self.name, src.path, c.lineno,
                    "thread is neither daemonized (daemon=True) nor joined "
                    "in this scope — a leaked non-daemon thread blocks "
                    "process exit",
                ))
        timers = [c for k, c in ctors if k == "timer"]
        if timers and not _scope_has(
            scope, ("join", "cancel"), daemon_set=True
        ):
            for c in timers:
                findings.append(Finding(
                    self.name, src.path, c.lineno,
                    "Timer is neither daemonized (<t>.daemon = True — the "
                    "ctor takes no daemon kwarg), joined, nor cancelled in "
                    "this scope — a pending non-daemon timer blocks "
                    "process exit",
                ))
        pools = [
            c for k, c in ctors if k == "pool" and id(c) not in owned_pools
        ]
        if pools and not _scope_has(scope, ("shutdown",), daemon_set=False):
            for c in pools:
                findings.append(Finding(
                    self.name, src.path, c.lineno,
                    "executor is neither owned (self.<attr> assignment, "
                    "passed to an owning call, with-block) nor shut down "
                    "in this scope — its non-daemon workers leak",
                ))
        for fn in nested:
            self._check_scope(src, fn, findings)
