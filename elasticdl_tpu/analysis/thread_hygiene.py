"""thread-hygiene: every thread is daemonized or provably joined.

A non-daemon thread that is never joined keeps the process alive after
main exits — in this codebase that turns a clean worker RESTART exit into
a hang the pod manager must SIGKILL out of (and a leaked prep/checkpoint
thread can pin device buffers).  The rule: every ``threading.Thread(...)``
constructor must either

- pass ``daemon=True`` at construction, or
- have a ``.join(...)`` call in the same lexical scope (function body, or
  module top level for module-level threads) — the bench-tool
  ``threads = [...]; for t in threads: t.start(); ... t.join()`` pattern,
  or a ``<t>.daemon = True`` assignment in that scope.

The join-proof is scope-local and name-blind (it accepts any ``x.join()``
in the scope that is not a string/``os.path`` join): a cross-function
hand-off (constructed here, joined elsewhere) is expressed with a waiver
naming the join site.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain


def _is_thread_ctor(node: ast.Call) -> bool:
    chain = attr_chain(node.func)
    return chain == "threading.Thread" or (
        isinstance(node.func, ast.Name) and node.func.id == "Thread"
    )


def _has_daemon_true(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return kw.value.value is True
    return False


def _scope_has_join_or_daemon_set(scope: ast.AST) -> bool:
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "join":
                recv = node.func.value
                # Exclude the two common non-thread joins: "sep".join(...)
                # and os.path.join(...).
                if isinstance(recv, ast.Constant):
                    continue
                if attr_chain(recv).endswith("path"):
                    continue
                return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon":
                    return True
    return False


class ThreadHygienePass(LintPass):
    name = "thread-hygiene"
    description = (
        "threading.Thread must be daemonized at construction or joined in "
        "the same scope"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        self._check_scope(src, src.tree, findings)
        return findings

    def _check_scope(self, src, scope, findings) -> None:
        # Per lexical scope: collect this scope's Thread ctors (not those
        # of nested functions), then recurse into nested functions.
        nested = []
        ctors: List[ast.Call] = []
        stack = list(
            scope.body if isinstance(scope.body, list) else [scope.body]
        )
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.append(node)
                continue
            if isinstance(node, ast.Call) and _is_thread_ctor(node):
                ctors.append(node)
            stack.extend(ast.iter_child_nodes(node))
        bad = [c for c in ctors if not _has_daemon_true(c)]
        if bad and not _scope_has_join_or_daemon_set(scope):
            for c in bad:
                findings.append(Finding(
                    self.name, src.path, c.lineno,
                    "thread is neither daemonized (daemon=True) nor joined "
                    "in this scope — a leaked non-daemon thread blocks "
                    "process exit",
                ))
        for fn in nested:
            self._check_scope(src, fn, findings)
