"""hot-path-sync: no silent blocking inside ``# hot-path`` functions.

The r5/r6 perf work moved every blocking operation of the worker task loop
(device syncs, metrics fetches, checkpoint writes, control RPCs) either off
the critical path or behind a named ``PhaseTimers`` boundary, so each
second of wall is attributable (docs/perf.md).  This pass keeps it that
way: a function whose ``def`` line (or the comment line above it) carries
``# hot-path`` may not, in its steady-state body, call

- ``<x>.block_until_ready()`` / ``jax.block_until_ready(...)`` — drains the
  dispatch pipeline;
- ``<x>.item()`` — a blocking device->host scalar read;
- ``jax.device_get(...)`` — blocking transfer;
- ``int(...)`` / ``float(...)`` / ``np.asarray(...)`` over an expression
  touching ``self.state`` — the classic accidental sync (``int(state.step)``
  costs a full pipeline drain; use the python-side mirror);
- ``time.sleep(...)``;
- ``<...>master.call(...)`` — a blocking control-plane RPC.

Designated boundaries are exempt, matching the runtime convention:

- statements inside ``with <...>.phases.phase("name"):`` (or any
  ``.phase(...)`` context) are *accounted* blocking — the boundary the
  invariant text refers to;
- ``except`` handler bodies (error paths are off the hot path; recovery is
  allowed to settle state);
- nested ``def``/``lambda`` bodies (deferred execution — background
  threads own their own time).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain

_CAST_CALLEES = {"int", "float"}
_ASARRAY_CHAINS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}


def _references_state(node: ast.AST) -> bool:
    """True when the expression touches ``self.state`` (device-backed train
    state) anywhere in its subtree."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and sub.attr == "state"
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "self"
        ):
            return True
        if isinstance(sub, ast.Name) and sub.id == "state":
            return True
    return False


def is_phase_context(ctx: ast.expr) -> bool:
    """``with self.phases.phase("x"):``-shaped context expression."""
    return (
        isinstance(ctx, ast.Call)
        and isinstance(ctx.func, ast.Attribute)
        and ctx.func.attr == "phase"
    )


_is_phase_context = is_phase_context  # r7 name, kept for callers


def blocking_reason(node: ast.Call) -> Optional[str]:
    """Why this call blocks the hot path, or None.  Shared detector: the
    per-function pass below flags these directly; the interprocedural
    blocking-propagation pass (analysis/blocking.py) uses the same
    predicate to decide which functions "may block" transitively."""
    f = node.func
    chain = attr_chain(f)
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready" or chain == "jax.block_until_ready":
            return "block_until_ready drains the dispatch pipeline"
        if f.attr == "item" and not node.args and not node.keywords:
            return ".item() is a blocking device->host scalar read"
        if chain == "jax.device_get":
            return "jax.device_get blocks on transfer"
        if chain == "time.sleep":
            return "time.sleep stalls the hot path"
        if f.attr in ("call", "call_async") and chain:
            recv = chain.rsplit(".", 1)[0].split(".")[-1]
            if recv == "master":
                return "blocking master RPC on the hot path"
        if chain in _ASARRAY_CHAINS and any(
            _references_state(a) for a in node.args
        ):
            return f"{chain} over self.state forces a device->host copy"
    elif isinstance(f, ast.Name) and f.id in _CAST_CALLEES:
        if any(_references_state(a) for a in node.args):
            return (
                f"{f.id}() over self.state is a blocking device read "
                "(use the python-side step mirror)"
            )
    return None


class HotPathSyncPass(LintPass):
    name = "hot-path-sync"
    description = (
        "functions marked '# hot-path' may not block (device syncs, "
        "sleeps, master RPCs) outside a phases.phase(...) boundary"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if src.is_hot_path(node.lineno):
                    self._walk(src, node.body, findings)
        return findings

    def _walk(self, src, body, findings) -> None:
        for node in body:
            self._visit(src, node, findings)

    def _visit(self, src, node, findings) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # deferred execution: not this function's hot path
        if isinstance(node, ast.With):
            if any(_is_phase_context(i.context_expr) for i in node.items):
                return  # accounted boundary: blocking here is by design
            self._walk(src, node.body, findings)
            return
        if isinstance(node, ast.Try):
            for stmt in node.body + node.orelse + node.finalbody:
                self._visit(src, stmt, findings)
            return  # handlers (error path) skipped
        if isinstance(node, ast.Call):
            self._check_call(src, node, findings)
        for child in ast.iter_child_nodes(node):
            self._visit(src, child, findings)

    def _check_call(self, src, node: ast.Call, findings) -> None:
        msg = blocking_reason(node)
        if msg is not None:
            findings.append(Finding(
                self.name, src.path, node.lineno,
                msg + " — move it behind a phases.phase(...) boundary, off "
                "the hot path, or waive with a reason",
            ))
