"""chaos-discipline: hot-path fault injection uses the no-op hook only.

``chaos/inject.py`` splits its surface the way ``common/trace.py`` does
(trace-discipline is the template):

- ``chaos.hook(point, **ctx)`` is the ONE hot-path-legal entry point —
  disabled (the default), it is a single attribute check and a return, so
  an unarmed production job pays nothing at the hook crossings;
- everything else — ``fire`` (the match/act machinery), ``configure`` /
  ``set_context`` (plan/context mutation under a lock), ``parse_plan``
  and ``ChaosInjector(...)`` construction — is setup/armed-mode API that
  belongs at process boundaries (worker __init__, membership apply, main
  entry points), never inside a ``# hot-path`` function's steady state.

A hot-path call site reaching past ``hook`` would make the INJECTION
FRAMEWORK a perturbation of its own even with no fault armed — the exact
failure mode the one-attribute-check design exists to rule out.  This
pass keeps the split enforced.

Traversal and exemption scope (handlers/nested defs exempt, no phase
excuse) are the shared ``HotPathCallDisciplinePass`` contract — one body
with ``trace-discipline``, so the family cannot drift.  The non-hook
names are matched on chaos-shaped receivers only (``chaos``/``inj``/
``injector``/``_INJ``), so an unrelated object's ``configure()`` is never
punished; ``ChaosInjector`` construction is matched by name anywhere.
"""

from __future__ import annotations

import ast

from elasticdl_tpu.analysis.core import (
    HotPathCallDisciplinePass,
    receiver_hinted,
)

#: Non-hook chaos API: flagged in a hot-path body when the receiver looks
#: like the chaos module/injector.
_SETUP_ATTRS = {"fire", "configure", "set_context", "parse_plan", "stats"}

_CHAOS_RECEIVER_HINTS = ("chaos", "inj", "injector", "_INJ")


def _is_chaos_setup_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        # Direct construction inside a hot path: the injector is a
        # process-global built once, never per-call.
        return f.id == "ChaosInjector"
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr not in _SETUP_ATTRS:
        return False
    return receiver_hinted(f, _CHAOS_RECEIVER_HINTS)


class ChaosDisciplinePass(HotPathCallDisciplinePass):
    name = "chaos-discipline"
    description = (
        "functions marked '# hot-path' may cross fault-injection points "
        "only through the no-op-when-disabled chaos.hook API; plan/"
        "context mutation and direct injector use (fire/configure/"
        "set_context/parse_plan/ChaosInjector) are findings"
    )
    message = (
        "chaos setup/injector API inside a '# hot-path' function — "
        "hot-path call sites use the no-op-when-disabled "
        "chaos.hook(...) only; arm plans at process boundaries, "
        "or waive with a reason"
    )

    def is_flagged_call(self, node: ast.Call) -> bool:
        return _is_chaos_setup_call(node)
