"""chaos-discipline: hot-path fault injection uses the no-op hook only.

``chaos/inject.py`` splits its surface the way ``common/trace.py`` does
(trace-discipline is the template):

- ``chaos.hook(point, **ctx)`` is the ONE hot-path-legal entry point —
  disabled (the default), it is a single attribute check and a return, so
  an unarmed production job pays nothing at the hook crossings;
- everything else — ``fire`` (the match/act machinery), ``configure`` /
  ``set_context`` (plan/context mutation under a lock), ``parse_plan``
  and ``ChaosInjector(...)`` construction — is setup/armed-mode API that
  belongs at process boundaries (worker __init__, membership apply, main
  entry points), never inside a ``# hot-path`` function's steady state.

A hot-path call site reaching past ``hook`` would make the INJECTION
FRAMEWORK a perturbation of its own even with no fault armed — the exact
failure mode the one-attribute-check design exists to rule out.  This
pass keeps the split enforced.

Scope notes, mirroring ``trace-discipline``:

- ``except`` handler bodies and nested ``def``/``lambda`` bodies are
  exempt (error paths and deferred execution own their own time);
- the non-hook names are matched on chaos-shaped receivers only
  (``chaos``/``inj``/``injector``/``_INJ``), so an unrelated object's
  ``configure()`` is never punished; ``ChaosInjector`` construction is
  matched by name anywhere.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain

#: Non-hook chaos API: flagged in a hot-path body when the receiver looks
#: like the chaos module/injector.
_SETUP_ATTRS = {"fire", "configure", "set_context", "parse_plan", "stats"}

_CHAOS_RECEIVER_HINTS = ("chaos", "inj", "injector", "_INJ")


def _is_chaos_setup_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        # Direct construction inside a hot path: the injector is a
        # process-global built once, never per-call.
        return f.id == "ChaosInjector"
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr not in _SETUP_ATTRS:
        return False
    chain = attr_chain(f)
    if chain:
        recv = chain.rsplit(".", 1)[0].split(".")[-1]
        return recv in _CHAOS_RECEIVER_HINTS
    # Dynamic receiver (``chaos.default().fire(...)``): the inner call's
    # own chain carries the hint.
    inner = f.value
    if isinstance(inner, ast.Call):
        ichain = attr_chain(inner.func)
        return any(
            part in _CHAOS_RECEIVER_HINTS for part in ichain.split(".")
        )
    return False


class ChaosDisciplinePass(LintPass):
    name = "chaos-discipline"
    description = (
        "functions marked '# hot-path' may cross fault-injection points "
        "only through the no-op-when-disabled chaos.hook API; plan/"
        "context mutation and direct injector use (fire/configure/"
        "set_context/parse_plan/ChaosInjector) are findings"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if src.is_hot_path(node.lineno):
                    self._walk(src, node.body, findings)
        return findings

    def _walk(self, src, body, findings) -> None:
        for node in body:
            self._visit(src, node, findings)

    def _visit(self, src, node, findings) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # deferred execution: not this function's hot path
        if isinstance(node, ast.Try):
            for stmt in node.body + node.orelse + node.finalbody:
                self._visit(src, stmt, findings)
            return  # handlers (error path) skipped
        if isinstance(node, ast.Call) and _is_chaos_setup_call(node):
            findings.append(Finding(
                self.name, src.path, node.lineno,
                "chaos setup/injector API inside a '# hot-path' function — "
                "hot-path call sites use the no-op-when-disabled "
                "chaos.hook(...) only; arm plans at process boundaries, "
                "or waive with a reason",
            ))
        for child in ast.iter_child_nodes(node):
            self._visit(src, child, findings)
