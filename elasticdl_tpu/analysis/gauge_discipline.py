"""gauge-discipline: hot-path metric updates use the O(1) ring/counter
API only.

``common/gauge.py`` splits its surface the way ``common/trace.py`` does
(trace-discipline is the template):

- ``inc``/``set``/``add``/``observe`` on a metric handle are O(1)
  leaf-lock updates — legal anywhere, including ``# hot-path``
  functions; registration (``counter``/``gauge``/``histogram``) is a
  dict lookup and also fine;
- everything scrape-side — ``snapshot``/``render_prometheus``/
  ``scalar_values`` (walk every family and run the registered
  collectors), ``render_families``/``merge_snapshots``/
  ``fleet_snapshot`` (the master's aggregation math) — belongs on
  control-plane boundaries (heartbeats, checkpoint reports, the scrape
  server's render callable), never inside a ``# hot-path`` function's
  steady state.

A scrape call inside a hot path would make MEASURING the thing that
stalls the measured loop — the exact failure mode the one-attribute-
check-when-disabled design exists to rule out.  This pass keeps the
split enforced.

Traversal and exemption scope (handlers/nested defs exempt, no phase
excuse) are the shared ``HotPathCallDisciplinePass`` contract — one body
with ``trace-discipline``/``chaos-discipline``, so the family cannot
drift.  The distinctive names (``render_prometheus``, ``render_families``,
``merge_snapshots``, ``fleet_snapshot``, ``scalar_values``) flag on any
receiver; ``snapshot`` is a common verb (``PhaseTimers.snapshot``,
``Trainer.snapshot_state`` are unrelated and hot-path-adjacent), so it is
matched only on gauge-shaped receivers.
"""

from __future__ import annotations

import ast

from elasticdl_tpu.analysis.core import (
    HotPathCallDisciplinePass,
    receiver_hinted,
)

#: Scrape/aggregation attribute names that always flag in a hot-path body.
_SCRAPE_ATTRS = {
    "render_prometheus",
    "render_families",
    "merge_snapshots",
    "fleet_snapshot",
    "scalar_values",
}

#: ``snapshot`` flags only when the receiver chain looks like a metrics
#: registry (``self.gauges.snapshot()``, ``registry.snapshot()``) — an
#: unrelated object's snapshot() is never punished.
_GAUGE_RECEIVER_HINTS = ("gauge", "gauges", "registry", "reg", "fleet")


def _is_scrape_call(node: ast.Call) -> bool:
    f = node.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr in _SCRAPE_ATTRS:
        return True
    if f.attr == "snapshot":
        return receiver_hinted(f, _GAUGE_RECEIVER_HINTS)
    return False


class GaugeDisciplinePass(HotPathCallDisciplinePass):
    name = "gauge-discipline"
    description = (
        "functions marked '# hot-path' may update metrics only through "
        "the O(1) counter/gauge/histogram API (inc/set/add/observe); "
        "scrape/aggregation calls (snapshot/render_prometheus/"
        "render_families/merge_snapshots/fleet_snapshot/scalar_values) "
        "are findings"
    )
    message = (
        "gauge scrape/aggregation inside a '# hot-path' function — "
        "serve snapshots from a control-plane boundary (heartbeat/"
        "report/scrape server) instead, or waive with a reason"
    )

    def is_flagged_call(self, node: ast.Call) -> bool:
        return _is_scrape_call(node)
